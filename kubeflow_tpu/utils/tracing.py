"""Tracing for the admission webhook (and anything else that wants spans).

The reference instruments its mutating webhook with OpenTelemetry: a lazy
tracer (sync.OnceValue, odh notebook_mutating_webhook.go:74-76), one root span
per admission with notebook/namespace/operation attributes (:366-373), a child
span inside maybeRestartRunningNotebook (:526), and span events for
ImageStream lookup misses (:912,928,961). Production default is the global
no-op provider; the test suite installs a real SDK provider with an in-memory
exporter (opentelemetry_test.go:26-78).

This module reproduces that shape with the stdlib only (the image carries no
opentelemetry SDK): an OTel-like API — ``get_tracer(name).start_span(...)`` as
a context manager, attributes, events, status — over a pluggable provider.
The default provider is a no-op (zero overhead on the admission hot path);
``set_provider(SDKProvider(exporter))`` installs a recording one.
"""

from __future__ import annotations

import re
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Iterator

from . import sanitizer

# ------------------------------------------------------------------ data model

STATUS_UNSET = "UNSET"
STATUS_OK = "OK"
STATUS_ERROR = "ERROR"

# Span attribute that binds a trace to a notebook for the flight recorder
# (set on reconcile root spans by the manager).
KEY_ATTRIBUTE = "reconcile.key"


@dataclass(frozen=True)
class SpanContext:
    """The propagatable identity of a span — what crosses process/controller
    boundaries (OTel's SpanContext). Carried on the wire as a W3C
    ``traceparent`` header and between controllers as an object annotation."""

    trace_id: int
    span_id: int


# W3C trace-context: version "00", 16-byte trace-id, 8-byte parent-id,
# 1-byte flags, all lowercase hex. All-zero ids are invalid per spec.
_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def format_traceparent(ctx: SpanContext) -> str:
    return f"00-{ctx.trace_id:032x}-{ctx.span_id:016x}-01"


def parse_traceparent(header: str | None) -> SpanContext | None:
    """Strict W3C traceparent parse; malformed headers yield None (the
    propagation spec says restart the trace, never fail the request)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip())
    if m is None:
        return None
    trace_id = int(m.group(1), 16)
    span_id = int(m.group(2), 16)
    if trace_id == 0 or span_id == 0:
        return None
    return SpanContext(trace_id=trace_id, span_id=span_id)


@dataclass
class SpanEvent:
    name: str
    attributes: dict[str, object]
    timestamp: float


@dataclass
class Span:
    name: str
    tracer: str
    trace_id: int
    span_id: int
    parent_id: int | None
    attributes: dict[str, object] = field(default_factory=dict)
    events: list[SpanEvent] = field(default_factory=list)
    status: str = STATUS_UNSET
    status_description: str = ""
    start_time: float = 0.0
    end_time: float = 0.0

    def set_attribute(self, key: str, value: object) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, attributes: dict | None = None) -> None:
        self.events.append(SpanEvent(name, dict(attributes or {}),
                                     time.time()))

    def set_status(self, status: str, description: str = "") -> None:
        self.status = status
        self.status_description = description

    def record_exception(self, exc: BaseException) -> None:
        self.add_event("exception", {
            "exception.type": type(exc).__name__,
            "exception.message": str(exc),
        })
        self.set_status(STATUS_ERROR, str(exc))

    def context(self) -> SpanContext:
        return SpanContext(trace_id=self.trace_id, span_id=self.span_id)


class _NoopSpan:
    """Attribute/event sink with no recording — the global default provider,
    like OTel's no-op TracerProvider."""

    def set_attribute(self, key: str, value: object) -> None: ...

    def add_event(self, name: str, attributes: dict | None = None) -> None: ...

    def set_status(self, status: str, description: str = "") -> None: ...

    def record_exception(self, exc: BaseException) -> None: ...

    def context(self) -> None:
        return None


_NOOP_SPAN = _NoopSpan()


class _NoopSpanCM:
    """Reusable no-op context manager: ``NoopProvider.span`` hands out ONE
    shared instance, so the tracing-off hot path allocates nothing per call
    (a @contextmanager would build a fresh generator each time)."""

    __slots__ = ()

    def __enter__(self) -> _NoopSpan:
        return _NOOP_SPAN

    def __exit__(self, *exc) -> bool:
        return False


_NOOP_SPAN_CM = _NoopSpanCM()


# ------------------------------------------------------------------- providers

class InMemorySpanExporter:
    """Test-side exporter mirroring tracetest.NewInMemoryExporter
    (opentelemetry_test.go:26-78)."""

    def __init__(self) -> None:
        self._lock = sanitizer.tracked_lock(
            "tracing.exporter", order=sanitizer.ORDER_LEAF)
        self._spans: list[Span] = []

    def export(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    @property
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def by_name(self, name: str) -> list[Span]:
        return [s for s in self.spans if s.name == name]

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()


class OtlpHttpExporter:
    """Production exporter: OTLP/HTTP JSON to a collector endpoint, stdlib
    only (the image carries no opentelemetry SDK). The reference's webhook
    emits real OTel spans a collector can receive (odh
    notebook_mutating_webhook.go:74-76); this is that wire format —
    POST ``{endpoint}/v1/traces`` with an ExportTraceServiceRequest JSON
    body (resourceSpans → scopeSpans → spans, ids as hex, times in unix
    nanos).

    Spans buffer and a daemon thread flushes them in batches (size- or
    interval-triggered) so the admission hot path never blocks on the
    collector; a dead collector drops batches with one rate-limited
    stderr note, never an exception into the webhook."""

    def __init__(self, endpoint: str, service_name: str = "kubeflow-tpu",
                 timeout_s: float = 5.0, batch_size: int = 64,
                 flush_interval_s: float = 2.0) -> None:
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self.timeout_s = timeout_s
        self.batch_size = batch_size
        self.flush_interval_s = flush_interval_s
        self._buf: list[Span] = []
        self._lock = sanitizer.tracked_lock(
            "tracing.exporter", order=sanitizer.ORDER_LEAF)
        self._wake = threading.Event()
        self._closed = False
        self._last_error_t = 0.0
        self.exported_total = 0
        self.failed_total = 0
        self._thread = threading.Thread(target=self._flusher, daemon=True,
                                        name="kubeflow-tpu-otlp")
        self._thread.start()

    # ------------------------------------------------------------- export
    def export(self, span: Span) -> None:
        with self._lock:
            if self._closed:
                return
            self._buf.append(span)
            full = len(self._buf) >= self.batch_size
        if full:
            self._wake.set()

    def force_flush(self) -> None:
        self._flush()

    def shutdown(self) -> None:
        with self._lock:
            self._closed = True
        self._wake.set()
        # the flusher may be mid-POST (up to timeout_s) AND still owe the
        # final flush (another timeout_s) — give it both before bailing
        self._thread.join(timeout=2 * self.timeout_s + 1)
        self._flush()

    def _flusher(self) -> None:
        while True:  # pump: flusher; returns after observing _closed
            self._wake.wait(self.flush_interval_s)
            self._wake.clear()
            with self._lock:
                closed = self._closed
            self._flush()
            if closed:
                return

    def _flush(self) -> None:
        with self._lock:
            batch, self._buf = self._buf, []
        if not batch:
            return
        import json
        import urllib.request
        body = json.dumps(self._encode(batch)).encode()
        req = urllib.request.Request(
            self.endpoint + "/v1/traces", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
            self.exported_total += len(batch)
        except Exception as e:  # noqa: BLE001 — telemetry must never raise
            self.failed_total += len(batch)
            now = time.time()
            if now - self._last_error_t > 30:
                self._last_error_t = now
                import sys
                sys.stderr.write(
                    f"otlp: export of {len(batch)} spans to "
                    f"{self.endpoint} failed: {e}\n")

    # ------------------------------------------------------------- encode
    @staticmethod
    def _attr_value(value: object) -> dict:
        if isinstance(value, bool):
            return {"boolValue": value}
        if isinstance(value, int):
            return {"intValue": str(value)}
        if isinstance(value, float):
            return {"doubleValue": value}
        return {"stringValue": str(value)}

    @classmethod
    def _attrs(cls, attributes: dict) -> list[dict]:
        return [{"key": k, "value": cls._attr_value(v)}
                for k, v in attributes.items()]

    def _encode(self, batch: list[Span]) -> dict:
        by_tracer: dict[str, list[Span]] = {}
        for span in batch:
            by_tracer.setdefault(span.tracer, []).append(span)
        status_code = {STATUS_UNSET: 0, STATUS_OK: 1, STATUS_ERROR: 2}
        scope_spans = []
        for tracer, spans in by_tracer.items():
            scope_spans.append({
                "scope": {"name": tracer},
                "spans": [{
                    "traceId": f"{span.trace_id:032x}",
                    "spanId": f"{span.span_id:016x}",
                    **({"parentSpanId": f"{span.parent_id:016x}"}
                       if span.parent_id is not None else {}),
                    "name": span.name,
                    "kind": 1,  # SPAN_KIND_INTERNAL
                    "startTimeUnixNano": str(int(span.start_time * 1e9)),
                    "endTimeUnixNano": str(int(span.end_time * 1e9)),
                    "attributes": self._attrs(span.attributes),
                    "events": [{
                        "timeUnixNano": str(int(ev.timestamp * 1e9)),
                        "name": ev.name,
                        "attributes": self._attrs(ev.attributes),
                    } for ev in span.events],
                    "status": {
                        "code": status_code.get(span.status, 0),
                        **({"message": span.status_description}
                           if span.status_description else {}),
                    },
                } for span in spans],
            })
        return {"resourceSpans": [{
            "resource": {"attributes": self._attrs(
                {"service.name": self.service_name})},
            "scopeSpans": scope_spans,
        }]}


class NoopProvider:
    recording = False

    def span(self, tracer: str, name: str, attributes: dict | None = None,
             parent: SpanContext | None = None) -> _NoopSpanCM:
        return _NOOP_SPAN_CM

    def emit(self, tracer: str, name: str, start_time: float, end_time: float,
             attributes: dict | None = None,
             parent: SpanContext | None = None) -> _NoopSpan:
        return _NOOP_SPAN


class SDKProvider:
    """Recording provider: spans export on end, parentage via a context stack
    (thread-local, like OTel context propagation). ``exporter`` is anything
    with ``export(span)`` — the in-memory test exporter or the production
    OTLP/HTTP one."""

    recording = True

    def __init__(self, exporter) -> None:
        # duck-typed exporter: InMemorySpanExporter, OtlpHttpExporter, or
        # a FlightRecorder (optionally teeing to one of the former)
        self.exporter = exporter
        self._local = threading.local()
        self._lock = sanitizer.tracked_lock(
            "tracing.ids", order=sanitizer.ORDER_LEAF)
        self._next_id = 1

    def _ids(self) -> int:
        with self._lock:
            i = self._next_id
            self._next_id += 1
            return i

    @contextmanager
    def span(self, tracer: str, name: str, attributes: dict | None = None,
             parent: SpanContext | None = None) -> Iterator[Span]:
        stack: list[Span] = getattr(self._local, "stack", None) or []
        self._local.stack = stack
        if parent is None:
            top = stack[-1] if stack else None
            parent = top.context() if top is not None else None
        # An explicit parent (a remote SpanContext from a traceparent header
        # or an annotation) wins over the thread stack — that's the stitch:
        # a span opened mid-reconcile can join ANOTHER object's trace, and
        # its children still nest under it via the stack.
        span = Span(name=name, tracer=tracer,
                    trace_id=parent.trace_id if parent else self._ids(),
                    span_id=self._ids(),
                    parent_id=parent.span_id if parent else None,
                    attributes=dict(attributes or {}),
                    start_time=time.time())
        stack.append(span)
        try:
            yield span
        except BaseException as exc:
            span.record_exception(exc)
            raise
        finally:
            span.end_time = time.time()
            stack.pop()
            self.exporter.export(span)

    def emit(self, tracer: str, name: str, start_time: float, end_time: float,
             attributes: dict | None = None,
             parent: SpanContext | None = None) -> Span:
        """Export an already-finished span with explicit timestamps — for
        phases measured before a span could be opened (workqueue wait,
        phase-collector read/write totals). Parent defaults to the current
        thread's innermost span."""
        if parent is None:
            stack = getattr(self._local, "stack", None)
            parent = stack[-1].context() if stack else None
        span = Span(name=name, tracer=tracer,
                    trace_id=parent.trace_id if parent else self._ids(),
                    span_id=self._ids(),
                    parent_id=parent.span_id if parent else None,
                    attributes=dict(attributes or {}),
                    start_time=start_time, end_time=end_time)
        self.exporter.export(span)
        return span


_provider: NoopProvider | SDKProvider = NoopProvider()
_provider_lock = sanitizer.tracked_lock(
    "tracing.provider", order=sanitizer.ORDER_LEAF)


def set_provider(provider: NoopProvider | SDKProvider) -> None:
    global _provider
    with _provider_lock:
        _provider = provider


def get_provider() -> NoopProvider | SDKProvider:
    return _provider


def is_recording() -> bool:
    """True when the installed provider records spans. Instrumentation sites
    guard attribute-dict construction and carrier writes on this so the
    no-op path stays allocation-free."""
    return _provider.recording


def current_span():
    """The innermost active recording span on this thread (OTel's
    trace.SpanFromContext) — a no-op sink when the provider isn't recording
    or no span is open, so callers can add events unconditionally."""
    provider = _provider
    if isinstance(provider, SDKProvider):
        stack = getattr(provider._local, "stack", None)
        if stack:
            return stack[-1]
    return _NOOP_SPAN


def current_context() -> SpanContext | None:
    """SpanContext of the innermost active span, or None when not recording
    — the value a carrier (traceparent header, annotation) should serialize."""
    provider = _provider
    if isinstance(provider, SDKProvider):
        stack = getattr(provider._local, "stack", None)
        if stack:
            return stack[-1].context()
    return None


def current_exemplar() -> dict[str, str] | None:
    """Exemplar labels for the active trace (``{"trace_id": ..., "span_id":
    ...}``) or None when not recording — what histogram ``observe(...,
    exemplar=)`` wants."""
    ctx = current_context()
    if ctx is None:
        return None
    return {"trace_id": f"{ctx.trace_id:032x}",
            "span_id": f"{ctx.span_id:016x}"}


class Tracer:
    """Named tracer handle — cheap, safe to cache (the reference memoizes via
    sync.OnceValue; here the provider lookup is deferred to span start so a
    provider installed later is picked up, same observable behavior)."""

    def __init__(self, name: str) -> None:
        self.name = name

    def start_span(self, name: str, attributes: dict | None = None,
                   parent: SpanContext | None = None):
        return _provider.span(self.name, name, attributes, parent=parent)

    def emit_span(self, name: str, start_time: float, end_time: float,
                  attributes: dict | None = None,
                  parent: SpanContext | None = None):
        return _provider.emit(self.name, name, start_time, end_time,
                              attributes, parent=parent)


def get_tracer(name: str) -> Tracer:
    return Tracer(name)


# ------------------------------------------------------------ flight recorder

def _span_dict(span: Span) -> dict:
    return {
        "name": span.name,
        "tracer": span.tracer,
        "trace_id": f"{span.trace_id:032x}",
        "span_id": f"{span.span_id:016x}",
        "parent_id": (f"{span.parent_id:016x}"
                      if span.parent_id is not None else None),
        "start": span.start_time,
        "end": span.end_time,
        "duration_s": max(span.end_time - span.start_time, 0.0),
        "status": span.status,
        "attributes": dict(span.attributes),
        "events": [{"name": ev.name, "ts": ev.timestamp,
                    "attributes": dict(ev.attributes)}
                   for ev in span.events],
    }


def trace_phase_breakdown(spans: list[dict]) -> dict[str, float]:
    """Wall-clock decomposition of one trace (span dicts as produced by
    ``_span_dict``): ``queue`` is workqueue enqueue-delivery plus queue
    wait, ``wire`` is client-side REST time, ``apf`` is the server-side
    priority-and-fairness wait (a SUBSET of wire — reported for insight,
    excluded from the sum), and ``reconcile`` is the remaining root wall.
    ``queue + wire + reconcile == wall`` by construction (one worker thread
    runs the reconcile serially, so the child spans don't overlap)."""
    if not spans:
        return {"wall": 0.0, "queue": 0.0, "apf": 0.0, "wire": 0.0,
                "reconcile": 0.0}
    start = min(s["start"] for s in spans)
    end = max(s["end"] for s in spans)
    wall = max(end - start, 0.0)
    queue = sum(s["duration_s"] for s in spans
                if s["name"].startswith("workqueue."))
    apf = sum(s["duration_s"] for s in spans
              if s["name"].startswith("apf."))
    wire = sum(s["duration_s"] for s in spans
               if s["name"].startswith("rest."))
    reconcile = max(wall - queue - wire, 0.0)
    return {"wall": wall, "queue": queue, "apf": apf, "wire": wire,
            "reconcile": reconcile}


class FlightRecorder:
    """Bounded in-process trace store: the last K lifecycle traces per
    notebook, served by ``/debug/notebooks/<ns>/<name>/trace``.

    Works as an exporter decorator — install as (or in front of) the
    SDKProvider exporter. Spans group by trace_id; a trace binds to a
    notebook key the first time one of its spans carries ``reconcile.key``
    (set on reconcile root spans). Children export before their root, so
    unbound traces park in an LRU-bounded buffer until the keyed root
    arrives; both the per-key ring and the buffer are hard-bounded, so a
    recorder left on forever stays O(keys·K) memory."""

    def __init__(self, inner=None, max_traces: int = 512,
                 traces_per_key: int = 8,
                 max_spans_per_trace: int = 256) -> None:
        self.inner = inner
        self.max_traces = max_traces
        self.traces_per_key = traces_per_key
        self.max_spans_per_trace = max_spans_per_trace
        self._lock = sanitizer.tracked_lock(
            "tracing.recorder", order=sanitizer.ORDER_LEAF)
        self._traces: OrderedDict[int, list[Span]] = OrderedDict()
        self._trace_key: dict[int, str] = {}
        self._by_key: dict[str, list[int]] = {}

    def export(self, span: Span) -> None:
        with self._lock:
            spans = self._traces.get(span.trace_id)
            if spans is None:
                spans = []
                self._traces[span.trace_id] = spans
                while len(self._traces) > self.max_traces:
                    self._evict_oldest_locked()
            else:
                self._traces.move_to_end(span.trace_id)
            if len(spans) < self.max_spans_per_trace:
                spans.append(span)
            key = span.attributes.get(KEY_ATTRIBUTE)
            if key is not None and span.trace_id not in self._trace_key:
                self._bind_locked(span.trace_id, str(key))
        if self.inner is not None:
            self.inner.export(span)

    def _bind_locked(self, trace_id: int, key: str) -> None:
        self._trace_key[trace_id] = key
        ring = self._by_key.setdefault(key, [])
        ring.append(trace_id)
        while len(ring) > self.traces_per_key:
            old = ring.pop(0)
            self._trace_key.pop(old, None)
            self._traces.pop(old, None)

    def _evict_oldest_locked(self) -> None:
        old, _ = self._traces.popitem(last=False)
        key = self._trace_key.pop(old, None)
        if key is not None:
            ring = self._by_key.get(key)
            if ring and old in ring:
                ring.remove(old)
                if not ring:
                    del self._by_key[key]

    def trace_for(self, namespace: str, name: str) -> list[dict]:
        """All recorded traces bound to ``namespace/name``, oldest first,
        each as ``{"trace_id": hex, "spans": [span dicts sorted by start]}``
        — the JSON body of the debug endpoint."""
        key = f"{namespace}/{name}"
        with self._lock:
            ring = list(self._by_key.get(key, ()))
            out = []
            for trace_id in ring:
                spans = self._traces.get(trace_id)
                if not spans:
                    continue
                out.append({
                    "trace_id": f"{trace_id:032x}",
                    "spans": [_span_dict(s) for s in
                              sorted(spans, key=lambda s: s.start_time)],
                })
        return out

    def keys(self) -> list[str]:
        with self._lock:
            return sorted(self._by_key)
