"""Tracing for the admission webhook (and anything else that wants spans).

The reference instruments its mutating webhook with OpenTelemetry: a lazy
tracer (sync.OnceValue, odh notebook_mutating_webhook.go:74-76), one root span
per admission with notebook/namespace/operation attributes (:366-373), a child
span inside maybeRestartRunningNotebook (:526), and span events for
ImageStream lookup misses (:912,928,961). Production default is the global
no-op provider; the test suite installs a real SDK provider with an in-memory
exporter (opentelemetry_test.go:26-78).

This module reproduces that shape with the stdlib only (the image carries no
opentelemetry SDK): an OTel-like API — ``get_tracer(name).start_span(...)`` as
a context manager, attributes, events, status — over a pluggable provider.
The default provider is a no-op (zero overhead on the admission hot path);
``set_provider(SDKProvider(exporter))`` installs a recording one.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Iterator

# ------------------------------------------------------------------ data model

STATUS_UNSET = "UNSET"
STATUS_OK = "OK"
STATUS_ERROR = "ERROR"


@dataclass
class SpanEvent:
    name: str
    attributes: dict[str, object]
    timestamp: float


@dataclass
class Span:
    name: str
    tracer: str
    trace_id: int
    span_id: int
    parent_id: int | None
    attributes: dict[str, object] = field(default_factory=dict)
    events: list[SpanEvent] = field(default_factory=list)
    status: str = STATUS_UNSET
    status_description: str = ""
    start_time: float = 0.0
    end_time: float = 0.0

    def set_attribute(self, key: str, value: object) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, attributes: dict | None = None) -> None:
        self.events.append(SpanEvent(name, dict(attributes or {}),
                                     time.time()))

    def set_status(self, status: str, description: str = "") -> None:
        self.status = status
        self.status_description = description

    def record_exception(self, exc: BaseException) -> None:
        self.add_event("exception", {
            "exception.type": type(exc).__name__,
            "exception.message": str(exc),
        })
        self.set_status(STATUS_ERROR, str(exc))


class _NoopSpan:
    """Attribute/event sink with no recording — the global default provider,
    like OTel's no-op TracerProvider."""

    def set_attribute(self, key: str, value: object) -> None: ...

    def add_event(self, name: str, attributes: dict | None = None) -> None: ...

    def set_status(self, status: str, description: str = "") -> None: ...

    def record_exception(self, exc: BaseException) -> None: ...


_NOOP_SPAN = _NoopSpan()


# ------------------------------------------------------------------- providers

class InMemorySpanExporter:
    """Test-side exporter mirroring tracetest.NewInMemoryExporter
    (opentelemetry_test.go:26-78)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._spans: list[Span] = []

    def export(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    @property
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def by_name(self, name: str) -> list[Span]:
        return [s for s in self.spans if s.name == name]

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()


class OtlpHttpExporter:
    """Production exporter: OTLP/HTTP JSON to a collector endpoint, stdlib
    only (the image carries no opentelemetry SDK). The reference's webhook
    emits real OTel spans a collector can receive (odh
    notebook_mutating_webhook.go:74-76); this is that wire format —
    POST ``{endpoint}/v1/traces`` with an ExportTraceServiceRequest JSON
    body (resourceSpans → scopeSpans → spans, ids as hex, times in unix
    nanos).

    Spans buffer and a daemon thread flushes them in batches (size- or
    interval-triggered) so the admission hot path never blocks on the
    collector; a dead collector drops batches with one rate-limited
    stderr note, never an exception into the webhook."""

    def __init__(self, endpoint: str, service_name: str = "kubeflow-tpu",
                 timeout_s: float = 5.0, batch_size: int = 64,
                 flush_interval_s: float = 2.0) -> None:
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self.timeout_s = timeout_s
        self.batch_size = batch_size
        self.flush_interval_s = flush_interval_s
        self._buf: list[Span] = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._closed = False
        self._last_error_t = 0.0
        self.exported_total = 0
        self.failed_total = 0
        self._thread = threading.Thread(target=self._flusher, daemon=True,
                                        name="kubeflow-tpu-otlp")
        self._thread.start()

    # ------------------------------------------------------------- export
    def export(self, span: Span) -> None:
        with self._lock:
            if self._closed:
                return
            self._buf.append(span)
            full = len(self._buf) >= self.batch_size
        if full:
            self._wake.set()

    def force_flush(self) -> None:
        self._flush()

    def shutdown(self) -> None:
        with self._lock:
            self._closed = True
        self._wake.set()
        # the flusher may be mid-POST (up to timeout_s) AND still owe the
        # final flush (another timeout_s) — give it both before bailing
        self._thread.join(timeout=2 * self.timeout_s + 1)
        self._flush()

    def _flusher(self) -> None:
        while True:
            self._wake.wait(self.flush_interval_s)
            self._wake.clear()
            with self._lock:
                closed = self._closed
            self._flush()
            if closed:
                return

    def _flush(self) -> None:
        with self._lock:
            batch, self._buf = self._buf, []
        if not batch:
            return
        import json
        import urllib.request
        body = json.dumps(self._encode(batch)).encode()
        req = urllib.request.Request(
            self.endpoint + "/v1/traces", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
            self.exported_total += len(batch)
        except Exception as e:  # noqa: BLE001 — telemetry must never raise
            self.failed_total += len(batch)
            now = time.time()
            if now - self._last_error_t > 30:
                self._last_error_t = now
                import sys
                sys.stderr.write(
                    f"otlp: export of {len(batch)} spans to "
                    f"{self.endpoint} failed: {e}\n")

    # ------------------------------------------------------------- encode
    @staticmethod
    def _attr_value(value: object) -> dict:
        if isinstance(value, bool):
            return {"boolValue": value}
        if isinstance(value, int):
            return {"intValue": str(value)}
        if isinstance(value, float):
            return {"doubleValue": value}
        return {"stringValue": str(value)}

    @classmethod
    def _attrs(cls, attributes: dict) -> list[dict]:
        return [{"key": k, "value": cls._attr_value(v)}
                for k, v in attributes.items()]

    def _encode(self, batch: list[Span]) -> dict:
        by_tracer: dict[str, list[Span]] = {}
        for span in batch:
            by_tracer.setdefault(span.tracer, []).append(span)
        status_code = {STATUS_UNSET: 0, STATUS_OK: 1, STATUS_ERROR: 2}
        scope_spans = []
        for tracer, spans in by_tracer.items():
            scope_spans.append({
                "scope": {"name": tracer},
                "spans": [{
                    "traceId": f"{span.trace_id:032x}",
                    "spanId": f"{span.span_id:016x}",
                    **({"parentSpanId": f"{span.parent_id:016x}"}
                       if span.parent_id is not None else {}),
                    "name": span.name,
                    "kind": 1,  # SPAN_KIND_INTERNAL
                    "startTimeUnixNano": str(int(span.start_time * 1e9)),
                    "endTimeUnixNano": str(int(span.end_time * 1e9)),
                    "attributes": self._attrs(span.attributes),
                    "events": [{
                        "timeUnixNano": str(int(ev.timestamp * 1e9)),
                        "name": ev.name,
                        "attributes": self._attrs(ev.attributes),
                    } for ev in span.events],
                    "status": {
                        "code": status_code.get(span.status, 0),
                        **({"message": span.status_description}
                           if span.status_description else {}),
                    },
                } for span in spans],
            })
        return {"resourceSpans": [{
            "resource": {"attributes": self._attrs(
                {"service.name": self.service_name})},
            "scopeSpans": scope_spans,
        }]}


class NoopProvider:
    recording = False

    @contextmanager
    def span(self, tracer: str, name: str,
             attributes: dict | None = None) -> Iterator[_NoopSpan]:
        yield _NOOP_SPAN


class SDKProvider:
    """Recording provider: spans export on end, parentage via a context stack
    (thread-local, like OTel context propagation). ``exporter`` is anything
    with ``export(span)`` — the in-memory test exporter or the production
    OTLP/HTTP one."""

    recording = True

    def __init__(self, exporter: InMemorySpanExporter | OtlpHttpExporter) \
            -> None:
        self.exporter = exporter
        self._local = threading.local()
        self._lock = threading.Lock()
        self._next_id = 1

    def _ids(self) -> int:
        with self._lock:
            i = self._next_id
            self._next_id += 1
            return i

    @contextmanager
    def span(self, tracer: str, name: str,
             attributes: dict | None = None) -> Iterator[Span]:
        stack: list[Span] = getattr(self._local, "stack", None) or []
        self._local.stack = stack
        parent = stack[-1] if stack else None
        span = Span(name=name, tracer=tracer,
                    trace_id=parent.trace_id if parent else self._ids(),
                    span_id=self._ids(),
                    parent_id=parent.span_id if parent else None,
                    attributes=dict(attributes or {}),
                    start_time=time.time())
        stack.append(span)
        try:
            yield span
        except BaseException as exc:
            span.record_exception(exc)
            raise
        finally:
            span.end_time = time.time()
            stack.pop()
            self.exporter.export(span)


_provider: NoopProvider | SDKProvider = NoopProvider()
_provider_lock = threading.Lock()


def set_provider(provider: NoopProvider | SDKProvider) -> None:
    global _provider
    with _provider_lock:
        _provider = provider


def get_provider() -> NoopProvider | SDKProvider:
    return _provider


def current_span():
    """The innermost active recording span on this thread (OTel's
    trace.SpanFromContext) — a no-op sink when the provider isn't recording
    or no span is open, so callers can add events unconditionally."""
    provider = _provider
    if isinstance(provider, SDKProvider):
        stack = getattr(provider._local, "stack", None)
        if stack:
            return stack[-1]
    return _NOOP_SPAN


class Tracer:
    """Named tracer handle — cheap, safe to cache (the reference memoizes via
    sync.OnceValue; here the provider lookup is deferred to span start so a
    provider installed later is picked up, same observable behavior)."""

    def __init__(self, name: str) -> None:
        self.name = name

    def start_span(self, name: str, attributes: dict | None = None):
        return _provider.span(self.name, name, attributes)


def get_tracer(name: str) -> Tracer:
    return Tracer(name)
