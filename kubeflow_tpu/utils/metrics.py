"""Minimal Prometheus metrics registry (text exposition format).

The reference exposes five series via the controller-runtime metrics registry
(components/notebook-controller/pkg/metrics/metrics.go:13-99):
``notebook_running`` (gauge, scraped by listing StatefulSets with the
``notebook-name`` label), ``notebook_create_total``,
``notebook_create_failed_total``, ``notebook_culling_total``, and
``last_notebook_culling_timestamp_seconds``. prometheus_client isn't part of
this image's baked-in set, so we implement the text format directly."""

from __future__ import annotations

import threading
import time
from typing import Callable


class _Metric:
    def __init__(self, name: str, help_: str, type_: str):
        self.name = name
        self.help = help_
        self.type = type_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def _labels_key(self, labels: dict[str, str] | None) -> tuple:
        return tuple(sorted((labels or {}).items()))

    def inc(self, labels: dict[str, str] | None = None, by: float = 1.0) -> None:
        key = self._labels_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + by

    def set(self, value: float, labels: dict[str, str] | None = None) -> None:
        with self._lock:
            self._values[self._labels_key(labels)] = value

    def get(self, labels: dict[str, str] | None = None) -> float:
        with self._lock:
            return self._values.get(self._labels_key(labels), 0.0)

    def total(self) -> float:
        """Sum over every label combination — e.g. all verbs/codes of
        rest_client_requests_total (what the loadtest's requests-per-
        notebook bound is computed from)."""
        with self._lock:
            return sum(self._values.values())

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.type}"]
        with self._lock:
            items = sorted(self._values.items())
        if not items:
            items = [((), 0.0)]
        for key, value in items:
            label_s = ",".join(f'{k}="{v}"' for k, v in key)
            suffix = f"{{{label_s}}}" if label_s else ""
            lines.append(f"{self.name}{suffix} {value:g}")
        return "\n".join(lines)


class MetricsRegistry:
    """Registry + the reference's notebook metric set. ``scrape_callbacks``
    mirrors the reference's collector that computes ``notebook_running`` at
    scrape time by listing StatefulSets (metrics.go:60-99)."""

    def __init__(self, include_notebook_metrics: bool = True) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._scrape_callbacks: list[Callable[[], None]] = []
        if not include_notebook_metrics:
            # a non-controller process (e.g. the serving server) wants the
            # registry machinery without the reference's notebook series
            return
        self.notebook_create_total = self.counter(
            "notebook_create_total", "Total times of creating notebooks")
        self.notebook_create_failed_total = self.counter(
            "notebook_create_failed_total", "Total failure times of creating notebooks")
        self.notebook_culling_total = self.counter(
            "notebook_culling_total", "Total times of culling notebooks")
        self.last_culling_timestamp = self.gauge(
            "last_notebook_culling_timestamp_seconds",
            "Timestamp of the last notebook culling in seconds")
        self.notebook_running = self.gauge(
            "notebook_running", "Current running notebooks in the cluster")

    def counter(self, name: str, help_: str) -> _Metric:
        # get-or-create (prometheus registration semantics): re-registering
        # must return the live metric, not silently reset it
        existing = self._metrics.get(name)
        if existing is not None:
            return existing
        m = _Metric(name, help_, "counter")
        self._metrics[name] = m
        return m

    def gauge(self, name: str, help_: str) -> _Metric:
        existing = self._metrics.get(name)
        if existing is not None:
            return existing
        m = _Metric(name, help_, "gauge")
        self._metrics[name] = m
        return m

    def on_scrape(self, fn: Callable[[], None]) -> None:
        self._scrape_callbacks.append(fn)

    def record_culling(self, namespace: str, name: str) -> None:
        self.notebook_culling_total.inc({"namespace": namespace, "name": name})
        self.last_culling_timestamp.set(time.time())

    def expose(self) -> str:
        for fn in self._scrape_callbacks:
            fn()
        return "\n".join(m.expose() for m in self._metrics.values()) + "\n"
