"""Minimal Prometheus metrics registry (text exposition format).

The reference exposes five series via the controller-runtime metrics registry
(components/notebook-controller/pkg/metrics/metrics.go:13-99):
``notebook_running`` (gauge, scraped by listing StatefulSets with the
``notebook-name`` label), ``notebook_create_total``,
``notebook_create_failed_total``, ``notebook_culling_total``, and
``last_notebook_culling_timestamp_seconds``. prometheus_client isn't part of
this image's baked-in set, so we implement the text format directly."""

from __future__ import annotations

import threading
import time
from typing import Callable

from . import sanitizer

# Every metric family name constructed anywhere in the package (one
# dynamic exception: runtime/server.py's scrape-mirrored
# ``serving_engine_<counter>`` gauges, whose names come from the engine).
# ci/lint.py's metric-catalog rule parses this literal from the AST and
# rejects any ``.counter("x", ...)``/``.gauge``/``.histogram`` whose
# literal name is missing — so a new family is a deliberate, reviewed
# addition to the exposition surface, never an accidental one.
METRIC_FAMILY_CATALOG = frozenset({
    # reference notebook metrics (metrics.go:13-99)
    "notebook_create_total",
    "notebook_create_failed_total",
    "notebook_culling_total",
    "last_notebook_culling_timestamp_seconds",
    "notebook_running",
    # controller-runtime analogs (manager)
    "controller_runtime_reconcile_total",
    "workqueue_adds_total",
    "workqueue_retries_total",
    "workqueue_queue_duration_seconds",
    "workqueue_work_duration_seconds",
    "workqueue_depth",
    "workqueue_unfinished_work_seconds",
    "workqueue_longest_running_processor_seconds",
    "reconcile_read_seconds",
    "reconcile_write_seconds",
    # sharding / resilience
    "shard_ownership",
    "shard_rebalance_total",
    "apiserver_available",
    "apiserver_breaker_state",
    "apiserver_breaker_transitions_total",
    # slice pool / repair
    "slicepool_bind_latency_seconds",
    "slicepool_bind_misses_total",
    "slicepool_size",
    "slice_repairs_total",
    "slice_repair_duration_seconds",
    "slice_quarantines_total",
    "slice_degraded",
    "notebook_migrations_total",
    "elastic_resizes_total",
    # fleet scheduler
    "scheduler_admissions_total",
    "scheduler_preemptions_total",
    "scheduler_gang_wait_seconds",
    "scheduler_quota_used",
    # serving
    "serving_http_requests_total",
    "serving_generate_seconds_sum",
    "serving_generate_seconds_count",
    # apiserver wire / store / cache
    "apf_dispatched_total",
    "apf_rejected_total",
    "apf_current_inqueue",
    "cache_index_lookups_total",
    "cache_full_scans_total",
    "rest_client_requests_total",
    "rest_client_retries_total",
    "rest_client_request_duration_seconds",
    "rest_client_connections_opened_total",
    "watch_resumes_total",
    "watch_cache_evictions_total",
    "store_list_lock_seconds",
    "store_write_lock_seconds",
    "watch_queue_coalesced_total",
    "watch_fanout_bytes_total",
    "watch_frames_sent_total",
    "apiserver_cache_lists_total",
    # concurrency sanitizer
    "sanitizer_violations_total",
})

# Label names per family — the cardinality contract that goes with the
# name contract above. Every literal label dict passed to
# ``.inc``/``.set``/``.observe`` anywhere in the package must use only
# these keys (tests/test_observability.py scans the AST and pins it);
# adding a label is a deliberate, reviewed cardinality change. Families
# with ``()`` expose a single unlabeled series.
METRIC_FAMILY_LABELS = {
    "apf_current_inqueue": ("priority_level",),
    "apf_dispatched_total": ("priority_level",),
    "apf_rejected_total": ("priority_level",),
    "apiserver_available": (),
    "apiserver_breaker_state": (),
    "apiserver_breaker_transitions_total": ("to",),
    "apiserver_cache_lists_total": (),
    "cache_full_scans_total": ("kind",),
    "cache_index_lookups_total": ("index", "kind"),
    "controller_runtime_reconcile_total": ("controller", "result"),
    "elastic_resizes_total": ("namespace", "outcome"),
    "last_notebook_culling_timestamp_seconds": (),
    "notebook_create_failed_total": (),
    "notebook_create_total": (),
    "notebook_culling_total": ("name", "namespace"),
    "notebook_migrations_total": ("outcome",),
    "notebook_running": (),
    "reconcile_read_seconds": ("controller",),
    "reconcile_write_seconds": ("controller",),
    "rest_client_connections_opened_total": ("type",),
    "rest_client_request_duration_seconds": ("verb",),
    "rest_client_requests_total": ("code", "method"),
    "rest_client_retries_total": ("reason", "verb"),
    "sanitizer_violations_total": ("rule",),
    "scheduler_admissions_total": ("outcome", "tenant"),
    "scheduler_gang_wait_seconds": ("tenant",),
    "scheduler_preemptions_total": ("outcome", "tier"),
    "scheduler_quota_used": ("tenant",),
    "serving_generate_seconds_count": (),
    "serving_generate_seconds_sum": (),
    "serving_http_requests_total": ("code", "method", "route"),
    "shard_ownership": ("manager", "shard"),
    "shard_rebalance_total": ("manager",),
    "slice_degraded": ("namespace", "state"),
    "slice_quarantines_total": ("namespace",),
    "slice_repair_duration_seconds": ("namespace",),
    "slice_repairs_total": ("namespace", "reason"),
    "slicepool_bind_latency_seconds": ("pool",),
    "slicepool_bind_misses_total": ("reason",),
    "slicepool_size": ("pool", "state"),
    "store_list_lock_seconds": ("kind",),
    "store_write_lock_seconds": ("kind",),
    "watch_cache_evictions_total": ("kind",),
    "watch_fanout_bytes_total": ("encoding",),
    "watch_frames_sent_total": ("encoding",),
    "watch_queue_coalesced_total": (),
    "watch_resumes_total": ("kind", "mode"),
    "workqueue_adds_total": ("name",),
    "workqueue_depth": ("name",),
    "workqueue_longest_running_processor_seconds": ("name",),
    "workqueue_queue_duration_seconds": ("name",),
    "workqueue_retries_total": ("name",),
    "workqueue_unfinished_work_seconds": ("name",),
    "workqueue_work_duration_seconds": ("name",),
}


def _escape_label_value(value: object) -> str:
    """Prometheus exposition escaping for label values: backslash, double
    quote, and line feed must be escaped (in that order — escaping the
    backslash first keeps the other two unambiguous)."""
    return (str(value).replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """HELP lines escape backslash and line feed (quotes are legal there)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _format_labels(key: tuple) -> str:
    return ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)


def _format_exemplar(exemplar: dict[str, str], value: float,
                     timestamp: float) -> str:
    """OpenMetrics exemplar rendered as an exposition comment —
    ``# {trace_id="..."} <value> <ts>`` appended to the sample line. Plain
    Prometheus text parsers treat everything after ``#`` as a comment, so
    the format stays 0.0.4-compatible."""
    labels = _format_labels(tuple(sorted(exemplar.items())))
    return f" # {{{labels}}} {value:g} {timestamp:.3f}"


class _Metric:
    def __init__(self, name: str, help_: str, type_: str):
        self.name = name
        self.help = help_
        self.type = type_
        self._values: dict[tuple, float] = {}
        self._lock = sanitizer.tracked_lock(
            "metrics.family", order=sanitizer.ORDER_LEAF)

    def _labels_key(self, labels: dict[str, str] | None) -> tuple:
        return tuple(sorted((labels or {}).items()))

    def inc(self, labels: dict[str, str] | None = None, by: float = 1.0) -> None:
        key = self._labels_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + by

    def set(self, value: float, labels: dict[str, str] | None = None) -> None:
        with self._lock:
            self._values[self._labels_key(labels)] = value

    def get(self, labels: dict[str, str] | None = None) -> float:
        with self._lock:
            return self._values.get(self._labels_key(labels), 0.0)

    def total(self) -> float:
        """Sum over every label combination — e.g. all verbs/codes of
        rest_client_requests_total (what the loadtest's requests-per-
        notebook bound is computed from)."""
        with self._lock:
            return sum(self._values.values())

    def sum_where(self, match: dict[str, str]) -> float:
        """Sum over every label combination whose labels include ``match``
        — e.g. ``watch_resumes_total`` summed across kinds for one mode
        (the loadtest's zero-relist bound)."""
        want = set(match.items())
        with self._lock:
            return sum(v for key, v in self._values.items()
                       if want <= set(key))

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.type}"]
        with self._lock:
            items = sorted(self._values.items())
        if not items:
            items = [((), 0.0)]
        for key, value in items:
            label_s = _format_labels(key)
            suffix = f"{{{label_s}}}" if label_s else ""
            lines.append(f"{self.name}{suffix} {value:g}")
        return "\n".join(lines)


# workqueue latencies span sub-ms (in-process store) to tens of seconds
# (big wire fan-outs); client-go's exponential buckets cover the same range
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   30.0, 60.0)


class _Histogram:
    """Prometheus histogram (cumulative ``_bucket{le=...}`` + ``_sum`` +
    ``_count`` exposition). Fixed buckets, chosen at registration."""

    def __init__(self, name: str, help_: str,
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.type = "histogram"
        self.buckets = tuple(sorted(buckets))
        # labels key → [per-bucket counts..., +Inf count, sum]
        self._series: dict[tuple, list[float]] = {}
        # labels key → (exemplar labels, observed value, unix ts): the most
        # recent exemplared observation, attached at exposition to the
        # bucket the value fell into (OpenMetrics exemplar semantics)
        self._exemplars: dict[tuple, tuple[dict[str, str], float, float]] = {}
        self._lock = sanitizer.tracked_lock(
            "metrics.family", order=sanitizer.ORDER_LEAF)

    def _labels_key(self, labels: dict[str, str] | None) -> tuple:
        return tuple(sorted((labels or {}).items()))

    def observe(self, value: float,
                labels: dict[str, str] | None = None,
                exemplar: dict[str, str] | None = None) -> None:
        key = self._labels_key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = [0.0] * (len(self.buckets) + 2)
            for i, le in enumerate(self.buckets):
                if value <= le:
                    series[i] += 1
            series[-2] += 1          # +Inf / _count
            series[-1] += value      # _sum
            if exemplar:
                self._exemplars[key] = (dict(exemplar), value, time.time())

    def count(self, labels: dict[str, str] | None = None) -> float:
        with self._lock:
            series = self._series.get(self._labels_key(labels))
        return series[-2] if series else 0.0

    def sum(self, labels: dict[str, str] | None = None) -> float:
        with self._lock:
            series = self._series.get(self._labels_key(labels))
        return series[-1] if series else 0.0

    def total_sum(self) -> float:
        """Sum of ``_sum`` over every label combination (e.g. all
        controllers of reconcile_read_seconds)."""
        with self._lock:
            return sum(series[-1] for series in self._series.values())

    def total_count(self) -> float:
        """Sum of ``_count`` over every label combination."""
        with self._lock:
            return sum(series[-2] for series in self._series.values())

    def _exemplar_bucket(self, value: float) -> int:
        """Index of the lowest bucket containing ``value`` (len(buckets)
        means +Inf)."""
        for i, le in enumerate(self.buckets):
            if value <= le:
                return i
        return len(self.buckets)

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.type}"]
        with self._lock:
            items = sorted((k, list(v)) for k, v in self._series.items())
            exemplars = dict(self._exemplars)
        for key, series in items:
            base = _format_labels(key)
            ex = exemplars.get(key)
            ex_bucket = self._exemplar_bucket(ex[1]) if ex else -1
            for i, le in enumerate(self.buckets):
                label_s = (base + "," if base else "") + f'le="{le:g}"'
                tail = (_format_exemplar(*ex)
                        if ex and i == ex_bucket else "")
                lines.append(f"{self.name}_bucket{{{label_s}}} "
                             f"{series[i]:g}{tail}")
            label_s = (base + "," if base else "") + 'le="+Inf"'
            tail = (_format_exemplar(*ex)
                    if ex and ex_bucket == len(self.buckets) else "")
            lines.append(f"{self.name}_bucket{{{label_s}}} "
                         f"{series[-2]:g}{tail}")
            suffix = f"{{{base}}}" if base else ""
            lines.append(f"{self.name}_sum{suffix} {series[-1]:g}")
            lines.append(f"{self.name}_count{suffix} {series[-2]:g}")
        return "\n".join(lines)


# --------------------------------------------------------- phase collector
# Per-reconcile read/write wall decomposition: the manager opens a
# collection window on the worker thread (phase_collect_start), the
# reconciler's client wrapper attributes each verb's duration to "read"
# (get/list/get_owned) or "write" (create/update/patch/delete) via
# phase_record, and the manager observes the totals into
# reconcile_read_seconds / reconcile_write_seconds at the end. Thread-local,
# so concurrent workers never mix phases; recording outside a window (watch
# threads, scrape callbacks) is a no-op.
_phase_tls = threading.local()


def phase_collect_start() -> None:
    _phase_tls.acc = {"read": 0.0, "write": 0.0}


def phase_record(phase: str, seconds: float) -> None:
    acc = getattr(_phase_tls, "acc", None)
    if acc is not None:
        acc[phase] = acc.get(phase, 0.0) + seconds


def phase_collect_finish() -> dict[str, float]:
    acc = getattr(_phase_tls, "acc", None) or {}
    _phase_tls.acc = None
    return acc


class MetricsRegistry:
    """Registry + the reference's notebook metric set. ``scrape_callbacks``
    mirrors the reference's collector that computes ``notebook_running`` at
    scrape time by listing StatefulSets (metrics.go:60-99)."""

    def __init__(self, include_notebook_metrics: bool = True) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._scrape_callbacks: list[Callable[[], None]] = []
        if not include_notebook_metrics:
            # a non-controller process (e.g. the serving server) wants the
            # registry machinery without the reference's notebook series
            return
        self.notebook_create_total = self.counter(
            "notebook_create_total", "Total times of creating notebooks")
        self.notebook_create_failed_total = self.counter(
            "notebook_create_failed_total", "Total failure times of creating notebooks")
        self.notebook_culling_total = self.counter(
            "notebook_culling_total", "Total times of culling notebooks")
        self.last_culling_timestamp = self.gauge(
            "last_notebook_culling_timestamp_seconds",
            "Timestamp of the last notebook culling in seconds")
        self.notebook_running = self.gauge(
            "notebook_running", "Current running notebooks in the cluster")

    def counter(self, name: str, help_: str) -> _Metric:
        # get-or-create (prometheus registration semantics): re-registering
        # must return the live metric, not silently reset it
        existing = self._metrics.get(name)
        if existing is not None:
            return existing
        m = _Metric(name, help_, "counter")
        self._metrics[name] = m
        return m

    def gauge(self, name: str, help_: str) -> _Metric:
        existing = self._metrics.get(name)
        if existing is not None:
            return existing
        m = _Metric(name, help_, "gauge")
        self._metrics[name] = m
        return m

    def histogram(self, name: str, help_: str,
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> _Histogram:
        existing = self._metrics.get(name)
        if existing is not None:
            return existing
        m = _Histogram(name, help_, buckets)
        self._metrics[name] = m
        return m

    def on_scrape(self, fn: Callable[[], None]) -> None:
        self._scrape_callbacks.append(fn)

    def record_culling(self, namespace: str, name: str) -> None:
        self.notebook_culling_total.inc({"namespace": namespace, "name": name})
        self.last_culling_timestamp.set(time.time())

    def expose(self) -> str:
        # snapshot both collections: a concurrent worker registering a
        # metric mid-scrape must not blow up the exposition iteration
        for fn in list(self._scrape_callbacks):
            fn()
        return "\n".join(m.expose()
                         for m in list(self._metrics.values())) + "\n"
