from .mutating import NotebookMutatingWebhook
from .validating import NotebookValidatingWebhook, AdmissionDenied

__all__ = ["NotebookMutatingWebhook", "NotebookValidatingWebhook",
           "AdmissionDenied"]
