"""AdmissionReview webhook server.

Production shape of the admission layer: the kube-apiserver POSTs
``admission.k8s.io/v1`` AdmissionReview JSON over HTTPS to
``/mutate-notebook-v1`` and ``/validate-notebook-v1`` (the reference
registers exactly these paths on the manager's webhook server, odh
main.go:306-331), and receives allowed/denied plus a JSONPatch for
mutations. ``failurePolicy=fail`` semantics live in the cluster-side webhook
configuration; this server's contract is: always answer, deny with a reason
on validation errors, 400 on malformed reviews.

stdlib-only (http.server + ssl): TLS when cert/key paths are given (the
serving cert comes from the platform CA in-cluster), plain HTTP for tests."""

from __future__ import annotations

import base64
import json
import logging
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from ..cluster.errors import ApiError
from ..utils import k8s

log = logging.getLogger("kubeflow_tpu.webhook.server")

MUTATE_PATH = "/mutate-notebook-v1"
VALIDATE_PATH = "/validate-notebook-v1"


def json_patch(original: Any, mutated: Any, path: str = "") -> list[dict]:
    """RFC 6902 patch ops transforming ``original`` into ``mutated``."""
    if original == mutated:
        return []
    if isinstance(original, dict) and isinstance(mutated, dict):
        ops: list[dict] = []
        for key in original:
            escaped = _escape(key)
            if key not in mutated:
                ops.append({"op": "remove", "path": f"{path}/{escaped}"})
            else:
                ops.extend(json_patch(original[key], mutated[key],
                                      f"{path}/{escaped}"))
        for key in mutated:
            if key not in original:
                ops.append({"op": "add", "path": f"{path}/{_escape(key)}",
                            "value": mutated[key]})
        return ops
    return [{"op": "replace", "path": path or "", "value": mutated}]


def _escape(key: str) -> str:
    return key.replace("~", "~0").replace("/", "~1")


class AdmissionServer:
    """Serves both webhooks. ``mutating``/``validating`` expose
    handle(operation, obj, old) — the same objects the in-process admission
    plugins use, so cluster deployments and the in-process apiserver share
    one code path."""

    def __init__(self, mutating, validating, host: str = "0.0.0.0",
                 port: int = 8443, certfile: str | None = None,
                 keyfile: str | None = None, tls_profile=None):
        self.mutating = mutating
        self.validating = validating
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route through logging
                log.debug("webhook http: " + fmt, *args)

            def do_POST(self) -> None:
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    review = json.loads(self.rfile.read(length))
                    response = outer.review(self.path, review)
                except (ValueError, KeyError) as exc:
                    self.send_error(400, f"malformed AdmissionReview: {exc}")
                    return
                body = json.dumps(response).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        if certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            if tls_profile is not None:
                # cluster TLS security profile (utils.tls_profile; reference
                # odh main.go:178-234 applies the fetched-or-fallback profile
                # to every listener)
                tls_profile.apply(ctx)
            else:
                ctx.minimum_version = ssl.TLSVersion.TLSv1_2
            ctx.load_cert_chain(certfile, keyfile)
            self._server.socket = ctx.wrap_socket(self._server.socket,
                                                  server_side=True)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def is_serving(self) -> bool:
        """True while the accept loop is actually running — readiness probes
        must reflect a dead listener, not mere construction."""
        return self._thread is not None and self._thread.is_alive()

    # ------------------------------------------------------------- review
    def review(self, path: str, review: dict) -> dict:
        request = review["request"]
        uid = request["uid"]
        operation = request.get("operation", "CREATE")
        obj = request.get("object")
        old = request.get("oldObject")
        resp: dict = {"uid": uid, "allowed": True}
        try:
            if path == MUTATE_PATH:
                mutated = self.mutating.handle(operation, k8s.deepcopy(obj),
                                               old)
                ops = json_patch(obj, mutated)
                if ops:
                    resp["patchType"] = "JSONPatch"
                    resp["patch"] = base64.b64encode(
                        json.dumps(ops).encode()).decode()
            elif path == VALIDATE_PATH:
                self.validating.handle(operation, obj, old)
            else:
                raise KeyError(f"unknown webhook path {path}")
        except ApiError as exc:
            resp["allowed"] = False
            resp["status"] = {"code": exc.code, "message": exc.message}
        except KeyError:
            raise  # malformed review → caller's 400
        except Exception as exc:  # noqa: BLE001 — always answer: a handler
            # crash (null object, wrong shapes) must become a deny, not a
            # dropped connection the apiserver reads as a webhook outage
            log.exception("webhook handler error")
            resp["allowed"] = False
            resp["status"] = {"code": 500,
                              "message": f"webhook error: {exc}"}
        return {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "response": resp,
        }

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="kubeflow-tpu-webhook")
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
