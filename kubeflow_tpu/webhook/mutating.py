"""Mutating admission webhook for Notebook CRs.

Re-implements the admission pipeline of the reference's NotebookWebhook.Handle
(odh notebook_mutating_webhook.go:360-516), TPU-adapted:

1. CREATE only: inject the reconciliation lock — the stop annotation set to a
   sentinel so the StatefulSet starts at replicas=0 until the extension
   reconciler confirms prerequisites (reference :382-389,:113-122; prevents
   the pod racing its image-pull secret);
2. image resolution + TPU swap: annotation-selected ImageStream tags resolve
   to digest-pinned references (SetContainerImageFromRegistry, :861-972),
   then CRs requesting a TPU slice get CUDA/generic images swapped for
   JAX/libtpu images — mapping from config.image_swap_map with
   config.tpu_default_image fallback;
3. CA bundle mount when the per-namespace trust ConfigMap exists
   (:699-859);
4. MLflow env-var injection, Feast config mount (label-gated), pipeline
   runtime-images mount (:405-462);
5. inject-auth: kube-rbac-proxy sidecar (:183-334) with
   annotation-overridable resources (default cpu 100m / mem 64Mi,
   odh notebook_controller.go:63-66);
6. restart gating (:518-581, the subtlest behavior — SURVEY §7 hard part):
   webhook-caused pod-spec changes on a RUNNING notebook are parked in the
   ``update-pending`` annotation rather than applied, so admission never
   silently bounces a live slice; user-caused changes pass through.
"""

from __future__ import annotations

import json
import logging

from ..api import types as api
from ..cluster.errors import InvalidError
from ..tpu.topology import parse_slice_request
from ..utils import k8s, names, tracing
from ..utils.config import ControllerConfig
from .diff import first_differences
from .validating import AdmissionDenied

log = logging.getLogger("kubeflow_tpu.webhook")
_tracer = tracing.get_tracer("kubeflow_tpu.webhook")

CA_BUNDLE_CONFIGMAP = "workbench-trusted-ca-bundle"
CA_CERT_PATH = "/etc/pki/tls/custom-certs"
RUNTIME_IMAGES_CONFIGMAP = "pipeline-runtime-images"
RUNTIME_IMAGES_MOUNT = "/opt/app-root/pipeline-runtimes"
FEAST_MOUNT = "/opt/app-root/src/feast-config"
AUTH_PROXY_CONTAINER = "kube-rbac-proxy"
AUTH_PROXY_PORT = 8443


class NotebookMutatingWebhook:
    """Registered as an apiserver admission plugin (ClusterStore) or behind
    the AdmissionReview HTTPS server (webhook.server) — same handle()."""

    def __init__(self, client, config: ControllerConfig | None = None):
        self.client = client
        self.config = config or ControllerConfig()

    def install(self, store) -> None:
        store.register_admission(api.KIND, self.handle)

    # ------------------------------------------------------------ pipeline
    def handle(self, operation: str, notebook: dict, old: dict | None) -> dict:
        """One root span per admission with notebook/namespace/operation
        attributes, like the reference (:366-373)."""
        if operation not in ("CREATE", "UPDATE"):
            return notebook
        if k8s.is_deleting(notebook):
            return notebook
        with _tracer.start_span("notebook-mutating-webhook", {
                "notebook.name": k8s.name(notebook),
                "notebook.namespace": k8s.namespace(notebook),
                "admission.operation": operation}) as span:
            mutated = k8s.deepcopy(notebook)

            if operation == "CREATE":
                self._inject_reconciliation_lock(mutated)

            self._resolve_image_selection(mutated, operation)
            self._swap_image_for_tpu(mutated)
            self._mount_ca_bundle(mutated)
            self._mount_runtime_images(mutated)
            self._mount_feast_config(mutated)
            self._mount_elyra_secret(mutated)
            self._inject_mlflow_env(mutated)
            self._inject_cluster_proxy_env(mutated)
            if k8s.get_annotation(mutated, names.INJECT_AUTH_ANNOTATION) == "true":
                self._inject_auth_proxy(mutated)
            else:
                self._remove_auth_proxy(mutated)

            if operation == "UPDATE" and old is not None:
                mutated = self._maybe_defer_updates(old, notebook, mutated)
            span.set_status(tracing.STATUS_OK)
            return mutated

    # ------------------------------------------------------ lock (stage 1)
    def _inject_reconciliation_lock(self, nb: dict) -> None:
        """Reference InjectReconciliationLock (:106-122): notebooks are born
        stopped under a sentinel value; the extension reconciler removes it
        once prerequisites (pull secrets, routes) exist."""
        anns = k8s.annotations(nb)
        if names.STOP_ANNOTATION not in anns:
            anns[names.STOP_ANNOTATION] = names.RECONCILIATION_LOCK_VALUE

    # ------------------------------------- image resolution (stage 2a)
    INTERNAL_REGISTRY_HOST = "image-registry.openshift-image-registry.svc:5000"

    def _resolve_image_selection(self, nb: dict, operation: str) -> None:
        """Annotation-driven image selection with digest pinning — reference
        SetContainerImageFromRegistry (notebook_mutating_webhook.go:861-972):

        - ``last-image-selection: <imagestream>:<tag>`` names the selection;
        - an image already pointing at the internal registry is left alone;
        - the ImageStream is looked up in the workbench-image-namespace
          annotation's namespace, defaulting to the controller namespace;
        - the newest item of the matching status tag provides the
          digest-pinned dockerImageReference, which becomes the container
          image (stable across reconciles — re-admission resolves to the
          same digest);
        - JUPYTER_IMAGE env (when present) is updated to the selection;
        - misses emit the reference's span events and leave the image as-is,
          except a malformed selection / missing tags, which deny admission.
        """
        selection = k8s.get_annotation(nb, names.IMAGE_SELECTION_ANNOTATION)
        if not selection:
            return
        # shared container convention (api.notebook_container: name-matched
        # else containers[0]) — webhook and reconcilers MUST target the same
        # container (api/types.py)
        container = api.notebook_container(nb)
        if container is None:
            raise InvalidError(
                f"notebook {k8s.name(nb)} has no containers to resolve the "
                f"image selection onto")
        if self.INTERNAL_REGISTRY_HOST in container.get("image", ""):
            return  # digest already pinned by the internal registry
        parts = selection.split(":")
        if len(parts) != 2:
            # strict on CREATE (reference errors on a malformed selection);
            # lenient on UPDATE so a pre-existing object carrying a legacy
            # or hand-written value is never bricked — stop/resume and
            # culling patches must keep flowing
            if operation == "CREATE":
                raise InvalidError(f"invalid image selection format: "
                                   f"{selection!r}")
            tracing.current_span().add_event(
                "image-selection-malformed", {"selection": selection})
            return
        stream_name, tag_name = parts
        stream_ns = (k8s.get_annotation(
            nb, names.WORKBENCH_IMAGE_NAMESPACE_ANNOTATION) or "").strip() \
            or self.config.controller_namespace
        stream = self.client.get_or_none("ImageStream", stream_ns, stream_name)
        if stream is None:
            tracing.current_span().add_event(
                "image-stream-not-found",
                {"imagestream": stream_name, "namespace": stream_ns})
            return
        tags = k8s.get_in(stream, "status", "tags", default=None)
        if not tags:
            tracing.current_span().add_event(
                "image-stream-tag-not-found", {"imagestream": stream_name})
            raise InvalidError(
                f"ImageStream {stream_ns}/{stream_name} has no status or tags")
        for tag in tags:
            if tag.get("tag") != tag_name:
                continue
            items = tag.get("items") or []
            if not items:
                continue
            newest = max(items, key=lambda item: item.get("created", ""))
            image_ref = newest.get("dockerImageReference", "")
            if not image_ref:
                continue
            container["image"] = image_ref
            for env in container.get("env", []) or []:
                if env.get("name") == "JUPYTER_IMAGE":
                    env["value"] = selection
                    break
            tracing.current_span().add_event(
                "image-resolved", {"selection": selection, "image": image_ref})
            return
        tracing.current_span().add_event(
            "image-stream-tag-not-found",
            {"imagestream": stream_name, "tag": tag_name})

    # ------------------------------------------------ image swap (stage 2)
    def _swap_image_for_tpu(self, nb: dict) -> None:
        """TPU-native stage after image resolution: a CR requesting a TPU
        slice gets CUDA/generic images replaced by the JAX/libtpu image so
        the provisioned pod can actually drive the chips. The replaced image
        is recorded in the tpu original-image annotation."""
        try:
            slice_spec = parse_slice_request(
                k8s.get_in(nb, "metadata", "annotations", default={}))
        except Exception:  # noqa: BLE001 — malformed request: the validating
            return        # webhook denies it with the proper admission error
        if slice_spec is None:
            return
        container = api.notebook_container(nb)
        if container is None:
            return
        image = container.get("image", "")
        swap_map = self.config.image_swap_map or {}
        if image in swap_map:
            new_image = swap_map[image]
        elif _looks_cuda(image) or _is_generic_notebook_image(image):
            new_image = self.config.tpu_default_image
        else:
            # the analog of the reference's ImageStream-miss span events
            # (:912,928,961): record why no swap happened
            tracing.current_span().add_event(
                "image-swap-skipped", {"image": image})
            return  # already a TPU-capable image (or user knows best)
        if new_image and new_image != image:
            k8s.set_annotation(nb, names.TPU_ORIGINAL_IMAGE_ANNOTATION, image)
            container["image"] = new_image
            tracing.current_span().add_event(
                "image-swapped", {"from": image, "to": new_image})

    # ------------------------------------------------- CA bundle (stage 3)
    def _mount_ca_bundle(self, nb: dict) -> None:
        """Mount the per-namespace trust bundle when present (reference
        CheckAndMountCACertBundle → InjectCertConfig, :699-859). Unsets the
        mount when the ConfigMap is gone."""
        ns = k8s.namespace(nb)
        cm = self.client.get_or_none("ConfigMap", ns, CA_BUNDLE_CONFIGMAP)
        pod_spec = api.notebook_pod_spec(nb)
        container = api.notebook_container(nb)
        if container is None:
            return
        bundle_file = f"{CA_CERT_PATH}/ca-bundle.crt"
        if cm is None or not k8s.get_in(cm, "data", "ca-bundle.crt"):
            k8s.remove_volume(pod_spec, "trusted-ca")
            k8s.remove_volume_mount(container, "trusted-ca")
            for var in ("PIP_CERT", "REQUESTS_CA_BUNDLE", "SSL_CERT_FILE",
                        "PIPELINES_SSL_SA_CERTS", "GIT_SSL_CAINFO"):
                k8s.remove_env(container, var)
            return
        k8s.upsert_volume(pod_spec, {
            "name": "trusted-ca",
            "configMap": {
                "name": CA_BUNDLE_CONFIGMAP,
                "optional": True,
                "items": [{"key": "ca-bundle.crt", "path": "ca-bundle.crt"}],
            },
        })
        k8s.upsert_volume_mount(container, {
            "name": "trusted-ca", "mountPath": CA_CERT_PATH, "readOnly": True})
        for var in ("PIP_CERT", "REQUESTS_CA_BUNDLE", "SSL_CERT_FILE",
                    "PIPELINES_SSL_SA_CERTS", "GIT_SSL_CAINFO"):
            k8s.upsert_env(container, var, bundle_file)

    # --------------------------------------------- runtime images (stage 4)
    def _mount_runtime_images(self, nb: dict) -> None:
        """Sync then mount the per-namespace pipeline-runtime-images
        ConfigMap (reference Handle runs SyncRuntimeImagesConfigMap before
        MountPipelineRuntimeImages, notebook_mutating_webhook.go:405-418,
        so the FIRST notebook in a namespace already gets the mount)."""
        from ..cluster import errors
        from ..controllers import runtime_images
        ns = k8s.namespace(nb)
        try:
            runtime_images.sync_runtime_images_config_map(
                self.client, self.config.controller_namespace, ns)
        except errors.ApiError as e:
            # supplemental: a conflict with the extension reconciler's
            # concurrent sync must not fail admission
            log.warning("runtime-images sync skipped during admission: %s",
                        e)
        cm = self.client.get_or_none("ConfigMap", ns, RUNTIME_IMAGES_CONFIGMAP)
        pod_spec = api.notebook_pod_spec(nb)
        container = api.notebook_container(nb)
        if container is None:
            return
        if cm is None or not cm.get("data"):
            k8s.remove_volume(pod_spec, "runtime-images")
            k8s.remove_volume_mount(container, "runtime-images")
            return
        k8s.upsert_volume(pod_spec, {
            "name": "runtime-images",
            "configMap": {"name": RUNTIME_IMAGES_CONFIGMAP, "optional": True},
        })
        k8s.upsert_volume_mount(container, {
            "name": "runtime-images", "mountPath": RUNTIME_IMAGES_MOUNT,
            "readOnly": True})

    # ----------------------------------------------------- feast (stage 4)
    def _mount_feast_config(self, nb: dict) -> None:
        """Label-gated Feast config mount (reference
        notebook_feast_config.go:25-158): label on → mount
        <name>-feast-config; label off → unmount."""
        pod_spec = api.notebook_pod_spec(nb)
        container = api.notebook_container(nb)
        if container is None:
            return
        enabled = k8s.get_label(nb, names.FEAST_LABEL) == "true"
        if not enabled:
            k8s.remove_volume(pod_spec, "feast-config")
            k8s.remove_volume_mount(container, "feast-config")
            return
        # deliberately NOT optional: if the Feast ConfigMap is missing the
        # pod must fail to start, surfacing the misconfiguration (reference
        # mounts the CM without optional, notebook_feast_config.go:60-70,
        # asserted in notebook_feast_config_test.go:513-564)
        k8s.upsert_volume(pod_spec, {
            "name": "feast-config",
            "configMap": {"name": f"{k8s.name(nb)}-feast-config"},
        })
        k8s.upsert_volume_mount(container, {
            "name": "feast-config", "mountPath": FEAST_MOUNT, "readOnly": True})

    # ----------------------------------------------------- elyra (stage 4)
    def _mount_elyra_secret(self, nb: dict) -> None:
        """Sync then mount the Elyra runtime Secret when pipeline-secret
        sync is on (reference SyncElyraRuntimeConfigSecret + Mount,
        :421-437). The webhook syncs BEFORE mounting so the first notebook
        in a namespace already gets the mount — the reference's
        RHOAIENG-24545 race fix (notebook_dspa_secret.go:307-312)."""
        from ..cluster import errors
        from ..controllers import elyra
        if not self.config.set_pipeline_secret:
            return
        try:
            elyra.sync_elyra_runtime_secret(self.client, self.config,
                                            k8s.namespace(nb))
        except errors.ApiError as e:
            # supplemental integration: a write conflict with the extension
            # reconciler's concurrent sync must not fail admission — the
            # reconciler converges the secret on its next pass
            log.warning("elyra secret sync skipped during admission: %s", e)
        elyra.mount_elyra_secret(self.client, nb)

    # ---------------------------------------------------- mlflow (stage 4)
    def _inject_mlflow_env(self, nb: dict) -> None:
        """Annotation-gated MLflow env injection (reference
        HandleMLflowEnvVars, notebook_mlflow.go:273-324): a present,
        non-empty (trimmed) instance annotation injects
        MLFLOW_K8S_INTEGRATION=true and
        MLFLOW_TRACKING_AUTH=kubernetes-namespaced unconditionally;
        MLFLOW_TRACKING_URI only when a hostname is determinable (else it
        is removed, never failing admission — integration is optional)."""
        from ..controllers import rbac
        container = api.notebook_container(nb)
        if container is None:
            return
        instance = (k8s.get_annotation(
            nb, names.MLFLOW_INSTANCE_ANNOTATION) or "").strip()
        if not self.config.mlflow_enabled or not instance:
            for var in ("MLFLOW_TRACKING_URI", "MLFLOW_K8S_INTEGRATION",
                        "MLFLOW_TRACKING_AUTH"):
                k8s.remove_env(container, var)
            return
        from ..cluster import errors
        k8s.upsert_env(container, "MLFLOW_K8S_INTEGRATION", "true")
        k8s.upsert_env(container, "MLFLOW_TRACKING_AUTH",
                       rbac.MLFLOW_TRACKING_AUTH_VALUE)
        try:
            uri = rbac.get_mlflow_tracking_uri(self.client, self.config,
                                               instance)
        except errors.ApiError as e:
            # a failed Gateway/Route lookup must never deny admission —
            # integration is optional (reference logs and skips,
            # notebook_mlflow.go:303-310)
            log.warning("MLflow tracking URI lookup failed: %s", e)
            uri = None
        if uri is None:
            log.warning("unable to determine MLflow tracking URI, "
                        "skipping injection")
            k8s.remove_env(container, "MLFLOW_TRACKING_URI")
            return
        k8s.upsert_env(container, "MLFLOW_TRACKING_URI", uri)

    # ---------------------------------------- cluster proxy env (stage 4)
    def _inject_cluster_proxy_env(self, nb: dict) -> None:
        """Inject cluster egress-proxy env vars (reference injects
        HTTP_PROXY/HTTPS_PROXY/NO_PROXY from the cluster Proxy config,
        notebook_mutating_webhook.go:335-354,648-697), gated by
        INJECT_CLUSTER_PROXY_ENV. Injection only happens when ALL THREE
        status fields are populated, and existing env vars are never
        removed — a missing Proxy object (non-OpenShift cluster) or a
        transiently empty status must not strip user-supplied proxy env."""
        if not self.config.inject_cluster_proxy_env:
            return  # feature off: user-supplied proxy env is left alone
        container = api.notebook_container(nb)
        if container is None:
            return
        proxy = self.client.get_or_none("Proxy", "", "cluster")
        status = k8s.get_in(proxy or {}, "status", default={}) or {}
        values = {env_name: status.get(field_, "")
                  for env_name, field_ in (("HTTP_PROXY", "httpProxy"),
                                           ("HTTPS_PROXY", "httpsProxy"),
                                           ("NO_PROXY", "noProxy"))}
        if not all(values.values()):
            return
        for env_name, value in values.items():
            k8s.upsert_env(container, env_name, value)

    # ------------------------------------------------- sidecar (stage 5)
    def _auth_sidecar_resources(self, nb: dict) -> dict:
        """Parse + validate the sidecar resource annotations (reference
        parseAndValidateAuthSidecarResources,
        notebook_mutating_webhook.go:132-181): defaults 100m/64Mi, the
        split request/limit annotations (legacy combined forms set both),
        whitespace trimmed, invalid or negative quantities and
        request > limit DENY admission — the original notebook is
        preserved (fail-early, auth_proxy_resources_test.go:509-566)."""

        explicit = {
            "cpu-request": names.AUTH_SIDECAR_CPU_REQUEST_ANNOTATION,
            "cpu-limit": names.AUTH_SIDECAR_CPU_LIMIT_ANNOTATION,
            "memory-request": names.AUTH_SIDECAR_MEMORY_REQUEST_ANNOTATION,
            "memory-limit": names.AUTH_SIDECAR_MEMORY_LIMIT_ANNOTATION,
        }
        # value + the annotation it came from (for actionable errors)
        values = {"cpu-request": ("100m", None), "cpu-limit": ("100m", None),
                  "memory-request": ("64Mi", None),
                  "memory-limit": ("64Mi", None)}
        legacy = {"cpu": names.AUTH_SIDECAR_CPU_ANNOTATION,
                  "memory": names.AUTH_SIDECAR_MEMORY_ANNOTATION}
        # reference-exact presence rule (notebook_mutating_webhook.go:157):
        # an EMPTY-STRING annotation is treated as absent (defaults apply);
        # any non-empty value — including whitespace-only — is trimmed and
        # validated, so " " denies while "" defaults, matching the Go code
        for res, ann in legacy.items():
            raw = k8s.get_annotation(nb, ann)
            if raw:
                values[f"{res}-request"] = values[f"{res}-limit"] = (raw, ann)
        for key, ann in explicit.items():
            raw = k8s.get_annotation(nb, ann)
            if raw:
                values[key] = (raw, ann)

        parsed = {}
        for key, (raw, source) in values.items():
            raw = raw.strip()
            source = source or explicit[key]
            try:
                parsed[key] = k8s.parse_quantity(raw)
            except ValueError as e:
                raise AdmissionDenied(
                    "invalid kube-rbac-proxy resource configuration: "
                    f"invalid value for annotation '{source}': "
                    f"{raw!r}: {e}")
            if parsed[key] < 0:
                raise AdmissionDenied(
                    "invalid kube-rbac-proxy resource configuration: "
                    f"annotation '{source}' value '{raw}' cannot be "
                    "negative")
            values[key] = (raw, source)
        for res in ("cpu", "memory"):
            if parsed[f"{res}-request"] > parsed[f"{res}-limit"]:
                raise AdmissionDenied(
                    "invalid kube-rbac-proxy resource configuration: "
                    f"{res} request ({values[res + '-request'][0]}) "
                    f"cannot be greater than {res} limit "
                    f"({values[res + '-limit'][0]})")
        return {"requests": {"cpu": values["cpu-request"][0],
                             "memory": values["memory-request"][0]},
                "limits": {"cpu": values["cpu-limit"][0],
                           "memory": values["memory-limit"][0]}}

    def _inject_auth_proxy(self, nb: dict) -> None:
        """kube-rbac-proxy sidecar (reference InjectKubeRbacProxy, :183-334):
        TLS reverse proxy on 8443 doing SubjectAccessReview against the
        SAR ConfigMap; probes mirror the reference's 30s/5s liveness and
        5s/5s readiness (notebook_mutating_webhook.go:227-254)."""
        nb_name = k8s.name(nb)
        pod_spec = api.notebook_pod_spec(nb)
        sidecar = {
            "name": AUTH_PROXY_CONTAINER,
            "image": self.config.auth_proxy_image,
            "args": [
                f"--secure-listen-address=0.0.0.0:{AUTH_PROXY_PORT}",
                "--upstream=http://127.0.0.1:8888/",
                f"--config-file=/etc/kube-rbac-proxy/{nb_name}-rbac-config.yaml",
                "--tls-cert-file=/etc/tls/private/tls.crt",
                "--tls-private-key-file=/etc/tls/private/tls.key",
                "--v=2",
            ],
            "ports": [{"containerPort": AUTH_PROXY_PORT, "name": "auth-proxy",
                       "protocol": "TCP"}],
            "resources": self._auth_sidecar_resources(nb),
            "livenessProbe": {
                "httpGet": {"path": "/healthz", "port": AUTH_PROXY_PORT,
                            "scheme": "HTTPS"},
                "initialDelaySeconds": 30, "periodSeconds": 5,
                "timeoutSeconds": 1, "successThreshold": 1,
                "failureThreshold": 3,
            },
            "readinessProbe": {
                "httpGet": {"path": "/healthz", "port": AUTH_PROXY_PORT,
                            "scheme": "HTTPS"},
                "initialDelaySeconds": 5, "periodSeconds": 5,
                "timeoutSeconds": 1, "successThreshold": 1,
                "failureThreshold": 3,
            },
            "volumeMounts": [
                {"name": "rbac-config",
                 "mountPath": "/etc/kube-rbac-proxy", "readOnly": True},
                {"name": "tls-certificates",
                 "mountPath": "/etc/tls/private", "readOnly": True},
            ],
        }
        containers = pod_spec.setdefault("containers", [])
        for i, c in enumerate(containers):
            if c.get("name") == AUTH_PROXY_CONTAINER:
                containers[i] = sidecar
                break
        else:
            containers.append(sidecar)
        k8s.upsert_volume(pod_spec, {
            "name": "rbac-config",
            "configMap": {"name": f"{nb_name}-rbac-config"},
        })
        k8s.upsert_volume(pod_spec, {
            "name": "tls-certificates",
            "secret": {"secretName": f"{nb_name}-tls",
                       "defaultMode": 420},
        })

    def _remove_auth_proxy(self, nb: dict) -> None:
        pod_spec = api.notebook_pod_spec(nb)
        containers = pod_spec.get("containers")
        if containers:
            pod_spec["containers"] = [
                c for c in containers if c.get("name") != AUTH_PROXY_CONTAINER]
        k8s.remove_volume(pod_spec, "rbac-config")
        k8s.remove_volume(pod_spec, "tls-certificates")

    # ------------------------------------------- restart gating (stage 6)
    def _maybe_defer_updates(self, old: dict, incoming: dict,
                             mutated: dict) -> dict:
        """Reference maybeRestartRunningNotebook (:518-581).

        Three versions are compared:
        - ``old``      what is stored (and what the pods run);
        - ``incoming`` the user's update as submitted;
        - ``mutated``  incoming + this webhook's mutations.

        If the notebook is running and the *webhook's* mutations change the
        pod spec beyond what the user asked for, those mutations are reverted
        and recorded in update-pending — admission must never silently bounce
        a live slice (a template change restarts every worker). User-caused
        changes always pass through. Stopped notebooks take everything."""
        with _tracer.start_span("maybe-restart-running-notebook") as span:
            stopped = k8s.get_annotation(incoming, names.STOP_ANNOTATION) is not None
            if stopped:
                k8s.remove_annotation(mutated, names.UPDATE_PENDING_ANNOTATION)
                return mutated
            incoming_spec = k8s.get_in(incoming, "spec", default={})
            mutated_spec = k8s.get_in(mutated, "spec", default={})
            if mutated_spec == incoming_spec:
                k8s.remove_annotation(mutated, names.UPDATE_PENDING_ANNOTATION)
                return mutated
            diffs = first_differences(incoming_spec, mutated_spec, path="spec")
            log.info("parking webhook mutations on running notebook %s/%s: %s",
                     k8s.namespace(incoming), k8s.name(incoming), diffs)
            span.add_event("updates-parked", {"diffs": json.dumps(diffs)})
            parked = k8s.deepcopy(mutated)
            parked["spec"] = k8s.deepcopy(incoming_spec)
            k8s.set_annotation(parked, names.UPDATE_PENDING_ANNOTATION,
                               json.dumps(diffs))
            return parked


def _looks_cuda(image: str) -> bool:
    lowered = image.lower()
    return any(t in lowered for t in ("cuda", "gpu", "nvidia", "rocm"))


def _is_generic_notebook_image(image: str) -> bool:
    lowered = image.lower()
    return any(t in lowered for t in ("jupyter", "notebook", "workbench")) \
        and not any(t in lowered for t in ("jax", "libtpu", "tpu"))
