"""Validating admission webhook for Notebook CRs.

Reference: odh notebook_validating_webhook.go:41-100 — denies removal of the
MLflow annotation on a running notebook (the injected env vars would outlive
the RoleBinding that authorizes them). TPU extensions: malformed TPU slice
requests are rejected at admission (instead of crash-looping a reconciler),
and the slice shape of a RUNNING notebook is immutable (resizing bounces
every worker; stop first)."""

from __future__ import annotations

from ..api import types as api
from ..cluster.errors import ApiError
from ..tpu.topology import TpuRequestError, parse_slice_request
from ..utils import k8s, names
from ..utils.config import ControllerConfig


class AdmissionDenied(ApiError):
    code = 403
    reason = "AdmissionDenied"


class NotebookValidatingWebhook:
    def __init__(self, config: ControllerConfig | None = None):
        self.config = config or ControllerConfig()

    def install(self, store) -> None:
        store.register_admission(api.KIND, self.handle)

    def handle(self, operation: str, notebook: dict, old: dict | None) -> dict:
        if operation not in ("CREATE", "UPDATE") or k8s.is_deleting(notebook):
            return notebook
        self._validate_tpu_request(notebook)
        if operation == "UPDATE" and old is not None:
            self._deny_mlflow_annotation_removal(notebook, old)
            self._deny_running_slice_resize(notebook, old)
        return notebook

    def _validate_tpu_request(self, nb: dict) -> None:
        try:
            parse_slice_request(
                k8s.get_in(nb, "metadata", "annotations", default={}))
        except TpuRequestError as exc:
            raise AdmissionDenied(f"invalid TPU request: {exc.message}") from exc

    def _deny_mlflow_annotation_removal(self, nb: dict, old: dict) -> None:
        """Reference validateMLflowAnnotationRemoval (:60-100): removing the
        annotation while running would leave MLFLOW_* env pointing at an
        instance the pod is no longer authorized for."""
        had = k8s.get_annotation(old, names.MLFLOW_INSTANCE_ANNOTATION)
        has = k8s.get_annotation(nb, names.MLFLOW_INSTANCE_ANNOTATION)
        running = k8s.get_annotation(old, names.STOP_ANNOTATION) is None
        if had and not has and running:
            raise AdmissionDenied(
                "cannot remove the MLflow annotation from a running notebook; "
                "stop it first")

    def _deny_running_slice_resize(self, nb: dict, old: dict) -> None:
        """TPU-native rule: slice topology is immutable while running — a
        resize rewrites the pod template and worker env, bouncing all workers
        mid-session. Stopping first makes the resize an explicit restart."""
        old_spec = parse_slice_request(
            k8s.get_in(old, "metadata", "annotations", default={}))
        new_spec = parse_slice_request(
            k8s.get_in(nb, "metadata", "annotations", default={}))
        running = k8s.get_annotation(old, names.STOP_ANNOTATION) is None
        if running and old_spec != new_spec:
            raise AdmissionDenied(
                f"cannot change TPU slice of a running notebook "
                f"({old_spec and old_spec.short_name} → "
                f"{new_spec and new_spec.short_name}); stop it first")
