"""Structural diff reporter for admission decisions.

The analog of the reference's go-cmp first-difference Reporter
(odh notebook_mutating_webhook.go:601-646): produces human-readable
"path: old → new" lines describing where two API objects diverge, used to
populate the ``update-pending`` annotation when webhook mutations are parked
on a running notebook."""

from __future__ import annotations

from typing import Any


def first_differences(old: Any, new: Any, path: str = "",
                      limit: int = 5) -> list[str]:
    out: list[str] = []
    _walk(old, new, path, out, limit)
    return out


def _fmt(v: Any) -> str:
    s = repr(v)
    return s if len(s) <= 120 else s[:117] + "..."


def _walk(old: Any, new: Any, path: str, out: list[str], limit: int) -> None:
    if len(out) >= limit:
        return
    if isinstance(old, dict) and isinstance(new, dict):
        for key in sorted(set(old) | set(new)):
            sub = f"{path}.{key}" if path else str(key)
            if key not in old:
                out.append(f"{sub}: <absent> → {_fmt(new[key])}")
            elif key not in new:
                out.append(f"{sub}: {_fmt(old[key])} → <removed>")
            else:
                _walk(old[key], new[key], sub, out, limit)
            if len(out) >= limit:
                return
    elif isinstance(old, list) and isinstance(new, list):
        if len(old) != len(new):
            out.append(f"{path}: len {len(old)} → {len(new)}")
            return
        for i, (a, b) in enumerate(zip(old, new)):
            _walk(a, b, f"{path}[{i}]", out, limit)
            if len(out) >= limit:
                return
    elif old != new:
        out.append(f"{path}: {_fmt(old)} → {_fmt(new)}")
