"""Structural CRD schema: typed PodSpec subset + OpenAPI v3 validator.

The reference ships an 11,650-line generated schema expanding the whole
corev1.PodSpec (config/crd/bases/kubeflow.org_notebooks.yaml), so a malformed
pod spec is rejected by the apiserver before any controller sees it. This
module is our equivalent: a hand-maintained *typed* schema for every PodSpec
field the controllers and webhooks actually read or write, with
``x-kubernetes-preserve-unknown-fields`` at the pod-spec and container levels
so user-supplied fields outside the typed subset flow through untouched
(k8s structural-schema semantics: preserve-unknown keeps unknown fields while
declared properties are still validated).

``validate_schema`` implements the subset of OpenAPI v3 structural validation
kube-apiserver applies to CRs: type checks, required, enum, pattern, items,
additionalProperties, minItems/minLength, int-or-string. No pruning — like
validation failures, unknown fields either pass (under preserve-unknown) or
are simply not checked; controllers never depend on pruning.

ClusterStore enforces these schemas generically: creating a
CustomResourceDefinition object registers its per-version schema, and every
subsequent write of that kind is validated server-side — which the HTTP
apiserver facade inherits, giving remote clients real 422 Invalid responses.
"""

from __future__ import annotations

import re
from typing import Any

# k8s resource.Quantity surface syntax — single source of truth shared
# with the webhook's parse_quantity (utils/k8s.py), so CRD validation and
# admission-time validation can never drift apart
from ..utils.k8s import QUANTITY_PATTERN  # noqa: E402,F401

_DNS1123_LABEL = r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$"

PRESERVE = "x-kubernetes-preserve-unknown-fields"


def _quantity() -> dict:
    return {"type": "string", "pattern": QUANTITY_PATTERN}


def _quantity_map() -> dict:
    return {"type": "object", "additionalProperties": _quantity()}


def env_var_schema() -> dict:
    return {
        "type": "object",
        "required": ["name"],
        "properties": {
            "name": {"type": "string", "minLength": 1},
            "value": {"type": "string"},
            "valueFrom": {"type": "object", PRESERVE: True},
        },
    }


def container_port_schema() -> dict:
    return {
        "type": "object",
        "required": ["containerPort"],
        "properties": {
            "containerPort": {"type": "integer", "minimum": 1,
                              "maximum": 65535},
            "name": {"type": "string"},
            "protocol": {"type": "string",
                         "enum": ["TCP", "UDP", "SCTP"]},
            "hostPort": {"type": "integer"},
            "hostIP": {"type": "string"},
        },
    }


def volume_mount_schema() -> dict:
    return {
        "type": "object",
        "required": ["name", "mountPath"],
        "properties": {
            "name": {"type": "string", "minLength": 1},
            "mountPath": {"type": "string", "minLength": 1},
            "subPath": {"type": "string"},
            "readOnly": {"type": "boolean"},
            "mountPropagation": {"type": "string"},
        },
    }


def resources_schema() -> dict:
    return {
        "type": "object",
        "properties": {
            "limits": _quantity_map(),
            "requests": _quantity_map(),
            "claims": {"type": "array",
                       "items": {"type": "object", PRESERVE: True}},
        },
    }


def container_schema() -> dict:
    """Typed on everything the webhook/reconcilers touch (image swap, env
    injection, sidecar validation, port defaulting — notebook.py:184-295,
    mutating.py), preserve-unknown for the rest (probes, lifecycle, ...)."""
    return {
        "type": "object",
        "required": ["name"],
        PRESERVE: True,
        "properties": {
            "name": {"type": "string", "minLength": 1,
                     "pattern": _DNS1123_LABEL},
            "image": {"type": "string"},
            "command": {"type": "array", "items": {"type": "string"}},
            "args": {"type": "array", "items": {"type": "string"}},
            "workingDir": {"type": "string"},
            "env": {"type": "array", "items": env_var_schema()},
            "envFrom": {"type": "array",
                        "items": {"type": "object", PRESERVE: True}},
            "ports": {"type": "array", "items": container_port_schema()},
            "resources": resources_schema(),
            "volumeMounts": {"type": "array", "items": volume_mount_schema()},
            "imagePullPolicy": {"type": "string",
                                "enum": ["Always", "IfNotPresent", "Never"]},
            "securityContext": {"type": "object", PRESERVE: True},
        },
    }


def volume_schema() -> dict:
    return {
        "type": "object",
        "required": ["name"],
        PRESERVE: True,  # the many volume source types stay untyped
        "properties": {
            "name": {"type": "string", "minLength": 1},
            "configMap": {"type": "object", PRESERVE: True},
            "secret": {"type": "object", PRESERVE: True},
            "emptyDir": {"type": "object", PRESERVE: True},
            "persistentVolumeClaim": {
                "type": "object",
                "required": ["claimName"],
                "properties": {"claimName": {"type": "string"},
                               "readOnly": {"type": "boolean"}},
            },
        },
    }


def pod_spec_subset() -> dict:
    """The hand-typed PodSpec OVERRIDE layer: only the fields where this
    repo's controllers/webhooks need TIGHTER validation than the generated
    expansion (quantity patterns for the sidecar-resource webhook,
    DNS-1123 container names, PVC requireds). Merged on top of the full
    mechanical expansion below."""
    return {
        "type": "object",
        "properties": {
            "containers": {"type": "array", "minItems": 1,
                           "items": container_schema()},
            "initContainers": {"items": container_schema()},
            "volumes": {"items": volume_schema()},
        },
    }


def pod_spec_schema() -> dict:
    """The full PodSpec schema the CRD carries: the mechanically-generated
    core/v1 expansion (api/podspec_gen.py — probes, lifecycle, affinity,
    topology spread, the volume-source zoo, matching the reference's
    11,650-line controller-gen output) with the hand-typed subset merged
    on top as the override layer. A mistyped ``livenessProbe.httpGet.port``
    or malformed ``affinity`` block is rejected server-side; fields beyond
    the expansion still flow through under preserve-unknown at the
    pod-spec level (future k8s fields must not brick existing CRs)."""
    from . import podspec_gen
    full = podspec_gen.pod_spec_schema_full()
    full[PRESERVE] = True
    return podspec_gen.merge_schema(full, pod_spec_subset())


# ------------------------------------------------------------------ validator


def validate_schema(value: Any, schema: dict, path: str = "") -> list[str]:
    """Validate ``value`` against an OpenAPI v3 structural schema; returns
    field-error strings shaped like apiserver field.Error messages."""
    errors: list[str] = []
    where = path or "<root>"
    expected = schema.get("type")

    if expected == "object":
        if not isinstance(value, dict):
            return [f"{where}: expected object, got {type(value).__name__}"]
        for req in schema.get("required", []):
            if req not in value:
                errors.append(f"{where}.{req}: required value")
        props = schema.get("properties", {})
        for key, sub in props.items():
            if key in value:
                errors.extend(validate_schema(value[key], sub,
                                              f"{where}.{key}"))
        extra = schema.get("additionalProperties")
        if isinstance(extra, dict):
            for key, item in value.items():
                if key not in props:
                    errors.extend(validate_schema(item, extra,
                                                  f"{where}.{key}"))
        return errors

    if expected == "array":
        if not isinstance(value, list):
            return [f"{where}: expected array, got {type(value).__name__}"]
        min_items = schema.get("minItems")
        if min_items is not None and len(value) < min_items:
            errors.append(f"{where}: must have at least {min_items} items")
        item_schema = schema.get("items")
        if item_schema:
            for i, item in enumerate(value):
                errors.extend(validate_schema(item, item_schema,
                                              f"{where}[{i}]"))
        return errors

    if expected == "string":
        if schema.get("x-kubernetes-int-or-string") and \
                isinstance(value, int) and not isinstance(value, bool):
            return []
        if not isinstance(value, str):
            return [f"{where}: expected string, got {type(value).__name__}"]
        min_len = schema.get("minLength")
        if min_len is not None and len(value) < min_len:
            errors.append(f"{where}: may not be empty")
        pattern = schema.get("pattern")
        if pattern and not re.match(pattern, value):
            errors.append(f"{where}: {value!r} does not match {pattern!r}")
    elif expected == "integer":
        if isinstance(value, bool) or not isinstance(value, int):
            return [f"{where}: expected integer, got {type(value).__name__}"]
    elif expected == "number":
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return [f"{where}: expected number, got {type(value).__name__}"]
    elif expected == "boolean":
        if not isinstance(value, bool):
            return [f"{where}: expected boolean, got {type(value).__name__}"]

    enum = schema.get("enum")
    if enum is not None and value not in enum:
        errors.append(f"{where}: unsupported value {value!r}, expected one "
                      f"of {enum}")
    minimum = schema.get("minimum")
    if minimum is not None and isinstance(value, (int, float)) \
            and not isinstance(value, bool) and value < minimum:
        errors.append(f"{where}: must be >= {minimum}")
    maximum = schema.get("maximum")
    if maximum is not None and isinstance(value, (int, float)) \
            and not isinstance(value, bool) and value > maximum:
        errors.append(f"{where}: must be <= {maximum}")
    return errors
