from .types import (API_VERSION, GROUP, KIND, install_notebook_crd,
                    new_notebook, notebook_container, validate_notebook)

__all__ = ["API_VERSION", "GROUP", "KIND", "install_notebook_crd",
           "new_notebook", "notebook_container", "validate_notebook"]
