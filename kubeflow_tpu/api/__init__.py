from .types import (API_VERSION, GROUP, KIND, SERVED_VERSIONS,
                    STORAGE_VERSION, convert_notebook, install_notebook_crd,
                    new_notebook, notebook_container, parse_version,
                    validate_notebook)

__all__ = ["API_VERSION", "GROUP", "KIND", "SERVED_VERSIONS",
           "STORAGE_VERSION", "convert_notebook", "install_notebook_crd",
           "new_notebook", "notebook_container", "parse_version",
           "validate_notebook"]
