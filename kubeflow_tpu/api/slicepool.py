"""SlicePool CRD types + the bound-slice helpers every controller shares.

No reference analog: the upstream notebook controller always cold-rolls a
StatefulSet per Notebook. A ``SlicePool`` (``tpu.kubeflow.org/v1``,
cluster-scoped like Node — pool capacity is fleet infrastructure, not
tenant state) declares a target count of **warm slices** for one
accelerator/topology: pre-rolled, pre-imaged StatefulSets held at full
replicas and Ready in the pool's materialization namespace. Notebook
creation with a matching topology *binds* a warm slice (annotation flip +
Service repoint, NotebookOS's replicas-bind-accelerators shape, PAPERS.md)
instead of provisioning one, and cull/stop *releases* it back to the pool.

Wire shape::

    apiVersion: tpu.kubeflow.org/v1
    kind: SlicePool
    metadata: {name: warm-v5e-16}
    spec:
      accelerator: v5e-16        # topology key (tpu/topology short name)
      warmReplicas: 2            # slice CAPACITY the pool maintains:
                                 # bound slices count toward it, so binds
                                 # never trigger replacement creation —
                                 # only drained (dead-capacity) slices or
                                 # a raised target are rebuilt
      namespace: tpu-slice-pools # where warm slices materialize
      weights: {team-a: 3}       # fair-share admission weight per
                                 # notebook namespace (absent → 1)
    status: {warm: 1, warming: 1, bound: 3, pending: 0}

The bound edge is annotation-carried on BOTH sides (Notebook's
``bound-slice`` ↔ StatefulSet's ``pool-bound-to``) so a controller crash
between the two patches is healed from either side on the next reconcile.
"""

from __future__ import annotations

from ..cluster.errors import InvalidError
from ..utils import k8s, names

GROUP = "tpu.kubeflow.org"
VERSION = "v1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "SlicePool"
PLURAL = "slicepools"


def new_slice_pool(name: str, accelerator: str, warm_replicas: int, *,
                   namespace: str | None = None,
                   weights: dict[str, int] | None = None) -> dict:
    """Build a SlicePool CR in wire form. ``namespace`` is where the warm
    slices materialize (defaults at reconcile time to
    config.pool_namespace); ``weights`` are the per-notebook-namespace
    fair-share admission weights."""
    pool = {
        "apiVersion": API_VERSION,
        "kind": KIND,
        "metadata": {"name": name},
        "spec": {
            "accelerator": accelerator,
            "warmReplicas": int(warm_replicas),
        },
        "status": {},
    }
    if namespace:
        pool["spec"]["namespace"] = namespace
    if weights:
        pool["spec"]["weights"] = dict(weights)
    return pool


def validate_slice_pool(pool: dict) -> None:
    """Structural + semantic validation the CRD schema/admission enforce:
    the accelerator must parse to a real slice shape (a pool of
    unprovisionable slices would warm nothing, silently, forever)."""
    from ..tpu.topology import TpuRequestError, parse_short_name
    if k8s.kind(pool) != KIND:
        raise InvalidError(f"kind must be {KIND}")
    if pool.get("apiVersion") != API_VERSION:
        raise InvalidError(f"apiVersion must be {API_VERSION}")
    if not k8s.name(pool):
        raise InvalidError("metadata.name required")
    spec = pool.get("spec") or {}
    accelerator = spec.get("accelerator")
    if not accelerator:
        raise InvalidError("spec.accelerator required")
    try:
        parse_short_name(accelerator)
    except TpuRequestError as exc:
        raise InvalidError(f"spec.accelerator: {exc}") from exc
    warm = spec.get("warmReplicas")
    if not isinstance(warm, int) or warm < 0:
        raise InvalidError("spec.warmReplicas must be a non-negative int")
    weights = spec.get("weights")
    if weights is not None:
        if not isinstance(weights, dict) or any(
                not isinstance(w, int) or w < 1 for w in weights.values()):
            raise InvalidError("spec.weights values must be ints >= 1")


def install_slicepool_crd(store) -> None:
    """Install the SlicePool CRD + admission into an apiserver — the
    sibling of api.types.install_notebook_crd."""
    from ..cluster.errors import AlreadyExistsError
    from ..deploy.manifests import slicepool_crd
    try:
        store.create(slicepool_crd())
    except AlreadyExistsError:
        pass

    def admit(operation, obj, old):
        if operation in ("CREATE", "UPDATE"):
            validate_slice_pool(obj)
        return obj
    store.register_admission(KIND, admit)


# ------------------------------------------------------ bound-slice helpers
def bound_slice_ref(notebook: dict) -> tuple[str, str] | None:
    """The (pool namespace, StatefulSet name) a Notebook is bound to, or
    None — THE predicate that flips the core/culling/repair controllers
    into bound mode."""
    raw = k8s.get_annotation(notebook, names.BOUND_SLICE_ANNOTATION)
    if not raw or "/" not in raw:
        return None
    ns, _, sts = raw.partition("/")
    return (ns, sts) if ns and sts else None


def bound_slice_pods(client, bound: tuple[str, str]) -> list[dict]:
    """The bound slice's worker pods — listed by the immutable
    ``statefulset`` selector label in the POOL namespace (bound pods live
    where the slice was warmed, not where the Notebook is)."""
    return client.list("Pod", bound[0], {"statefulset": bound[1]})


def pod_notebook_mapper(obj: dict):
    """Watch mapper: a pod carrying the notebook-name label enqueues its
    Notebook. Bound pool pods live in the pool namespace but belong to a
    Notebook elsewhere — the bound-namespace label carries the real home
    (plain label_mapper would enqueue a nonexistent pool-namespace key
    and the real Notebook would never hear about its workers)."""
    from ..controllers.manager import Request
    nb = k8s.get_label(obj, names.NOTEBOOK_NAME_LABEL)
    if not nb:
        return []
    ns = k8s.get_label(obj, names.BOUND_NAMESPACE_LABEL) or k8s.namespace(obj)
    return [Request(ns, nb)]
