"""TPUQuota CRD types — per-tenant slice ceilings the fleet scheduler
enforces at gang admission.

No reference analog: the upstream notebook controller admits every CR
and lets the cluster autoscaler sort out capacity. A ``TPUQuota``
(``tpu.kubeflow.org/v1``, cluster-scoped like SlicePool — quota is fleet
policy, not tenant state) caps the total slices one tenant namespace may
hold across every v5e topology at once: bound warm slices, elastic
training slices, and in-flight gang reservations all count against it.
The scheduler refuses (keeps Pending) any gang whose admission would
push its tenant past the cap — quota denial is an admission outcome, not
an error, so a shrunk quota never kills running work, it only gates new
grants.

Wire shape::

    apiVersion: tpu.kubeflow.org/v1
    kind: TPUQuota
    metadata: {name: team-a-quota}
    spec:
      tenant: team-a             # notebook namespace the cap applies to
      maxSlices: 4               # ceiling across ALL topologies; 0 means
                                 # the tenant may hold nothing (explicit
                                 # freeze), absent quota means unlimited

Multiple quotas for one tenant are legal (different admins, different
manifests); the scheduler takes the MINIMUM — the conservative read that
makes a duplicate-apply race harmless.
"""

from __future__ import annotations

from ..cluster.errors import InvalidError
from ..utils import k8s

GROUP = "tpu.kubeflow.org"
VERSION = "v1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "TPUQuota"
PLURAL = "tpuquotas"


def new_tpu_quota(name: str, tenant: str, max_slices: int) -> dict:
    """Build a TPUQuota CR in wire form: ``tenant`` is the notebook
    namespace the ceiling applies to, ``max_slices`` the total slices it
    may hold fleet-wide."""
    return {
        "apiVersion": API_VERSION,
        "kind": KIND,
        "metadata": {"name": name},
        "spec": {
            "tenant": str(tenant),
            "maxSlices": int(max_slices),
        },
        "status": {},
    }


def validate_tpu_quota(quota: dict) -> None:
    """Structural validation the CRD schema/admission enforce: a quota
    with no tenant binds nothing, and a negative cap has no sane
    reading (0 is the explicit freeze)."""
    if k8s.kind(quota) != KIND:
        raise InvalidError(f"kind must be {KIND}")
    if quota.get("apiVersion") != API_VERSION:
        raise InvalidError(f"apiVersion must be {API_VERSION}")
    if not k8s.name(quota):
        raise InvalidError("metadata.name required")
    spec = quota.get("spec") or {}
    tenant = spec.get("tenant")
    if not tenant or not isinstance(tenant, str):
        raise InvalidError("spec.tenant required")
    max_slices = spec.get("maxSlices")
    if not isinstance(max_slices, int) or isinstance(max_slices, bool) \
            or max_slices < 0:
        raise InvalidError("spec.maxSlices must be a non-negative int")


def install_tpuquota_crd(store) -> None:
    """Install the TPUQuota CRD + admission into an apiserver — the
    sibling of api.slicepool.install_slicepool_crd."""
    from ..cluster.errors import AlreadyExistsError
    from ..deploy.manifests import tpuquota_crd
    try:
        store.create(tpuquota_crd())
    except AlreadyExistsError:
        pass

    def admit(operation, obj, old):
        if operation in ("CREATE", "UPDATE"):
            validate_tpu_quota(obj)
        return obj
    store.register_admission(KIND, admit)


def tenant_quota(client, tenant: str) -> int | None:
    """The effective slice ceiling for ``tenant``: the MINIMUM maxSlices
    over every TPUQuota naming it, or None when no quota applies
    (unlimited). Shared by the scheduler's admission path and any
    read-only tooling so both agree on the duplicate-quota rule."""
    caps = [k8s.get_in(q, "spec", "maxSlices")
            for q in client.list(KIND)
            if k8s.get_in(q, "spec", "tenant") == tenant]
    caps = [c for c in caps if isinstance(c, int)]
    return min(caps) if caps else None
