"""Notebook CRD types.

Reference shape: components/notebook-controller/api/v1/notebook_types.go:27-88 —
``Notebook{Spec{Template{Spec: corev1.PodSpec}}, Status{Conditions,
ReadyReplicas, ContainerState}}`` with kubeflow.org/v1 as the storage version
(api/v1/notebook_types.go:67-68). The spec is deliberately a bare PodSpec
wrapper: users provide the pod template; controllers and webhooks enrich it.

This framework keeps that wire shape byte-compatible (so existing Notebook CRs
apply unchanged) and adds the TPU request as annotations
(``tpu.kubeflow.org/accelerator`` / ``tpu.kubeflow.org/topology``) rather than
spec fields, matching the reference's convention of feature-gating via
annotations (SURVEY §5 config system)."""

from __future__ import annotations

from typing import Any

from ..cluster.errors import InvalidError
from ..utils import k8s

GROUP = "kubeflow.org"
VERSION = "v1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "Notebook"

# Condition types mirrored into status from the pod (reference
# notebook_controller.go:299-374 mirrors pod conditions verbatim).
CONDITION_RUNNING = "Running"
CONDITION_WAITING = "Waiting"
CONDITION_READY = "Ready"
# TPU-native aggregate condition (new): all workers of a slice ready AND the
# JAX mesh formed — SURVEY §7 hard part "multi-host readiness semantics".
CONDITION_SLICE_READY = "SliceReady"


def new_notebook(name: str, namespace: str, *,
                 image: str = "jupyter-minimal:latest",
                 annotations: dict[str, str] | None = None,
                 labels: dict[str, str] | None = None,
                 containers: list[dict] | None = None,
                 pod_spec_extra: dict | None = None) -> dict:
    """Build a Notebook CR in wire form."""
    if containers is None:
        containers = [{"name": name, "image": image}]
    pod_spec: dict[str, Any] = {"containers": containers}
    if pod_spec_extra:
        pod_spec.update(pod_spec_extra)
    nb = {
        "apiVersion": API_VERSION,
        "kind": KIND,
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"template": {"spec": pod_spec}},
        "status": {},
    }
    if annotations:
        nb["metadata"]["annotations"] = dict(annotations)
    if labels:
        nb["metadata"]["labels"] = dict(labels)
    return nb


def notebook_pod_spec(notebook: dict) -> dict:
    return k8s.get_in(notebook, "spec", "template", "spec", default={}) or {}


def pod_spec_notebook_container(pod_spec: dict, nb_name: str) -> dict | None:
    """The notebook container convention, shared by webhook and reconcilers
    (they MUST agree to target the same container): the container named after
    the CR, else containers[0], else None (reference webhook uses the same
    convention, notebook_mutating_webhook.go:861-972)."""
    c = k8s.find_container(pod_spec, nb_name)
    if c is not None:
        return c
    containers = pod_spec.get("containers") or []
    return containers[0] if containers else None


def notebook_container(notebook: dict) -> dict | None:
    return pod_spec_notebook_container(notebook_pod_spec(notebook),
                                       k8s.name(notebook))


def validate_notebook(notebook: dict) -> None:
    """Structural validation the CRD schema would enforce."""
    if k8s.kind(notebook) != KIND:
        raise InvalidError(f"kind must be {KIND}")
    if notebook.get("apiVersion") != API_VERSION:
        raise InvalidError(f"apiVersion must be {API_VERSION}")
    if not k8s.name(notebook):
        raise InvalidError("metadata.name required")
    containers = notebook_pod_spec(notebook).get("containers")
    if not containers:
        raise InvalidError("spec.template.spec.containers must be non-empty")
    for c in containers:
        if not c.get("name") or not c.get("image"):
            raise InvalidError("containers require name and image")


def install_notebook_crd(store) -> None:
    """Install the Notebook CRD's structural schema validation into an
    apiserver (ClusterStore) — the analog of applying
    config/crd/bases/kubeflow.org_notebooks.yaml: invalid CRs are rejected at
    admission instead of crash-looping reconcilers."""
    def admit(operation, obj, old):
        if operation in ("CREATE", "UPDATE"):
            validate_notebook(obj)
        return obj
    store.register_admission(KIND, admit)


def get_condition(notebook: dict, cond_type: str) -> dict | None:
    for c in k8s.get_in(notebook, "status", "conditions", default=[]) or []:
        if c.get("type") == cond_type:
            return c
    return None
