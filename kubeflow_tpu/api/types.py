"""Notebook CRD types.

Reference shape: components/notebook-controller/api/v1/notebook_types.go:27-88 —
``Notebook{Spec{Template{Spec: corev1.PodSpec}}, Status{Conditions,
ReadyReplicas, ContainerState}}`` with kubeflow.org/v1 as the storage version
(api/v1/notebook_types.go:67-68). The spec is deliberately a bare PodSpec
wrapper: users provide the pod template; controllers and webhooks enrich it.

This framework keeps that wire shape byte-compatible (so existing Notebook CRs
apply unchanged) and adds the TPU request as annotations
(``tpu.kubeflow.org/accelerator`` / ``tpu.kubeflow.org/topology``) rather than
spec fields, matching the reference's convention of feature-gating via
annotations (SURVEY §5 config system)."""

from __future__ import annotations

from typing import Any

from ..cluster.errors import InvalidError
from ..utils import k8s

GROUP = "kubeflow.org"
VERSION = "v1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "Notebook"

# Served versions. The reference registers three schemes (v1, v1beta1,
# v1alpha1 — notebook-controller/main.go:48-56) over structurally identical
# types; v1 is the storage version (api/v1/notebook_types.go:67-68). Because
# the schemas are identical, conversion is an apiVersion rewrite (the
# reference needs no conversion webhook either).
SERVED_VERSIONS = ("v1", "v1beta1", "v1alpha1")
STORAGE_VERSION = VERSION

# Condition types mirrored into status from the pod (reference
# notebook_controller.go:299-374 mirrors pod conditions verbatim).
CONDITION_RUNNING = "Running"
CONDITION_WAITING = "Waiting"
CONDITION_READY = "Ready"
# TPU-native aggregate condition (new): all workers of a slice ready AND the
# JAX mesh formed — SURVEY §7 hard part "multi-host readiness semantics".
CONDITION_SLICE_READY = "SliceReady"
# Slice health & repair state machine (controllers/slicerepair.py), mirrored
# into status alongside SliceReady. The condition type is "Slice" + the
# state value carried in the tpu.kubeflow.org/slice-health annotation.
CONDITION_SLICE_DEGRADED = "SliceDegraded"
CONDITION_SLICE_REPAIRING = "SliceRepairing"
CONDITION_SLICE_QUARANTINED = "SliceQuarantined"
SLICE_HEALTH_STATES = ("Degraded", "Repairing", "Quarantined")
# Warm slice pools (controllers/slicepool.py): True while the notebook is
# served by a pool-owned warm slice (bound-slice annotation present); False
# with reason Migrating while a checkpoint migration is re-binding it.
CONDITION_POOL_BOUND = "PoolBound"


def new_notebook(name: str, namespace: str, *,
                 image: str = "jupyter-minimal:latest",
                 annotations: dict[str, str] | None = None,
                 labels: dict[str, str] | None = None,
                 containers: list[dict] | None = None,
                 pod_spec_extra: dict | None = None) -> dict:
    """Build a Notebook CR in wire form."""
    if containers is None:
        containers = [{"name": name, "image": image}]
    pod_spec: dict[str, Any] = {"containers": containers}
    if pod_spec_extra:
        pod_spec.update(pod_spec_extra)
    nb = {
        "apiVersion": API_VERSION,
        "kind": KIND,
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"template": {"spec": pod_spec}},
        "status": {},
    }
    if annotations:
        nb["metadata"]["annotations"] = dict(annotations)
    if labels:
        nb["metadata"]["labels"] = dict(labels)
    return nb


def notebook_pod_spec(notebook: dict) -> dict:
    return k8s.get_in(notebook, "spec", "template", "spec", default={}) or {}


def pod_spec_notebook_container(pod_spec: dict, nb_name: str) -> dict | None:
    """The notebook container convention, shared by webhook and reconcilers
    (they MUST agree to target the same container): the container named after
    the CR, else containers[0], else None (reference webhook uses the same
    convention, notebook_mutating_webhook.go:861-972)."""
    c = k8s.find_container(pod_spec, nb_name)
    if c is not None:
        return c
    containers = pod_spec.get("containers") or []
    return containers[0] if containers else None


def notebook_container(notebook: dict) -> dict | None:
    return pod_spec_notebook_container(notebook_pod_spec(notebook),
                                       k8s.name(notebook))


def parse_version(notebook: dict) -> str:
    """The CR's version ("v1"), validated against the served set."""
    api_version = notebook.get("apiVersion") or ""
    group, _, version = api_version.partition("/")
    if group != GROUP or version not in SERVED_VERSIONS:
        served = ", ".join(f"{GROUP}/{v}" for v in SERVED_VERSIONS)
        raise InvalidError(f"apiVersion must be one of: {served}")
    return version


def convert_notebook(notebook: dict, to_version: str = STORAGE_VERSION) -> dict:
    """Convert a Notebook between served versions. The hub-and-spoke
    conversion the apiserver would perform; with identical schemas this is an
    apiVersion rewrite (returns the same object if already at to_version)."""
    parse_version(notebook)
    if to_version not in SERVED_VERSIONS:
        raise InvalidError(f"unknown version {to_version!r}")
    target = f"{GROUP}/{to_version}"
    if notebook.get("apiVersion") == target:
        return notebook
    converted = k8s.deepcopy(notebook)
    converted["apiVersion"] = target
    return converted


def validate_notebook(notebook: dict) -> None:
    """Structural validation the CRD schema would enforce."""
    if k8s.kind(notebook) != KIND:
        raise InvalidError(f"kind must be {KIND}")
    parse_version(notebook)
    md = k8s.meta(notebook)
    # admission runs before the apiserver expands generateName, so an empty
    # name is valid when generateName is set (as on a real apiserver)
    if not md.get("name") and not md.get("generateName"):
        raise InvalidError("metadata.name required")
    containers = notebook_pod_spec(notebook).get("containers")
    if not isinstance(containers, list) or not containers:
        raise InvalidError("spec.template.spec.containers must be a "
                           "non-empty list")
    for c in containers:
        if not isinstance(c, dict) or not c.get("name") or not c.get("image"):
            raise InvalidError("containers require name and image")


def install_notebook_crd(store) -> None:
    """Install the Notebook CRD into an apiserver (ClusterStore) — the analog
    of applying config/crd/bases/kubeflow.org_notebooks.yaml: the CRD object
    carries the typed structural schema (api/schema.py) which the store
    enforces server-side, so a malformed pod spec is rejected at admission
    instead of crash-looping reconcilers; typed admission adds the semantic
    checks and version conversion a schema can't express."""
    from ..cluster.errors import AlreadyExistsError
    from ..deploy.manifests import notebook_crd
    try:
        store.create(notebook_crd())
    except AlreadyExistsError:
        pass

    def admit(operation, obj, old):
        if operation in ("CREATE", "UPDATE"):
            validate_notebook(obj)
            # the apiserver persists at the storage version regardless of the
            # served version the client wrote (api/v1/notebook_types.go:67-68)
            obj = convert_notebook(obj, STORAGE_VERSION)
        return obj
    store.register_admission(KIND, admit)


def get_condition(notebook: dict, cond_type: str) -> dict | None:
    for c in k8s.get_in(notebook, "status", "conditions", default=[]) or []:
        if c.get("type") == cond_type:
            return c
    return None
