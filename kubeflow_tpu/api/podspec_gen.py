"""Mechanically-generated full core/v1 PodSpec OpenAPI schema.

The reference validates the ENTIRE PodSpec server-side via an 11,650-line
generated expansion (components/notebook-controller/config/crd/bases/
kubeflow.org_notebooks.yaml, produced by controller-gen from the vendored
k8s type definitions). Our analog: this module vendors a declarative
model of the core/v1 type graph (transcribed from the public Kubernetes
API spec — field names, types, requireds, enums) and a tiny generator
assembling it into the same OpenAPI v3 structural form api/schema.py
validates. The hand-typed subset in api/schema.py stays the OVERRIDE
layer for fields the controllers actively consume (tighter patterns:
quantities, DNS-1123 names); everything else — probes, lifecycle,
affinity, topology spread, the volume-source zoo — is typed here, so a
mistyped ``livenessProbe.httpGet.port`` or a malformed ``affinity`` is a
422 at the apiserver, before any controller sees it.

Generation is deterministic pure-Python (no network, no controller-gen):
``pod_spec_schema_full()`` returns the complete schema; the CRD manifest
is regenerated via ``make manifests`` and drift-gated in CI.
"""

from __future__ import annotations

# ----------------------------------------------------------- leaf helpers
def S(**kw) -> dict:
    return {"type": "string", **kw}


def I(**kw) -> dict:  # noqa: E743 — mirrors the schema vocabulary
    return {"type": "integer", **kw}


def B() -> dict:
    return {"type": "boolean"}


def INT_OR_STR() -> dict:
    return {"type": "string", "x-kubernetes-int-or-string": True}


def ARR(items: dict, **kw) -> dict:
    return {"type": "array", "items": items, **kw}


def OBJ(properties: dict, required: list[str] | None = None, **kw) -> dict:
    out = {"type": "object", "properties": properties, **kw}
    if required:
        out["required"] = required
    return out


def STR_MAP() -> dict:
    return {"type": "object", "additionalProperties": {"type": "string"}}


def QUANTITY() -> dict:
    # the tighter QUANTITY_PATTERN lives in the override layer (schema.py)
    # for the resource maps the webhook validates; here plain int-or-string
    # matches what the apiserver's Quantity unmarshals
    return INT_OR_STR()


# ------------------------------------------------------- shared meta types
def label_selector() -> dict:
    return OBJ({
        "matchExpressions": ARR(OBJ({
            "key": S(),
            "operator": S(enum=["In", "NotIn", "Exists", "DoesNotExist"]),
            "values": ARR(S()),
        }, required=["key", "operator"])),
        "matchLabels": STR_MAP(),
    })


def local_object_reference() -> dict:
    return OBJ({"name": S()})


def key_to_path() -> dict:
    return OBJ({"key": S(), "mode": I(), "path": S()},
               required=["key", "path"])


def object_field_selector() -> dict:
    return OBJ({"apiVersion": S(), "fieldPath": S()}, required=["fieldPath"])


def resource_field_selector() -> dict:
    return OBJ({"containerName": S(), "divisor": QUANTITY(),
                "resource": S()}, required=["resource"])


# -------------------------------------------------------- container pieces
def env_var_source() -> dict:
    return OBJ({
        "configMapKeyRef": OBJ({"key": S(), "name": S(), "optional": B()},
                               required=["key"]),
        "fieldRef": object_field_selector(),
        "resourceFieldRef": resource_field_selector(),
        "secretKeyRef": OBJ({"key": S(), "name": S(), "optional": B()},
                            required=["key"]),
    })


def env_from_source() -> dict:
    return OBJ({
        "configMapRef": OBJ({"name": S(), "optional": B()}),
        "prefix": S(),
        "secretRef": OBJ({"name": S(), "optional": B()}),
    })


def exec_action() -> dict:
    return OBJ({"command": ARR(S())})


def http_get_action() -> dict:
    return OBJ({
        "host": S(),
        "httpHeaders": ARR(OBJ({"name": S(), "value": S()},
                               required=["name", "value"])),
        "path": S(),
        "port": INT_OR_STR(),
        "scheme": S(enum=["HTTP", "HTTPS"]),
    }, required=["port"])


def tcp_socket_action() -> dict:
    return OBJ({"host": S(), "port": INT_OR_STR()}, required=["port"])


def grpc_action() -> dict:
    return OBJ({"port": I(), "service": S()}, required=["port"])


def probe() -> dict:
    return OBJ({
        "exec": exec_action(),
        "failureThreshold": I(),
        "grpc": grpc_action(),
        "httpGet": http_get_action(),
        "initialDelaySeconds": I(),
        "periodSeconds": I(),
        "successThreshold": I(),
        "tcpSocket": tcp_socket_action(),
        "terminationGracePeriodSeconds": I(),
        "timeoutSeconds": I(),
    })


def lifecycle_handler() -> dict:
    return OBJ({
        "exec": exec_action(),
        "httpGet": http_get_action(),
        "sleep": OBJ({"seconds": I()}, required=["seconds"]),
        "tcpSocket": tcp_socket_action(),
    })


def lifecycle() -> dict:
    return OBJ({"postStart": lifecycle_handler(),
                "preStop": lifecycle_handler()})


def se_linux_options() -> dict:
    return OBJ({"level": S(), "role": S(), "type": S(), "user": S()})


def seccomp_profile() -> dict:
    return OBJ({"localhostProfile": S(),
                "type": S(enum=["Localhost", "RuntimeDefault",
                                "Unconfined"])}, required=["type"])


def app_armor_profile() -> dict:
    return OBJ({"localhostProfile": S(),
                "type": S(enum=["Localhost", "RuntimeDefault",
                                "Unconfined"])}, required=["type"])


def windows_options() -> dict:
    return OBJ({"gmsaCredentialSpec": S(), "gmsaCredentialSpecName": S(),
                "hostProcess": B(), "runAsUserName": S()})


def container_security_context() -> dict:
    return OBJ({
        "allowPrivilegeEscalation": B(),
        "appArmorProfile": app_armor_profile(),
        "capabilities": OBJ({"add": ARR(S()), "drop": ARR(S())}),
        "privileged": B(),
        "procMount": S(),
        "readOnlyRootFilesystem": B(),
        "runAsGroup": I(),
        "runAsNonRoot": B(),
        "runAsUser": I(),
        "seLinuxOptions": se_linux_options(),
        "seccompProfile": seccomp_profile(),
        "windowsOptions": windows_options(),
    })


def container_full() -> dict:
    """Full core/v1 Container. The override layer (api/schema.py) tightens
    name/env/ports/resources/volumeMounts on top of this."""
    return OBJ({
        "args": ARR(S()),
        "command": ARR(S()),
        "env": ARR(OBJ({"name": S(), "value": S(),
                        "valueFrom": env_var_source()}, required=["name"])),
        "envFrom": ARR(env_from_source()),
        "image": S(),
        "imagePullPolicy": S(enum=["Always", "IfNotPresent", "Never"]),
        "lifecycle": lifecycle(),
        "livenessProbe": probe(),
        "name": S(),
        "ports": ARR(OBJ({
            "containerPort": I(minimum=1, maximum=65535),
            "hostIP": S(),
            "hostPort": I(),
            "name": S(),
            "protocol": S(enum=["TCP", "UDP", "SCTP"]),
        }, required=["containerPort"])),
        "readinessProbe": probe(),
        "resizePolicy": ARR(OBJ({
            "resourceName": S(),
            "restartPolicy": S(enum=["NotRequired", "RestartContainer"]),
        }, required=["resourceName", "restartPolicy"])),
        "resources": OBJ({
            "claims": ARR(OBJ({"name": S(), "request": S()},
                              required=["name"])),
            "limits": {"type": "object",
                       "additionalProperties": QUANTITY()},
            "requests": {"type": "object",
                         "additionalProperties": QUANTITY()},
        }),
        "restartPolicy": S(),
        "securityContext": container_security_context(),
        "startupProbe": probe(),
        "stdin": B(),
        "stdinOnce": B(),
        "terminationMessagePath": S(),
        "terminationMessagePolicy": S(enum=["File",
                                            "FallbackToLogsOnError"]),
        "tty": B(),
        "volumeDevices": ARR(OBJ({"devicePath": S(), "name": S()},
                                 required=["devicePath", "name"])),
        "volumeMounts": ARR(OBJ({
            "mountPath": S(),
            "mountPropagation": S(),
            "name": S(),
            "readOnly": B(),
            "recursiveReadOnly": S(),
            "subPath": S(),
            "subPathExpr": S(),
        }, required=["mountPath", "name"])),
        "workingDir": S(),
    }, required=["name"])


# ---------------------------------------------------------------- affinity
def node_selector_requirement() -> dict:
    return OBJ({
        "key": S(),
        "operator": S(enum=["In", "NotIn", "Exists", "DoesNotExist",
                            "Gt", "Lt"]),
        "values": ARR(S()),
    }, required=["key", "operator"])


def node_selector_term() -> dict:
    return OBJ({
        "matchExpressions": ARR(node_selector_requirement()),
        "matchFields": ARR(node_selector_requirement()),
    })


def node_selector() -> dict:
    return OBJ({"nodeSelectorTerms": ARR(node_selector_term())},
               required=["nodeSelectorTerms"])


def pod_affinity_term() -> dict:
    return OBJ({
        "labelSelector": label_selector(),
        "matchLabelKeys": ARR(S()),
        "mismatchLabelKeys": ARR(S()),
        "namespaceSelector": label_selector(),
        "namespaces": ARR(S()),
        "topologyKey": S(minLength=1),
    }, required=["topologyKey"])


def weighted_pod_affinity_term() -> dict:
    return OBJ({"podAffinityTerm": pod_affinity_term(), "weight": I()},
               required=["podAffinityTerm", "weight"])


def pod_affinity() -> dict:
    return OBJ({
        "preferredDuringSchedulingIgnoredDuringExecution":
            ARR(weighted_pod_affinity_term()),
        "requiredDuringSchedulingIgnoredDuringExecution":
            ARR(pod_affinity_term()),
    })


def affinity() -> dict:
    return OBJ({
        "nodeAffinity": OBJ({
            "preferredDuringSchedulingIgnoredDuringExecution": ARR(OBJ({
                "preference": node_selector_term(),
                "weight": I(),
            }, required=["preference", "weight"])),
            "requiredDuringSchedulingIgnoredDuringExecution":
                node_selector(),
        }),
        "podAffinity": pod_affinity(),
        "podAntiAffinity": pod_affinity(),
    })


def ephemeral_container() -> dict:
    """core/v1 EphemeralContainer: EphemeralContainerCommon embeds the
    Container field set (the SCHEMA carries probes/lifecycle/ports even
    though admission rejects them on ephemeral containers — same shape the
    reference CRD expansion emits) plus ``targetContainerName``."""
    schema = container_full()
    schema["properties"]["targetContainerName"] = S()
    return schema


# ----------------------------------------------------------------- volumes
def persistent_volume_claim_spec() -> dict:
    """core/v1 PersistentVolumeClaimSpec — the payload of the ``ephemeral``
    volume source's claim template."""
    typed_ref = OBJ({"apiGroup": S(), "kind": S(), "name": S()},
                    required=["kind", "name"])
    return OBJ({
        "accessModes": ARR(S()),
        "dataSource": typed_ref,
        "dataSourceRef": OBJ({"apiGroup": S(), "kind": S(), "name": S(),
                              "namespace": S()},
                             required=["kind", "name"]),
        "resources": OBJ({
            "limits": {"type": "object", "additionalProperties": QUANTITY()},
            "requests": {"type": "object",
                         "additionalProperties": QUANTITY()},
        }),
        "selector": label_selector(),
        "storageClassName": S(),
        "volumeAttributesClassName": S(),
        "volumeMode": S(enum=["Block", "Filesystem"]),
        "volumeName": S(),
    })


def ephemeral_volume_source() -> dict:
    """core/v1 EphemeralVolumeSource: an inline PVC template. The template
    metadata is the restricted embedded form (labels/annotations etc., not
    a full ObjectMeta)."""
    return OBJ({
        "volumeClaimTemplate": OBJ({
            "metadata": OBJ({
                "annotations": STR_MAP(),
                "finalizers": ARR(S()),
                "labels": STR_MAP(),
                "name": S(),
                "namespace": S(),
            }),
            "spec": persistent_volume_claim_spec(),
        }, required=["spec"]),
    })


def cluster_trust_bundle_projection() -> dict:
    """core/v1 ClusterTrustBundleProjection (projected-volume source)."""
    return OBJ({
        "labelSelector": label_selector(),
        "name": S(),
        "optional": B(),
        "path": S(),
        "signerName": S(),
    }, required=["path"])


def downward_api_items() -> dict:
    return ARR(OBJ({
        "fieldRef": object_field_selector(),
        "mode": I(),
        "path": S(),
        "resourceFieldRef": resource_field_selector(),
    }, required=["path"]))


def volume_full() -> dict:
    """Every core/v1 volume source, fully typed — including the legacy
    cloud tail — matching the reference CRD's complete controller-gen
    expansion (kubeflow.org_notebooks.yaml)."""
    typed_sources = {
        "configMap": OBJ({"defaultMode": I(), "items": ARR(key_to_path()),
                          "name": S(), "optional": B()}),
        "secret": OBJ({"defaultMode": I(), "items": ARR(key_to_path()),
                       "optional": B(), "secretName": S()}),
        "emptyDir": OBJ({"medium": S(), "sizeLimit": QUANTITY()}),
        "hostPath": OBJ({"path": S(), "type": S()}, required=["path"]),
        "nfs": OBJ({"path": S(), "readOnly": B(), "server": S()},
                   required=["path", "server"]),
        "persistentVolumeClaim": OBJ({"claimName": S(), "readOnly": B()},
                                     required=["claimName"]),
        "downwardAPI": OBJ({"defaultMode": I(),
                            "items": downward_api_items()}),
        "projected": OBJ({
            "defaultMode": I(),
            "sources": ARR(OBJ({
                "clusterTrustBundle": cluster_trust_bundle_projection(),
                "configMap": OBJ({"items": ARR(key_to_path()), "name": S(),
                                  "optional": B()}),
                "downwardAPI": OBJ({"items": downward_api_items()}),
                "secret": OBJ({"items": ARR(key_to_path()), "name": S(),
                               "optional": B()}),
                "serviceAccountToken": OBJ({"audience": S(),
                                            "expirationSeconds": I(),
                                            "path": S()},
                                           required=["path"]),
            })),
        }),
        "csi": OBJ({"driver": S(), "fsType": S(),
                    "nodePublishSecretRef": local_object_reference(),
                    "readOnly": B(),
                    "volumeAttributes": STR_MAP()}, required=["driver"]),
        "ephemeral": ephemeral_volume_source(),
        "image": OBJ({"pullPolicy": S(enum=["Always", "IfNotPresent",
                                            "Never"]),
                      "reference": S()}),
    }
    # the legacy/out-of-tree cloud sources, typed from the public core/v1
    # spec like everything else (the reference's expansion types all of
    # them; none is consumed by the controllers)
    legacy_sources = {
        "awsElasticBlockStore": OBJ({"fsType": S(), "partition": I(),
                                     "readOnly": B(), "volumeID": S()},
                                    required=["volumeID"]),
        "azureDisk": OBJ({"cachingMode": S(), "diskName": S(),
                          "diskURI": S(), "fsType": S(), "kind": S(),
                          "readOnly": B()},
                         required=["diskName", "diskURI"]),
        "azureFile": OBJ({"readOnly": B(), "secretName": S(),
                          "shareName": S()},
                         required=["secretName", "shareName"]),
        "cephfs": OBJ({"monitors": ARR(S()), "path": S(), "readOnly": B(),
                       "secretFile": S(),
                       "secretRef": local_object_reference(), "user": S()},
                      required=["monitors"]),
        "cinder": OBJ({"fsType": S(), "readOnly": B(),
                       "secretRef": local_object_reference(),
                       "volumeID": S()}, required=["volumeID"]),
        "fc": OBJ({"fsType": S(), "lun": I(), "readOnly": B(),
                   "targetWWNs": ARR(S()), "wwids": ARR(S())}),
        "flexVolume": OBJ({"driver": S(), "fsType": S(),
                           "options": STR_MAP(), "readOnly": B(),
                           "secretRef": local_object_reference()},
                          required=["driver"]),
        "flocker": OBJ({"datasetName": S(), "datasetUUID": S()}),
        "gcePersistentDisk": OBJ({"fsType": S(), "partition": I(),
                                  "pdName": S(), "readOnly": B()},
                                 required=["pdName"]),
        "gitRepo": OBJ({"directory": S(), "repository": S(),
                        "revision": S()}, required=["repository"]),
        "glusterfs": OBJ({"endpoints": S(), "path": S(), "readOnly": B()},
                         required=["endpoints", "path"]),
        "iscsi": OBJ({"chapAuthDiscovery": B(), "chapAuthSession": B(),
                      "fsType": S(), "initiatorName": S(), "iqn": S(),
                      "iscsiInterface": S(), "lun": I(),
                      "portals": ARR(S()), "readOnly": B(),
                      "secretRef": local_object_reference(),
                      "targetPortal": S()},
                     required=["iqn", "lun", "targetPortal"]),
        "photonPersistentDisk": OBJ({"fsType": S(), "pdID": S()},
                                    required=["pdID"]),
        "portworxVolume": OBJ({"fsType": S(), "readOnly": B(),
                               "volumeID": S()}, required=["volumeID"]),
        "quobyte": OBJ({"group": S(), "readOnly": B(), "registry": S(),
                        "tenant": S(), "user": S(), "volume": S()},
                       required=["registry", "volume"]),
        "rbd": OBJ({"fsType": S(), "image": S(), "keyring": S(),
                    "monitors": ARR(S()), "pool": S(), "readOnly": B(),
                    "secretRef": local_object_reference(), "user": S()},
                   required=["image", "monitors"]),
        "scaleIO": OBJ({"fsType": S(), "gateway": S(),
                        "protectionDomain": S(), "readOnly": B(),
                        "secretRef": local_object_reference(),
                        "sslEnabled": B(), "storageMode": S(),
                        "storagePool": S(), "system": S(),
                        "volumeName": S()},
                       required=["gateway", "secretRef", "system"]),
        "storageos": OBJ({"fsType": S(), "readOnly": B(),
                          "secretRef": local_object_reference(),
                          "volumeName": S(), "volumeNamespace": S()}),
        "vsphereVolume": OBJ({"fsType": S(), "storagePolicyID": S(),
                              "storagePolicyName": S(),
                              "volumePath": S()},
                             required=["volumePath"]),
    }
    props = {"name": S(minLength=1)}
    props.update(typed_sources)
    props.update(legacy_sources)
    return OBJ(props, required=["name"])


# ---------------------------------------------------------------- pod spec
def pod_security_context() -> dict:
    return OBJ({
        "appArmorProfile": app_armor_profile(),
        "fsGroup": I(),
        "fsGroupChangePolicy": S(enum=["Always", "OnRootMismatch"]),
        "runAsGroup": I(),
        "runAsNonRoot": B(),
        "runAsUser": I(),
        "seLinuxChangePolicy": S(),
        "seLinuxOptions": se_linux_options(),
        "seccompProfile": seccomp_profile(),
        "supplementalGroups": ARR(I()),
        "supplementalGroupsPolicy": S(),
        "sysctls": ARR(OBJ({"name": S(), "value": S()},
                           required=["name", "value"])),
        "windowsOptions": windows_options(),
    })


def toleration() -> dict:
    return OBJ({
        "effect": S(enum=["NoSchedule", "PreferNoSchedule", "NoExecute"]),
        "key": S(),
        "operator": S(enum=["Exists", "Equal"]),
        "tolerationSeconds": I(),
        "value": S(),
    })


def topology_spread_constraint() -> dict:
    return OBJ({
        "labelSelector": label_selector(),
        "matchLabelKeys": ARR(S()),
        "maxSkew": I(),
        "minDomains": I(),
        "nodeAffinityPolicy": S(),
        "nodeTaintsPolicy": S(),
        "topologyKey": S(),
        "whenUnsatisfiable": S(enum=["DoNotSchedule", "ScheduleAnyway"]),
    }, required=["maxSkew", "topologyKey", "whenUnsatisfiable"])


def pod_spec_schema_full() -> dict:
    """The complete core/v1 PodSpec expansion (generator output). The
    hand-typed subset in api/schema.py deep-merges ON TOP of this."""
    container = container_full()
    return OBJ({
        "activeDeadlineSeconds": I(),
        "affinity": affinity(),
        "automountServiceAccountToken": B(),
        "containers": ARR(container, minItems=1),
        "dnsConfig": OBJ({
            "nameservers": ARR(S()),
            "options": ARR(OBJ({"name": S(), "value": S()})),
            "searches": ARR(S()),
        }),
        "dnsPolicy": S(enum=["ClusterFirst", "ClusterFirstWithHostNet",
                             "Default", "None"]),
        "enableServiceLinks": B(),
        "ephemeralContainers": ARR(ephemeral_container()),
        "hostAliases": ARR(OBJ({"hostnames": ARR(S()), "ip": S()},
                               required=["ip"])),
        "hostIPC": B(),
        "hostNetwork": B(),
        "hostPID": B(),
        "hostUsers": B(),
        "hostname": S(),
        "imagePullSecrets": ARR(local_object_reference()),
        "initContainers": ARR(container),
        "nodeName": S(),
        "nodeSelector": STR_MAP(),
        "os": OBJ({"name": S()}, required=["name"]),
        "overhead": {"type": "object", "additionalProperties": QUANTITY()},
        "preemptionPolicy": S(enum=["Never", "PreemptLowerPriority"]),
        "priority": I(),
        "priorityClassName": S(),
        "readinessGates": ARR(OBJ({"conditionType": S()},
                                  required=["conditionType"])),
        "resourceClaims": ARR(OBJ({
            "name": S(),
            "resourceClaimName": S(),
            "resourceClaimTemplateName": S(),
        }, required=["name"])),
        "restartPolicy": S(enum=["Always", "OnFailure", "Never"]),
        "runtimeClassName": S(),
        "schedulerName": S(),
        "schedulingGates": ARR(OBJ({"name": S()}, required=["name"])),
        "securityContext": pod_security_context(),
        "serviceAccount": S(),
        "serviceAccountName": S(),
        "setHostnameAsFQDN": B(),
        "shareProcessNamespace": B(),
        "subdomain": S(),
        "terminationGracePeriodSeconds": I(),
        "tolerations": ARR(toleration()),
        "topologySpreadConstraints": ARR(topology_spread_constraint()),
        "volumes": ARR(volume_full()),
    }, required=["containers"])


# ------------------------------------------------------------------- merge
def merge_schema(base: dict, override: dict) -> dict:
    """Deep-merge two OpenAPI schemas: ``override`` wins on leaves,
    ``properties``/object subtrees merge recursively, arrays' item
    schemas merge. Everything else from the base survives — this is how
    the hand-typed subset refines the generated expansion without
    re-declaring it."""
    out = dict(base)
    for key, value in override.items():
        if key in ("properties",) and isinstance(value, dict) \
                and isinstance(base.get(key), dict):
            merged = dict(base[key])
            for prop, sub in value.items():
                merged[prop] = merge_schema(merged.get(prop, {}), sub) \
                    if isinstance(sub, dict) else sub
            out[key] = merged
        elif key == "items" and isinstance(value, dict) \
                and isinstance(base.get(key), dict):
            out[key] = merge_schema(base[key], value)
        elif isinstance(value, dict) and isinstance(base.get(key), dict):
            out[key] = merge_schema(base[key], value)
        else:
            out[key] = value
    return out
