"""kubeflow_tpu — a TPU-native notebook-workbench control plane.

A from-scratch re-implementation of the capabilities of the OpenDataHub/Kubeflow
notebook subsystem (reference: red-hat-data-services/kubeflow, see SURVEY.md):
a ``Notebook`` custom resource reconciled into StatefulSets + Services, a
mutating/validating admission webhook, Gateway-API routing with an auth sidecar,
idle culling — re-designed so the workload layer is TPU-native: StatefulSets
request ``google.com/tpu`` with GKE TPU nodeSelectors, multi-host slices get a
headless Service plus ``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES`` injection,
and culling treats a slice as one atomic unit.

Package map
-----------
- ``api``         Notebook CR types + CRD manifest (reference: components/notebook-controller/api)
- ``cluster``     API-machinery: in-process apiserver, chaos client, kubelet simulator
- ``controllers`` core reconciler, culler, manager/workqueue
- ``tpu``         topology → slice provisioning math (the TPU-native core)
- ``utils``       names, metrics (Prometheus text format), config, k8s helpers
- ``webhook``     mutating/validating admission (image swap, sidecar, restart gating)
- ``runtime``     in-container side: mesh bootstrap from TPU_WORKER_* env
- ``parallel``    jax.sharding mesh/partition conventions, collectives, ring attention
- ``ops``         Pallas/XLA kernels for the hot paths of provisioned workloads
- ``models``      flagship workloads used for slice verification + benchmarking
"""

__version__ = "0.1.0"
