"""HTTP(S) apiserver facade over a ClusterStore.

Serves the real Kubernetes REST wire protocol — resource paths, list kinds,
``?watch=true`` streaming, RFC 7386 merge-patch, the ``/status`` subresource,
``Status`` error objects — backed by the in-process ClusterStore. Two roles:

- **standalone-mode apiserver**: ``python -m kubeflow_tpu.main
  --serve-apiserver 6443`` exposes the store so *other processes* (a second
  manager replica, kubectl-style tooling, the e2e suite) reconcile the same
  cluster state over real HTTP — the transport seam the reference gets from
  kube-apiserver (controllers speak HTTPS to it,
  notebook-controller/main.go:95-148);
- **transport test target**: the HttpApiClient record/replay tests run the
  full client↔server protocol (auth, conflicts, watch streaming) without
  needing a real cluster.

Admission plugins registered on the backing store run server-side, exactly
where kube-apiserver runs its webhook phase — remote clients get mutated
objects and admission denials as 4xx Status responses.
"""

from __future__ import annotations

import json
import logging
import queue
import socket
import ssl
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..utils import k8s, names
from . import faults, restmapper
from .errors import ApiError, NotFoundError
from .store import WatchEvent

log = logging.getLogger("kubeflow_tpu.apiserver")

WATCH_BOOKMARK_INTERVAL_S = 10.0


def _parse_label_selector(raw: str | None) -> dict[str, str | None] | None:
    """``key=value`` equality terms plus bare ``key`` existence terms
    (mapped to value ``None``, matching k8s.matches_labels)."""
    if not raw:
        return None
    out: dict[str, str | None] = {}
    for part in raw.split(","):
        part = part.strip()
        if "=" in part:
            key, _, val = part.partition("=")
            out[key.strip()] = val.strip()
        elif part:
            out[part] = None
    return out or None


def _status_body(code: int, reason: str, message: str) -> bytes:
    return json.dumps({
        "kind": "Status", "apiVersion": "v1", "status": "Failure",
        "message": message, "reason": reason, "code": code,
    }).encode()


class _Route:
    """A parsed request path: which mapping, namespace, name, subresource.
    ``tail`` holds the path segments AFTER the subresource — the proxy
    subresource forwards them to the backend."""

    def __init__(self, mapping: restmapper.RestMapping,
                 namespace: str | None, name: str | None,
                 subresource: str | None,
                 tail: tuple[str, ...] = ()) -> None:
        self.mapping = mapping
        self.namespace = namespace
        self.name = name
        self.subresource = subresource
        self.tail = tail


def _wire_verb(method: str, route: _Route, is_watch: bool) -> str:
    """Map a request to the client-go verb vocabulary a FaultPlan rules on."""
    if method == "GET":
        if is_watch:
            return "watch"
        return "get" if route.name else "list"
    return {"POST": "create", "PUT": "update", "PATCH": "patch",
            "DELETE": "delete"}.get(method, method.lower())


def _parse_path(path: str) -> _Route | None:
    parts = [p for p in path.split("/") if p]
    if not parts:
        return None
    if parts[0] == "api":
        if len(parts) < 3 or parts[1] != "v1":
            return None
        group, version, rest = "", "v1", parts[2:]
    elif parts[0] == "apis":
        if len(parts) < 4:
            return None
        group, version, rest = parts[1], parts[2], parts[3:]
    else:
        return None
    namespace: str | None = None
    if rest[0] == "namespaces" and len(rest) >= 3:
        # /namespaces/{ns}/{plural}... — but /api/v1/namespaces/{name} alone
        # is the Namespace resource itself
        namespace, rest = rest[1], rest[2:]
    elif rest[0] == "namespaces":
        mapping = restmapper.mapping_for_route("", "v1", "namespaces")
        name = rest[1] if len(rest) > 1 else None
        return _Route(mapping, None, name, None) if mapping else None
    plural, rest = rest[0], rest[1:]
    mapping = restmapper.mapping_for_route(group, version, plural)
    if mapping is None:
        return None
    name = rest[0] if rest else None
    subresource = rest[1] if len(rest) > 1 else None
    return _Route(mapping, namespace, name, subresource,
                  tuple(rest[2:]))


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "kubeflow-tpu-apiserver"

    # ------------------------------------------------------------- plumbing
    def log_message(self, fmt, *args):  # route through logging, not stderr
        log.debug("%s %s", self.address_string(), fmt % args)

    @property
    def store(self):
        return self.server.store  # type: ignore[attr-defined]

    def _authorized(self) -> bool:
        token = self.server.token  # type: ignore[attr-defined]
        if token is None:
            return True
        got = self.headers.get("Authorization", "")
        return got == f"Bearer {token}"

    def _send_json(self, code: int, body: dict) -> None:
        data = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        # audit BEFORE the body reaches the socket: once the client sees
        # the response it may issue its next request, and that request's
        # audit line must not be able to overtake this one (the
        # idempotency checker replays the trail in order)
        self._audit_now()
        self.wfile.write(data)

    def _send_error_status(self, code: int, reason: str, message: str,
                           retry_after_s: float | None = None) -> None:
        data = _status_body(code, reason, message)
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        if retry_after_s is not None:
            # real apiserver priority-and-fairness sends integer seconds;
            # sub-second plans still need pacing, so send the raw float
            # (HttpApiClient parses either)
            self.send_header("Retry-After", f"{retry_after_s:g}")
        self.end_headers()
        self._audit_now()  # same ordering argument as _send_json
        self.wfile.write(data)

    def _send_api_error(self, err: ApiError) -> None:
        self._send_error_status(err.code, err.reason, err.message)

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length) or b"{}")

    def send_response(self, code, message=None):  # noqa: D102 — audit tap
        self._last_status = code
        super().send_response(code, message)

    def _audit_now(self) -> None:
        """Write this request's audit line exactly once (first caller
        wins: the response senders call it pre-body, the dispatch finally
        is the catch-all)."""
        method = getattr(self, "_audit_method", None)
        if method is None or getattr(self, "_audited", True):
            return
        self._audited = True
        self._audit(method, self._audit_path)

    def _audit(self, method: str, path: str) -> None:
        """One NDJSON line per mutating request (verb, path, the resource
        NAME — for POST the server-assigned one, so retried creates are
        attributable to one object — peer, the RESPONSE status so
        denied/failed mutations are distinguishable, RFC3339 timestamp) —
        the analog of the reference test suite's optional apiserver audit
        log (odh suite_test.go:127-157). The chaos soak's idempotency
        check greps this trail: two 201s for one (path, name) would mean
        a retried create double-applied. Reads are skipped (GET/watch
        volume would drown the trail) and an audit write failure must
        never break serving."""
        audit = getattr(self.server, "audit_log", None)
        if audit is None or method == "GET":
            return
        line = json.dumps({
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "verb": method, "path": path,
            "name": getattr(self, "_audit_name", None),
            "status": getattr(self, "_last_status", None),
            "peer": self.address_string(),
        }) + "\n"
        try:
            with self.server.audit_lock:  # type: ignore[attr-defined]
                audit.write(line)
                audit.flush()
        except (OSError, ValueError) as exc:
            # disk full, or stop() closed the file under a late handler
            log.warning("audit write failed: %s", exc)

    def _dispatch(self, method: str) -> None:
        # audit bookkeeping for THIS request (handler instances are
        # per-connection, reused across keep-alive requests — reset all
        # of it): the line is written by whichever response sender runs
        # first (_audit_now before the body bytes, so a client's next
        # request can't overtake its own trail), the finally is the
        # catch-all for paths that never send a full response
        self._audit_method = method
        self._audit_path = urlparse(self.path).path
        self._audit_name = None
        self._audited = False
        latency = getattr(self.server, "latency_s", 0.0)
        if latency:
            # emulated network+processing round trip (ApiServerProxy
            # latency_s): a real apiserver is a remote process; sleeping
            # here (GIL released) is what lets concurrent clients overlap
            # their in-flight requests like they would over a real wire.
            # Watch streams are exempt below (the stream is long-lived;
            # per-frame latency is not request latency).
            if "watch" not in parse_qs(urlparse(self.path).query):
                time.sleep(latency)
        if not self._authorized():
            self._send_error_status(401, "Unauthorized", "invalid bearer token")
            return
        parsed = urlparse(self.path)
        if parsed.path in ("/healthz", "/readyz", "/livez"):
            # health endpoints are NOT exempt from wire faults (matched as
            # GET with no kind): a partitioned or dead apiserver cannot
            # answer its own readyz either, so FaultPlan.outage() must
            # fail the breaker's ping probe too, or the breaker would
            # flap closed on a clean 200 one probe interval after opening
            plan = getattr(self.server, "fault_plan", None)
            rule = plan.decide("get", None) if plan is not None else None
            if rule is not None:
                if rule.fault == faults.FAULT_LATENCY:
                    time.sleep(rule.latency_s)
                elif rule.fault == faults.FAULT_RESET:
                    self._inject_reset()
                    return
                elif rule.fault == faults.FAULT_HTTP:
                    self._send_error_status(
                        rule.status, rule.reason,
                        f"injected {rule.status} fault",
                        retry_after_s=rule.retry_after_s)
                    return
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"ok")
            return
        route = _parse_path(parsed.path)
        if route is None:
            self._send_error_status(404, "NotFound",
                                    f"unrecognized path {parsed.path}")
            return
        # ------------------------------------------------ fault injection
        # (FaultPlan, cluster/faults.py): decided per request AFTER auth
        # and routing — the plan speaks the verb/kind vocabulary — but
        # BEFORE the handler for unambiguous faults (429/5xx: the real
        # apiserver rejects those before processing). Connection resets
        # instead run the handler and truncate the response: the mutation
        # HAS applied, the client cannot know — the ambiguity retried
        # creates must disambiguate. Health endpoints stay exempt above.
        self._audit_name = route.name  # POST overwrites with the created name
        self._watch_kill_after = None
        reset_rule = None
        plan = getattr(self.server, "fault_plan", None)
        if plan is not None:
            is_watch = method == "GET" and \
                parse_qs(parsed.query).get("watch", ["false"])[-1] in \
                ("true", "1")
            verb = _wire_verb(method, route, is_watch)
            rule = plan.decide(verb, route.mapping.kind)
            if rule is not None:
                if rule.fault == faults.FAULT_LATENCY:
                    time.sleep(rule.latency_s)
                elif rule.fault == faults.FAULT_WATCH_KILL:
                    self._watch_kill_after = rule.after_s
                elif rule.fault == faults.FAULT_HTTP:
                    self._send_error_status(
                        rule.status, rule.reason,
                        f"injected {rule.status} fault",
                        retry_after_s=rule.retry_after_s)
                    return
                elif rule.fault == faults.FAULT_RESET:
                    if verb == "watch":
                        # a buffered watch stream would never terminate;
                        # reset the connect instead (same client outcome:
                        # reconnect + RV-diff resync)
                        self._inject_reset()
                        return
                    reset_rule = rule
        if route.subresource == "proxy" and method != "GET":
            # the probes this facade serves are GETs; refusing the rest
            # loudly beats misrouting them into the REST verbs. Drain
            # the unread body first: on a keep-alive connection stale
            # body bytes would be parsed as the NEXT request line.
            try:
                length = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                length = 0
            if length > 0:
                self.rfile.read(length)
            self._send_error_status(405, "MethodNotAllowed",
                                    "the service proxy forwards GET only")
            return
        query = {key: vals[-1] for key, vals in parse_qs(parsed.query).items()}
        # the proxy subresource forwards the RAW query string verbatim
        # (parse_qs collapses duplicate keys — fine for list options,
        # wrong for a passthrough)
        self._raw_query = parsed.query
        try:
            if reset_rule is not None:
                self._serve_then_reset(method, route, query)
            else:
                getattr(self, f"_handle_{method}")(route, query)
        except ApiError as err:
            self._send_api_error(err)
        except BrokenPipeError:
            raise
        except Exception as exc:  # noqa: BLE001 — surface as 500 Status
            log.exception("handler error on %s %s", method, self.path)
            self._send_error_status(500, "InternalError", str(exc))
        finally:
            # catch-all for paths that never reached a response sender
            # (broken pipe mid-handler, injected reset); _audited dedups
            self._audit_now()

    do_GET = lambda self: self._dispatch("GET")            # noqa: E731
    do_POST = lambda self: self._dispatch("POST")          # noqa: E731
    do_PUT = lambda self: self._dispatch("PUT")            # noqa: E731
    do_PATCH = lambda self: self._dispatch("PATCH")        # noqa: E731
    do_DELETE = lambda self: self._dispatch("DELETE")      # noqa: E731

    def _inject_reset(self, promised: int = 128) -> None:
        """Promise a body, deliver nothing, then RST the socket (SO_LINGER
        0 makes close() send RST, not FIN) — the LB-killed-connection
        failure mode: the client's read fails with ECONNRESET /
        IncompleteRead instead of a clean status."""
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(promised))
            self.end_headers()
            self.wfile.flush()
            self.connection.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                       struct.pack("ii", 1, 0))
        except OSError:
            pass  # peer already gone; nothing left to reset
        self.close_connection = True

    def _serve_then_reset(self, method: str, route: _Route,
                          query: dict) -> None:
        """FAULT_RESET for REST verbs: run the REAL handler with the
        response buffered, then deliver only part of it and RST the
        socket. The side effect (create/update/delete) has been applied
        server-side; the client sees a connection reset and cannot know —
        the ambiguous failure mode a retried create disambiguates via 409
        AlreadyExists + a live read."""
        import io
        real = self.wfile
        buf = io.BytesIO()
        self.wfile = buf
        try:
            getattr(self, f"_handle_{method}")(route, query)
        finally:
            self.wfile = real
        data = buf.getvalue()
        try:
            # deliver roughly half — enough that the status line usually
            # parses and the BODY truncates (IncompleteRead), sometimes
            # cutting mid-headers (BadStatusLine): both shapes occur on a
            # real wire and the client must survive both
            real.write(data[:max(len(data) // 2, 1)])
            real.flush()
            self.connection.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                       struct.pack("ii", 1, 0))
        except OSError:
            pass
        self.close_connection = True

    def _handle_service_proxy(self, route: _Route) -> None:
        """GET ``/api/v1/namespaces/{ns}/services/{name}:{port}/proxy/…``
        — the apiserver's service-proxy subresource, the path the idle
        culler's probes take in dev mode (reference:
        culling_controller.go:249-254 builds exactly this URL; the
        serving-activity prober does too, controllers/culling.py).

        Backend resolution: in this in-process cluster pods hold no real
        sockets, so the Service carries ``tpu.kubeflow.org/proxy-backend``
        annotations naming the actual listeners' base URLs (set by the
        dev composition root or a test) — the facade's analog of ready
        Endpoints. PER-PORT resolution mirrors real endpoints: the
        suffixed form ``…/proxy-backend-<port-or-name>`` wins over the
        bare key, so one multi-port notebook Service can route its
        Jupyter and model-serving ports to distinct listeners (the
        culler runs BOTH probes against the same Service). No resolvable
        annotation → 503, exactly what a real apiserver answers for a
        Service with no ready endpoints. The requested port must exist
        on the Service spec (by number or name), like the real
        subresource; the query string forwards; 3xx responses relay
        as-is (Location included) instead of being followed."""
        import urllib.error
        import urllib.request
        if route.mapping.kind != "Service":
            self._send_error_status(
                404, "NotFound",
                f"proxy subresource not supported on "
                f"{route.mapping.kind}")
            return
        name, _, port = (route.name or "").partition(":")
        svc = self.store.get("Service", route.namespace or "", name)
        ports = k8s.get_in(svc, "spec", "ports", default=[]) or []
        entry = next((p for p in ports if str(p.get("port")) == port
                      or p.get("name") == port), None) if port else None
        if port and entry is None:
            self._send_error_status(
                503, "ServiceUnavailable",
                f"no port {port!r} on service {name}")
            return
        # per-port annotation first (by the requested spelling, the
        # port's name, and its number), then the bare fallback
        candidates = [port]
        if entry is not None:
            candidates += [entry.get("name"), str(entry.get("port"))]
        keys = [f"{names.PROXY_BACKEND_ANNOTATION}-{c}"
                for c in dict.fromkeys(c for c in candidates if c)]
        keys.append(names.PROXY_BACKEND_ANNOTATION)
        backend = next((v for v in (k8s.get_annotation(svc, k)
                                    for k in keys) if v), None)
        if not backend:
            self._send_error_status(
                503, "ServiceUnavailable",
                f"service {name} has no resolvable endpoints (the "
                f"in-process facade resolves through the "
                f"{names.PROXY_BACKEND_ANNOTATION}[-<port>] annotations)")
            return
        if not backend.startswith(("http://", "https://")):
            # annotations are author-ish input (same stance as
            # k8s.parse_port): a file:// or ftp:// backend must not
            # reach urllib's non-HTTP handlers
            self._send_error_status(
                503, "ServiceUnavailable",
                f"service {name} proxy backend must be http(s), "
                f"got {backend.split(':', 1)[0]!r}")
            return
        url = backend.rstrip("/") + "/" + "/".join(route.tail)
        if self._raw_query:
            url += "?" + self._raw_query

        def relay(status: int, headers, body: bytes) -> None:
            self.send_response(status)
            self.send_header("Content-Type",
                             headers.get("Content-Type",
                                         "application/octet-stream"))
            if headers.get("Location"):  # relayed 3xx keeps its target
                self.send_header("Location", headers["Location"])
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        class _NoRedirect(urllib.request.HTTPRedirectHandler):
            # the real subresource RELAYS 3xx; following it here could
            # also walk off the annotated backend entirely
            def redirect_request(self, *args, **kwargs):
                return None

        opener = urllib.request.build_opener(_NoRedirect)
        try:
            with opener.open(url, timeout=10.0) as resp:
                relay(resp.status, resp.headers, resp.read())
        except urllib.error.HTTPError as err:
            # the backend's OWN status (errors AND unfollowed redirects)
            relay(err.code, err.headers, err.read())
        except (urllib.error.URLError, OSError) as err:
            self._send_error_status(
                502, "BadGateway",
                f"proxy to {name} failed: {err}")

    # ---------------------------------------------------------------- verbs
    def _handle_GET(self, route: _Route, query: dict) -> None:
        kind = route.mapping.kind
        if route.subresource == "proxy":
            self._handle_service_proxy(route)
            return
        if route.name:
            obj = self.store.get(kind, route.namespace or "", route.name)
            self._send_json(200, obj)
            return
        selector = _parse_label_selector(query.get("labelSelector"))
        if query.get("watch") in ("true", "1"):
            self._stream_watch(route, selector)
            return
        # chunked LIST (?limit=&continue=) + resourceVersion passthrough
        # (rv=0 is the informer cache-ack form — see ClusterStore.list_page)
        try:
            limit = int(query["limit"]) if query.get("limit") else None
        except ValueError:
            self._send_error_status(400, "BadRequest",
                                    f"invalid limit {query['limit']!r}")
            return
        pager = getattr(self.store, "list_page", None)
        if pager is not None:
            items, next_cont, list_rv = pager(
                kind, route.namespace, selector, limit=limit,
                continue_token=query.get("continue"),
                resource_version=query.get("resourceVersion"))
        else:  # wrapped store without pagination: one full page
            items, next_cont, list_rv = \
                self.store.list(kind, route.namespace, selector), None, "0"
        list_meta: dict = {"resourceVersion": list_rv}
        if next_cont:
            list_meta["continue"] = next_cont
        self._send_json(200, {
            "kind": f"{kind}List",
            "apiVersion": route.mapping.api_version,
            "metadata": list_meta,
            "items": items,
        })

    def _handle_POST(self, route: _Route, query: dict) -> None:
        obj = self._read_body()
        obj.setdefault("kind", route.mapping.kind)
        obj.setdefault("apiVersion", route.mapping.api_version)
        if route.namespace and route.mapping.namespaced:
            k8s.meta(obj).setdefault("namespace", route.namespace)
        created = self.store.create(obj)
        # the collection path carries no name; audit the server-assigned
        # one (generateName included) so the idempotency check can group
        # creates per object
        self._audit_name = k8s.name(created)
        self._send_json(201, created)

    def _handle_PUT(self, route: _Route, query: dict) -> None:
        if not route.name:
            raise NotFoundError("PUT requires a resource name")
        obj = self._read_body()
        obj.setdefault("kind", route.mapping.kind)
        obj.setdefault("apiVersion", route.mapping.api_version)
        if route.subresource == "status":
            self._send_json(200, self.store.update_status(obj))
        else:
            self._send_json(200, self.store.update(obj))

    def _handle_PATCH(self, route: _Route, query: dict) -> None:
        if not route.name:
            raise NotFoundError("PATCH requires a resource name")
        ctype = self.headers.get("Content-Type", "")
        if "merge-patch" not in ctype and "strategic-merge-patch" not in ctype:
            self._send_error_status(
                415, "UnsupportedMediaType",
                f"unsupported patch type {ctype!r}; use "
                f"application/merge-patch+json")
            return
        patch = self._read_body()
        if route.subresource == "status":
            # status-subresource semantics: only .status from the patch is
            # applied (a real apiserver ignores spec fields sent here).
            # Merge-patch never conflicts: re-merge on a racing writer, the
            # same loop store.patch runs for the main resource.
            from .errors import ConflictError
            while True:
                old = self.store.get(route.mapping.kind,
                                     route.namespace or "", route.name)
                old["status"] = k8s.json_merge_patch(
                    old.get("status") or {}, patch.get("status") or {})
                try:
                    self._send_json(200, self.store.update_status(old))
                    return
                except ConflictError:
                    continue
        self._send_json(200, self.store.patch(
            route.mapping.kind, route.namespace or "", route.name, patch))

    def _handle_DELETE(self, route: _Route, query: dict) -> None:
        if not route.name:
            raise NotFoundError("DELETE requires a resource name")
        self.store.delete(route.mapping.kind, route.namespace or "", route.name)
        self._send_json(200, {"kind": "Status", "apiVersion": "v1",
                              "status": "Success"})

    # ---------------------------------------------------------------- watch
    def _stream_watch(self, route: _Route, selector) -> None:
        """Stream watch events as newline-delimited JSON, the real watch wire
        format. The connection closes when the client goes away (detected on
        the next write — idle bookmarks bound the detection latency) or the
        server shuts down."""
        events: queue.Queue = queue.Queue()
        relay = events.put
        self.store.watch(route.mapping.kind, relay,
                         namespace=route.namespace, label_selector=selector)
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Connection", "close")
        self.end_headers()
        self.close_connection = True
        # injected watch kill (FaultPlan): close the stream after its
        # armed lifetime — the client sees EOF mid-watch and must
        # reconnect + resync by resourceVersion diff
        kill_at = None
        if getattr(self, "_watch_kill_after", None) is not None:
            kill_at = time.monotonic() + self._watch_kill_after
        try:
            while not self.server.shutting_down:  # type: ignore[attr-defined]
                timeout = WATCH_BOOKMARK_INTERVAL_S
                if kill_at is not None:
                    remaining = kill_at - time.monotonic()
                    if remaining <= 0:
                        return  # injected stream kill (finally unwatches)
                    timeout = min(timeout, remaining)
                try:
                    event: WatchEvent = events.get(timeout=timeout)
                    frame = {"type": event.type, "object": event.obj}
                except queue.Empty:
                    if kill_at is not None and time.monotonic() >= kill_at:
                        return
                    frame = {"type": "BOOKMARK", "object": {}}
                self.wfile.write(json.dumps(frame).encode() + b"\n")
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self.store.unwatch(relay)


class ApiServerProxy:
    """The HTTP front door for a ClusterStore. Optional bearer-token auth and
    TLS (certfile/keyfile) — the same knobs a real apiserver endpoint has."""

    def __init__(self, store, port: int = 0, host: str = "127.0.0.1",
                 token: str | None = None, certfile: str | None = None,
                 keyfile: str | None = None,
                 audit_log: str | None = None,
                 latency_s: float = 0.0,
                 fault_plan: "faults.FaultPlan | None" = None) -> None:
        self.store = store
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.store = store  # type: ignore[attr-defined]
        self._httpd.token = token  # type: ignore[attr-defined]
        self._httpd.shutting_down = False  # type: ignore[attr-defined]
        # programmable wire-fault seam (cluster/faults.py): per-verb/kind
        # 429/5xx/reset/watch-kill/latency — the chaos runner and soaks
        # flip this live via set_fault_plan()
        self._httpd.fault_plan = fault_plan  # type: ignore[attr-defined]
        # emulated request round-trip latency (loadtest knob: a localhost
        # facade has ~0 RTT while a production apiserver has 1-10 ms; the
        # dispatch worker-pool measurements need the real shape)
        self._httpd.latency_s = latency_s  # type: ignore[attr-defined]
        # optional mutating-request audit trail (suite_test.go:127-157
        # analog); opened append so restarts extend the trail
        self._audit_file = open(audit_log, "a") if audit_log else None
        self._httpd.audit_log = self._audit_file  # type: ignore[attr-defined]
        self._httpd.audit_lock = threading.Lock()  # type: ignore[attr-defined]
        self.scheme = "http"
        if certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile, keyfile)
            self._httpd.socket = ctx.wrap_socket(self._httpd.socket,
                                                 server_side=True)
            self.scheme = "https"
        self._thread: threading.Thread | None = None

    @property
    def fault_plan(self):
        return self._httpd.fault_plan  # type: ignore[attr-defined]

    def set_fault_plan(self, plan) -> None:
        """Swap the active FaultPlan (None = heal). Takes effect on the
        next request; in-flight watch streams keep any armed kill."""
        self._httpd.fault_plan = plan  # type: ignore[attr-defined]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"{self.scheme}://127.0.0.1:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="kubeflow-tpu-apiserver")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutting_down = True  # type: ignore[attr-defined]
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._audit_file is not None:
            # under the lock so a late handler's write either lands before
            # the close or hits the guarded ValueError path, never a race
            with self._httpd.audit_lock:  # type: ignore[attr-defined]
                self._httpd.audit_log = None  # type: ignore[attr-defined]
                self._audit_file.close()
                self._audit_file = None
