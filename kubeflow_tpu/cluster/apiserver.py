"""HTTP(S) apiserver facade over a ClusterStore.

Serves the real Kubernetes REST wire protocol — resource paths, list kinds,
``?watch=true`` streaming, RFC 7386 merge-patch, the ``/status`` subresource,
``Status`` error objects — backed by the in-process ClusterStore. Two roles:

- **standalone-mode apiserver**: ``python -m kubeflow_tpu.main
  --serve-apiserver 6443`` exposes the store so *other processes* (a second
  manager replica, kubectl-style tooling, the e2e suite) reconcile the same
  cluster state over real HTTP — the transport seam the reference gets from
  kube-apiserver (controllers speak HTTPS to it,
  notebook-controller/main.go:95-148);
- **transport test target**: the HttpApiClient record/replay tests run the
  full client↔server protocol (auth, conflicts, watch streaming) without
  needing a real cluster.

Admission plugins registered on the backing store run server-side, exactly
where kube-apiserver runs its webhook phase — remote clients get mutated
objects and admission denials as 4xx Status responses.
"""

from __future__ import annotations

import bisect
import itertools
import json
import logging
import queue
import socket
import ssl
import struct
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..utils import k8s, names, sanitizer, tracing
from . import apf as apf_mod
from . import codec, faults, restmapper
from .errors import (ApiError, ConflictError, GoneError, InvalidError,
                     NotFoundError)
from .store import EventFrame, WatchEvent, _decode_continue, _encode_continue

log = logging.getLogger("kubeflow_tpu.apiserver")

_TRACER = tracing.get_tracer("kubeflow_tpu.apiserver")

WATCH_BOOKMARK_INTERVAL_S = 10.0

#: retry budget for the status-subresource merge-PATCH re-merge loop —
#: matches ClusterStore.PATCH_MAX_RETRIES; past it the racing writer wins
#: and the client gets the 409 to reason about
STATUS_PATCH_MAX_RETRIES = 20

#: per-watcher queue depth beyond which MODIFIED frames coalesce per key
#: (latest state wins). Healthy watchers drain far below this; a stalled
#: one converges to at most one pending frame per live object — bounded
#: by fleet size, not by event rate × stall time.
WATCH_QUEUE_SOFT_LIMIT = 128
#: hard depth cap: coalescing bounds MODIFIED churn, but ADDED/DELETED
#: frames always append (edges must not be lost), so create/delete churn
#: against a stalled watcher still grows the queue — past this the
#: watcher is declared too slow and its STREAM is closed (the real
#: apiserver does the same), which is cheap now: the client reconnects
#: and resumes by resourceVersion from the watch-cache ring (sized the
#: same), or relists after 410 if it stalled past the window.
WATCH_QUEUE_HARD_LIMIT = 4096


def _frame_line(etype: str, frame: EventFrame) -> bytes:
    """One NDJSON watch frame from the shared encoding: the object bytes
    are serialized once per EVENT (EventFrame caches them); only the tiny
    type envelope is composed per watcher."""
    return b'{"type":"' + etype.encode() + b'","object":' + \
        frame.obj_bytes() + b"}\n"


def _frame_line_binary(etype: str, frame: EventFrame) -> bytes:
    """The binary-wire twin of _frame_line: a length-prefixed frame
    spliced around the event's cached binary object payload — a mixed
    fleet (JSON + binary watchers on one ring) encodes each event at
    most once per format, never per watcher."""
    return codec.frame_event(etype, frame.obj_bytes_binary())


class _WatcherQueue:
    """Bounded per-watcher frame queue with level-safe coalescing.

    ``put`` is called from the store's dispatch (never blocks the writer);
    ``get`` from the one streaming thread. Under backpressure (depth ≥
    ``soft_limit``) an incoming MODIFIED frame coalesces into the pending
    cell for the same object instead of appending — the delivery TYPE of
    the pending cell is preserved (an undelivered ADDED stays ADDED,
    carrying the newest state: level semantics, exactly what an informer
    needs) and the cell MOVES to the queue tail, keeping delivered rvs
    monotonic: an in-place replace would hand a higher-rv frame out ahead
    of earlier-queued frames of other keys, and a client whose stream
    died in between would resume PAST the undelivered ones — silently
    lost events. ADDED and DELETED frames always append, so no edge is
    lost and a DELETED is never overtaken by a stale MODIFIED (the key
    map is cleared at the delete, isolating incarnations).

    Coalescing bounds MODIFIED churn; ADDED/DELETED churn is bounded by
    the HARD cap instead: past ``hard_limit`` the queue flips
    ``overflowed`` and drops everything — the streaming thread closes the
    stream, and the client's RV-resume (or 410→relist) re-delivers
    level-safely. Memory is therefore bounded by
    max(fleet size + soft_limit, hard_limit) frames per watcher."""

    __slots__ = ("_cv", "_items", "_by_key", "_seq", "soft_limit",
                 "hard_limit", "overflowed", "coalesced", "_on_coalesce")

    def __init__(self, soft_limit: int = WATCH_QUEUE_SOFT_LIMIT,
                 hard_limit: int = WATCH_QUEUE_HARD_LIMIT,
                 on_coalesce=None) -> None:
        self._cv = sanitizer.tracked_condition(
            "apiserver.watch_queue", order=sanitizer.ORDER_WATCH,
            no_blocking=True)
        # FIFO by insertion seq; coalescing re-inserts at the tail in O(1).
        # cells: [deliver_type, frame, key, seq]
        self._items: OrderedDict = sanitizer.guarded_by(
            OrderedDict(), self._cv, "apiserver.watch_queue.items")
        self._by_key: dict = sanitizer.guarded_by(
            {}, self._cv, "apiserver.watch_queue.by_key")
        self._seq = itertools.count()
        self.soft_limit = soft_limit
        self.hard_limit = hard_limit
        self.overflowed = False
        self.coalesced = 0
        self._on_coalesce = on_coalesce

    def __len__(self) -> int:
        with self._cv:
            return len(self._items)

    def put(self, frame: EventFrame) -> None:
        key = (k8s.namespace(frame.obj), k8s.name(frame.obj))
        with self._cv:
            if self.overflowed:
                return  # stream is doomed; stop accumulating now
            if frame.type == "MODIFIED" and \
                    len(self._items) >= self.soft_limit:
                cell = self._by_key.get(key)
                if cell is not None:
                    # latest state wins; type preserved; move to tail
                    del self._items[cell[3]]
                    cell[1] = frame
                    cell[3] = next(self._seq)
                    self._items[cell[3]] = cell
                    self.coalesced += 1
                    if self._on_coalesce is not None:
                        self._on_coalesce()
                    return
            if len(self._items) >= self.hard_limit:
                # non-coalescible frame on a full queue: the watcher is
                # too slow — drop everything and flag; delivering a
                # partial stream would be worse than a clean kill, since
                # the client's reconnect re-covers it exactly once
                self.overflowed = True
                self._items.clear()
                self._by_key.clear()
                self._cv.notify()
                return
            cell = [frame.type, frame, key, next(self._seq)]
            self._items[cell[3]] = cell
            if frame.type == "DELETED":
                self._by_key.pop(key, None)
            else:
                self._by_key[key] = cell
            self._cv.notify()

    def get(self, timeout: float):
        """Next ``(deliver_type, frame)`` or ``(None, None)`` on timeout."""
        with self._cv:
            if not self._items:
                self._cv.wait(timeout)
            if not self._items:
                return None, None
            _, cell = self._items.popitem(last=False)
            if self._by_key.get(cell[2]) is cell:
                del self._by_key[cell[2]]
            return cell[0], cell[1]


#: how long an rv-gated read waits for the serve cache to catch up to the
#: requested resourceVersion before falling back to the store path (with a
#: single in-process store the cache is fed synchronously and never waits;
#: the gate exists for conformance with kube's wait-until-fresh reads)
SERVE_CACHE_FRESH_WAIT_S = 2.0


class _KindServeCache:
    """Server-side watch cache for one kind: the consistent-read-from-cache
    store kube-apiserver serves ``LIST ?resourceVersion=0`` (and rv-gated
    GETs) from, so resyncs and scrapes never touch the store's write-path
    lock.

    Fed through the store's frame relay — registered ATOMICALLY with a
    deepcopied snapshot (``snapshot_with_frames``), and every subsequent
    event applies under the store lock's rv ordering — so the cache is
    never stale relative to the store: a write's frame lands here before
    the write's lock is released. Reads therefore serve FRAME OBJECTS by
    reference (the serialize-once immutability contract) with no deepcopy
    and no store lock: the cost of a cache-served LIST is pure JSON
    encoding, and N managers' resyncs stop stampeding the write path.

    ``wait_for_rv`` is kube's wait-until-fresh gate for
    ``resourceVersion=N`` reads: block (bounded) until the cache has seen
    rv ≥ N. With the in-process store it returns immediately; a timeout
    falls back to the authoritative store path rather than erroring."""

    __slots__ = ("kind", "_cv", "objects", "rv", "_sorted", "_gen",
                 "_ready", "_pending")

    def __init__(self, store, kind: str) -> None:
        self.kind = kind
        self._cv = sanitizer.tracked_condition(
            "apiserver.serve_cache", order=sanitizer.ORDER_CACHE,
            no_blocking=True)
        self.objects: dict[tuple[str, str], dict] = {}
        self.rv = 0
        self._sorted: list | None = None
        self._gen = 0  # membership generation; bumps invalidate _sorted
        self._ready = False
        self._pending: list[EventFrame] = []
        snapshot, anchor = store.snapshot_with_frames(kind, self._on_frame)
        with self._cv:
            for obj in snapshot:
                self._apply_locked(obj, self._obj_rv(obj), deleted=False)
            # frames that raced the snapshot application queue in _pending;
            # all carry rv > anchor ≥ any snapshot rv, so applying them
            # after the snapshot preserves rv order exactly
            for frame in self._pending:
                self._apply_locked(frame.obj, frame.rv,
                                   deleted=frame.type == "DELETED")
            self._pending = []
            if anchor > self.rv:
                self.rv = anchor
            self._ready = True
            self._cv.notify_all()

    @staticmethod
    def _obj_rv(obj: dict) -> int:
        try:
            return int(k8s.get_in(obj, "metadata", "resourceVersion") or 0)
        except (TypeError, ValueError):
            return 0

    def _on_frame(self, frame: EventFrame) -> None:
        # called under the STORE lock: pure dict work under our own lock,
        # never re-enters the store (the frame-relay contract)
        with self._cv:
            if not self._ready:
                self._pending.append(frame)
                return
            self._apply_locked(frame.obj, frame.rv,
                               deleted=frame.type == "DELETED")
            self._cv.notify_all()

    def _apply_locked(self, obj: dict, rv: int, deleted: bool) -> None:
        key = (k8s.namespace(obj), k8s.name(obj))
        if deleted:
            if self.objects.pop(key, None) is not None:
                self._sorted = None  # membership changed; re-sort lazily
                self._gen += 1
        else:
            cur = self.objects.get(key)
            if cur is None or self._obj_rv(cur) <= rv:
                if cur is None:
                    self._sorted = None
                    self._gen += 1
                self.objects[key] = obj
        if rv > self.rv:
            self.rv = rv

    def wait_for_rv(self, min_rv: int,
                    timeout: float = SERVE_CACHE_FRESH_WAIT_S) -> bool:
        deadline = time.monotonic() + timeout
        with self._cv:
            while self.rv < min_rv:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
            return True

    def get(self, namespace: str, name: str) -> dict | None:
        with self._cv:
            return self.objects.get((namespace, name))

    def list_page(self, namespace: str | None, selector,
                  limit: int | None = None,
                  continue_token: str | None = None,
                  ) -> tuple[list[dict], str | None, str]:
        """Same chunking semantics (and continue-token encoding) as
        ClusterStore.list_page, served lock-free from the cache: keys in
        deterministic (namespace, name) order, objects handed out by
        reference (immutable frames — the HTTP layer encodes them
        straight to bytes, no deepcopy)."""
        start_after = (_decode_continue(continue_token)
                       if continue_token else None)
        if limit is not None and limit <= 0:
            limit = None
        # sort OUTSIDE the cv: _on_frame runs under the STORE lock and
        # needs this cv — an O(n log n) fleet-key sort held inside it
        # would stall every store write behind a cache LIST during
        # churn. The lock covers only the O(n) key snapshot; the sorted
        # list is published back iff no membership change raced it
        # (stale pairs are fine either way: the chunked-LIST contract
        # already tolerates objects created/deleted mid-walk).
        with self._cv:
            pairs = self._sorted
            list_rv = str(self.rv)
        if pairs is None:
            with self._cv:
                keys = list(self.objects)
                gen = self._gen
            keys.sort()
            pairs = keys
            with self._cv:
                if self._gen == gen:
                    self._sorted = pairs
        start = (bisect.bisect_right(pairs, start_after)
                 if start_after is not None else 0)
        out: list[dict] = []
        last_pair: tuple[str, str] | None = None
        next_token: str | None = None
        for pair in pairs[start:]:
            obj = self.objects.get(pair)  # may have raced a delete: skip
            if obj is None \
                    or (namespace is not None and pair[0] != namespace) \
                    or not k8s.matches_labels(obj, selector):
                continue
            if limit is not None and len(out) >= limit:
                next_token = _encode_continue(*last_pair)
                break
            out.append(obj)
            last_pair = pair
        return out, next_token, list_rv


def _parse_label_selector(raw: str | None) -> dict[str, str | None] | None:
    """``key=value`` equality terms plus bare ``key`` existence terms
    (mapped to value ``None``, matching k8s.matches_labels)."""
    if not raw:
        return None
    out: dict[str, str | None] = {}
    for part in raw.split(","):
        part = part.strip()
        if "=" in part:
            key, _, val = part.partition("=")
            out[key.strip()] = val.strip()
        elif part:
            out[part] = None
    return out or None


def _status_body(code: int, reason: str, message: str) -> bytes:
    return json.dumps({
        "kind": "Status", "apiVersion": "v1", "status": "Failure",
        "message": message, "reason": reason, "code": code,
    }).encode()


class _Route:
    """A parsed request path: which mapping, namespace, name, subresource.
    ``tail`` holds the path segments AFTER the subresource — the proxy
    subresource forwards them to the backend."""

    def __init__(self, mapping: restmapper.RestMapping,
                 namespace: str | None, name: str | None,
                 subresource: str | None,
                 tail: tuple[str, ...] = ()) -> None:
        self.mapping = mapping
        self.namespace = namespace
        self.name = name
        self.subresource = subresource
        self.tail = tail


def _wire_verb(method: str, route: _Route, is_watch: bool) -> str:
    """Map a request to the client-go verb vocabulary a FaultPlan rules on."""
    if method == "GET":
        if is_watch:
            return "watch"
        return "get" if route.name else "list"
    return {"POST": "create", "PUT": "update", "PATCH": "patch",
            "DELETE": "delete"}.get(method, method.lower())


def _parse_path(path: str) -> _Route | None:
    parts = [p for p in path.split("/") if p]
    if not parts:
        return None
    if parts[0] == "api":
        if len(parts) < 3 or parts[1] != "v1":
            return None
        group, version, rest = "", "v1", parts[2:]
    elif parts[0] == "apis":
        if len(parts) < 4:
            return None
        group, version, rest = parts[1], parts[2], parts[3:]
    else:
        return None
    namespace: str | None = None
    if rest[0] == "namespaces" and len(rest) >= 3:
        # /namespaces/{ns}/{plural}... — but /api/v1/namespaces/{name} alone
        # is the Namespace resource itself
        namespace, rest = rest[1], rest[2:]
    elif rest[0] == "namespaces":
        mapping = restmapper.mapping_for_route("", "v1", "namespaces")
        name = rest[1] if len(rest) > 1 else None
        return _Route(mapping, None, name, None) if mapping else None
    plural, rest = rest[0], rest[1:]
    mapping = restmapper.mapping_for_route(group, version, plural)
    if mapping is None:
        return None
    name = rest[0] if rest else None
    subresource = rest[1] if len(rest) > 1 else None
    return _Route(mapping, namespace, name, subresource,
                  tuple(rest[2:]))


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "kubeflow-tpu-apiserver"
    # keep-alive clients reuse one connection for many small requests:
    # without TCP_NODELAY, Nagle holds each response body until the peer
    # ACKs the headers (delayed ACK ≈ 40 ms) — per REQUEST, which dwarfs
    # any real apiserver RTT. Per-request connections masked this via
    # Connection: close flushing the socket.
    disable_nagle_algorithm = True

    # ------------------------------------------------------------- plumbing
    def setup(self):  # noqa: D102 — connection tracking for stop()
        super().setup()
        # register the accepted socket so stop() can shut down keep-alive
        # connections: with client-side pooling a connection outlives its
        # requests, and a "stopped" apiserver that keeps serving pooled
        # peers would be unrealistic (a real restart drops every conn)
        conns = getattr(self.server, "open_connections", None)
        if conns is not None:
            with self.server.conn_lock:  # type: ignore[attr-defined]
                conns.add(self.connection)

    def finish(self):  # noqa: D102
        conns = getattr(self.server, "open_connections", None)
        if conns is not None:
            with self.server.conn_lock:  # type: ignore[attr-defined]
                conns.discard(self.connection)
        super().finish()

    def handle_one_request(self):  # noqa: D102
        try:
            super().handle_one_request()
        except (ConnectionResetError, BrokenPipeError):
            # peer (or stop()) dropped the keep-alive connection between
            # or during requests — normal teardown, not a handler error
            # worth a socketserver stderr traceback
            self.close_connection = True

    def log_message(self, fmt, *args):  # route through logging, not stderr
        log.debug("%s %s", self.address_string(), fmt % args)

    @property
    def store(self):
        return self.server.store  # type: ignore[attr-defined]

    def _authorized(self) -> bool:
        token = self.server.token  # type: ignore[attr-defined]
        if token is None:
            return True
        got = self.headers.get("Authorization", "")
        return got == f"Bearer {token}"

    def _send_json(self, code: int, body: dict) -> None:
        """Send a success body in the NEGOTIATED encoding: binary when the
        request's Accept names the binary media type, JSON (the default
        and the debugging path) otherwise. Error Status bodies always go
        through _send_error_status as JSON — a client that cannot decode
        its error would be debugging blind."""
        if codec.accepts_binary(self.headers.get("Accept")):
            data = codec.encode(body)
            ctype = codec.BINARY_CONTENT_TYPE
        else:
            data = json.dumps(body).encode()
            ctype = "application/json"
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        # audit BEFORE the body reaches the socket: once the client sees
        # the response it may issue its next request, and that request's
        # audit line must not be able to overtake this one (the
        # idempotency checker replays the trail in order)
        self._audit_now()
        self.wfile.write(data)

    def _send_error_status(self, code: int, reason: str, message: str,
                           retry_after_s: float | None = None) -> None:
        data = _status_body(code, reason, message)
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        if retry_after_s is not None:
            # real apiserver priority-and-fairness sends integer seconds;
            # sub-second plans still need pacing, so send the raw float
            # (HttpApiClient parses either)
            self.send_header("Retry-After", f"{retry_after_s:g}")
        self.end_headers()
        self._audit_now()  # same ordering argument as _send_json
        self.wfile.write(data)

    def _send_api_error(self, err: ApiError) -> None:
        self._send_error_status(err.code, err.reason, err.message)

    def _read_body(self) -> dict:
        """Decode the request body by its Content-Type: the binary media
        type routes through the codec (a malformed binary body is a typed
        422 Status — the client treats its own failure to DECODE a binary
        response as a retryable transport error, but a body the server
        cannot parse is the sender's bug, not a wire flake); everything
        else stays on the JSON default."""
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length)
        if codec.accepts_binary(self.headers.get("Content-Type")):
            try:
                return codec.decode(raw)
            except codec.CodecError as exc:
                raise InvalidError(f"malformed binary body: {exc}") from None
        return json.loads(raw or b"{}")

    def send_response(self, code, message=None):  # noqa: D102 — audit tap
        self._last_status = code
        super().send_response(code, message)

    def _audit_now(self) -> None:
        """Write this request's audit line exactly once (first caller
        wins: the response senders call it pre-body, the dispatch finally
        is the catch-all)."""
        method = getattr(self, "_audit_method", None)
        if method is None or getattr(self, "_audited", True):
            return
        self._audited = True
        self._audit(method, self._audit_path)

    def _audit(self, method: str, path: str) -> None:
        """One NDJSON line per mutating request (verb, path, the resource
        NAME — for POST the server-assigned one, so retried creates are
        attributable to one object — peer, the RESPONSE status so
        denied/failed mutations are distinguishable, RFC3339 timestamp) —
        the analog of the reference test suite's optional apiserver audit
        log (odh suite_test.go:127-157). The chaos soak's idempotency
        check greps this trail: two 201s for one (path, name) would mean
        a retried create double-applied. Reads are skipped (GET/watch
        volume would drown the trail) and an audit write failure must
        never break serving."""
        audit = getattr(self.server, "audit_log", None)
        if audit is None or method == "GET":
            return
        line = json.dumps({
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "verb": method, "path": path,
            "name": getattr(self, "_audit_name", None),
            "status": getattr(self, "_last_status", None),
            "peer": self.address_string(),
            # the client's W3C trace id (traceparent header) — joins the
            # audit trail against traces; null when tracing is off
            "trace_id": getattr(self, "_trace_id_hex", None),
        }) + "\n"
        try:
            with self.server.audit_lock:  # type: ignore[attr-defined]
                audit.write(line)
                audit.flush()
        except (OSError, ValueError) as exc:
            # disk full, or stop() closed the file under a late handler
            log.warning("audit write failed: %s", exc)

    def _dispatch(self, method: str) -> None:
        # audit bookkeeping for THIS request (handler instances are
        # per-connection, reused across keep-alive requests — reset all
        # of it): the line is written by whichever response sender runs
        # first (_audit_now before the body bytes, so a client's next
        # request can't overtake its own trail), the finally is the
        # catch-all for paths that never send a full response
        parsed = urlparse(self.path)
        qs = parse_qs(parsed.query)  # parsed ONCE for the whole request
        # per-frontend request accounting (replicated frontends over one
        # store): the loadtest's per-frontend table reads this to show
        # the client-side endpoint spreading actually spread
        req_lock = getattr(self.server, "req_count_lock", None)
        if req_lock is not None:
            with req_lock:
                self.server.requests_total += 1  # type: ignore[attr-defined]
        self._audit_method = method
        self._audit_path = parsed.path
        self._audit_name = None
        self._audited = False
        # incoming W3C trace context: parsed whenever the CLIENT sent the
        # header — the audit trail must correlate even when this server
        # process has no recording provider of its own (the two-process
        # production shape traces the manager, not the apiserver). Untraced
        # clients send no header, so the hot path stays a dict miss;
        # malformed headers restart the trace (None).
        self._trace_id_hex = None
        remote_ctx = None
        traceparent = self.headers.get("traceparent")
        if traceparent is not None:
            remote_ctx = tracing.parse_traceparent(traceparent)
            if remote_ctx is not None:
                self._trace_id_hex = f"{remote_ctx.trace_id:032x}"
        latency = getattr(self.server, "latency_s", 0.0)
        if latency:
            # emulated network+processing round trip (ApiServerProxy
            # latency_s): a real apiserver is a remote process; sleeping
            # here (GIL released) is what lets concurrent clients overlap
            # their in-flight requests like they would over a real wire.
            # Watch streams are exempt below (the stream is long-lived;
            # per-frame latency is not request latency).
            if "watch" not in qs:
                time.sleep(latency)
        if not self._authorized():
            self._send_error_status(401, "Unauthorized", "invalid bearer token")
            return
        if parsed.path in ("/healthz", "/readyz", "/livez"):
            # health endpoints are NOT exempt from wire faults (matched as
            # GET with no kind): a partitioned or dead apiserver cannot
            # answer its own readyz either, so FaultPlan.outage() must
            # fail the breaker's ping probe too, or the breaker would
            # flap closed on a clean 200 one probe interval after opening
            plan = getattr(self.server, "fault_plan", None)
            rule = plan.decide("get", None) if plan is not None else None
            if rule is not None:
                if rule.fault == faults.FAULT_LATENCY:
                    time.sleep(rule.latency_s)
                elif rule.fault == faults.FAULT_RESET:
                    self._inject_reset()
                    return
                elif rule.fault == faults.FAULT_HTTP:
                    self._send_error_status(
                        rule.status, rule.reason,
                        f"injected {rule.status} fault",
                        retry_after_s=rule.retry_after_s)
                    return
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"ok")
            return
        route = _parse_path(parsed.path)
        if route is None:
            self._send_error_status(404, "NotFound",
                                    f"unrecognized path {parsed.path}")
            return
        # ------------------------------------------------ fault injection
        # (FaultPlan, cluster/faults.py): decided per request AFTER auth
        # and routing — the plan speaks the verb/kind vocabulary — but
        # BEFORE the handler for unambiguous faults (429/5xx: the real
        # apiserver rejects those before processing). Connection resets
        # instead run the handler and truncate the response: the mutation
        # HAS applied, the client cannot know — the ambiguity retried
        # creates must disambiguate. Health endpoints stay exempt above.
        self._audit_name = route.name  # POST overwrites with the created name
        self._watch_kill_after = None
        reset_rule = None
        is_watch = method == "GET" and \
            qs.get("watch", ["false"])[-1] in ("true", "1")
        verb = _wire_verb(method, route, is_watch)
        # ---------------------------------------- priority & fairness (APF)
        # classify → seat or queue BEFORE any handler work, as the real
        # apiserver's flow control does. Watch streams are exempt (a seat
        # held for a stream's lifetime would permanently leak concurrency;
        # their cost is bounded by the fan-out layer instead), health
        # endpoints returned above. Rejections surface as 429+Retry-After,
        # the standard flow-control path every client verb retries.
        dispatcher = getattr(self.server, "apf", None)
        apf_ticket = None
        rec = tracing.is_recording()
        # server-side root for this request, parented on the client's wire
        # span via traceparent — one trace covers client retries, APF
        # queueing, and the handler (a shared no-op context manager when
        # tracing is off, so nothing is allocated)
        with _TRACER.start_span(
                "apiserver.request",
                {"http.method": method, "k8s.verb": verb,
                 "k8s.kind": route.mapping.kind} if rec else None,
                parent=remote_ctx):
            if dispatcher is not None and not is_watch:
                try:
                    with _TRACER.start_span("apf.wait") as apf_span:
                        apf_ticket, apf_queued = dispatcher.acquire_info(
                            {"user_agent": self.headers.get("User-Agent", ""),
                             "verb": verb, "kind": route.mapping.kind})
                        if rec:
                            apf_span.set_attribute("apf.priority_level",
                                                   apf_ticket)
                            apf_span.set_attribute("apf.queued", apf_queued)
                except apf_mod.RejectedError as err:
                    self._send_error_status(429, "TooManyRequests", str(err),
                                            retry_after_s=err.retry_after_s)
                    return
            try:
                self._dispatch_admitted(method, route, parsed, qs, verb,
                                        is_watch, reset_rule)
            finally:
                if apf_ticket is not None:
                    dispatcher.release(apf_ticket)

    def _dispatch_admitted(self, method: str, route: _Route, parsed,
                           qs: dict, verb: str, is_watch: bool,
                           reset_rule) -> None:
        """The post-APF remainder of _dispatch: fault injection, routing
        guards, and the verb handler (the caller holds the APF seat)."""
        plan = getattr(self.server, "fault_plan", None)
        if plan is not None:
            rule = plan.decide(verb, route.mapping.kind)
            if rule is not None:
                if tracing.is_recording():
                    # fault provenance on the server span: a trace through
                    # an injected 503/reset shows WHY the wire call failed
                    tracing.current_span().add_event(
                        "fault-injected", {"fault": rule.fault,
                                           "verb": verb,
                                           "kind": route.mapping.kind})
                if rule.fault == faults.FAULT_LATENCY:
                    time.sleep(rule.latency_s)
                elif rule.fault == faults.FAULT_WATCH_KILL:
                    self._watch_kill_after = rule.after_s
                elif rule.fault == faults.FAULT_HTTP:
                    self._send_error_status(
                        rule.status, rule.reason,
                        f"injected {rule.status} fault",
                        retry_after_s=rule.retry_after_s)
                    return
                elif rule.fault == faults.FAULT_RESET:
                    if verb == "watch":
                        # a buffered watch stream would never terminate;
                        # reset the connect instead (same client outcome:
                        # reconnect + RV-diff resync)
                        self._inject_reset()
                        return
                    reset_rule = rule
        if route.subresource == "proxy" and method != "GET":
            # the probes this facade serves are GETs; refusing the rest
            # loudly beats misrouting them into the REST verbs. Drain
            # the unread body first: on a keep-alive connection stale
            # body bytes would be parsed as the NEXT request line.
            try:
                length = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                length = 0
            if length > 0:
                self.rfile.read(length)
            self._send_error_status(405, "MethodNotAllowed",
                                    "the service proxy forwards GET only")
            return
        query = {key: vals[-1] for key, vals in qs.items()}
        # the proxy subresource forwards the RAW query string verbatim
        # (parse_qs collapses duplicate keys — fine for list options,
        # wrong for a passthrough)
        self._raw_query = parsed.query
        try:
            with _TRACER.start_span("apiserver.handle"):
                if reset_rule is not None:
                    self._serve_then_reset(method, route, query)
                else:
                    getattr(self, f"_handle_{method}")(route, query)
        except ApiError as err:
            self._send_api_error(err)
        except BrokenPipeError:
            raise
        except Exception as exc:  # noqa: BLE001 — surface as 500 Status
            log.exception("handler error on %s %s", method, self.path)
            self._send_error_status(500, "InternalError", str(exc))
        finally:
            # catch-all for paths that never reached a response sender
            # (broken pipe mid-handler, injected reset); _audited dedups
            self._audit_now()

    do_GET = lambda self: self._dispatch("GET")            # noqa: E731
    do_POST = lambda self: self._dispatch("POST")          # noqa: E731
    do_PUT = lambda self: self._dispatch("PUT")            # noqa: E731
    do_PATCH = lambda self: self._dispatch("PATCH")        # noqa: E731
    do_DELETE = lambda self: self._dispatch("DELETE")      # noqa: E731

    def _inject_reset(self, promised: int = 128) -> None:
        """Promise a body, deliver nothing, then RST the socket (SO_LINGER
        0 makes close() send RST, not FIN) — the LB-killed-connection
        failure mode: the client's read fails with ECONNRESET /
        IncompleteRead instead of a clean status."""
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(promised))
            self.end_headers()
            self.wfile.flush()
            self.connection.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                       struct.pack("ii", 1, 0))
        except OSError:
            pass  # peer already gone; nothing left to reset
        self.close_connection = True

    def _serve_then_reset(self, method: str, route: _Route,
                          query: dict) -> None:
        """FAULT_RESET for REST verbs: run the REAL handler with the
        response buffered, then deliver only part of it and RST the
        socket. The side effect (create/update/delete) has been applied
        server-side; the client sees a connection reset and cannot know —
        the ambiguous failure mode a retried create disambiguates via 409
        AlreadyExists + a live read."""
        import io
        real = self.wfile
        buf = io.BytesIO()
        self.wfile = buf
        try:
            getattr(self, f"_handle_{method}")(route, query)
        finally:
            self.wfile = real
        data = buf.getvalue()
        try:
            # deliver roughly half — enough that the status line usually
            # parses and the BODY truncates (IncompleteRead), sometimes
            # cutting mid-headers (BadStatusLine): both shapes occur on a
            # real wire and the client must survive both
            real.write(data[:max(len(data) // 2, 1)])
            real.flush()
            self.connection.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                       struct.pack("ii", 1, 0))
        except OSError:
            pass
        self.close_connection = True

    def _handle_service_proxy(self, route: _Route) -> None:
        """GET ``/api/v1/namespaces/{ns}/services/{name}:{port}/proxy/…``
        — the apiserver's service-proxy subresource, the path the idle
        culler's probes take in dev mode (reference:
        culling_controller.go:249-254 builds exactly this URL; the
        serving-activity prober does too, controllers/culling.py).

        Backend resolution: in this in-process cluster pods hold no real
        sockets, so the Service carries ``tpu.kubeflow.org/proxy-backend``
        annotations naming the actual listeners' base URLs (set by the
        dev composition root or a test) — the facade's analog of ready
        Endpoints. PER-PORT resolution mirrors real endpoints: the
        suffixed form ``…/proxy-backend-<port-or-name>`` wins over the
        bare key, so one multi-port notebook Service can route its
        Jupyter and model-serving ports to distinct listeners (the
        culler runs BOTH probes against the same Service). No resolvable
        annotation → 503, exactly what a real apiserver answers for a
        Service with no ready endpoints. The requested port must exist
        on the Service spec (by number or name), like the real
        subresource; the query string forwards; 3xx responses relay
        as-is (Location included) instead of being followed."""
        import urllib.error
        import urllib.request
        if route.mapping.kind != "Service":
            self._send_error_status(
                404, "NotFound",
                f"proxy subresource not supported on "
                f"{route.mapping.kind}")
            return
        name, _, port = (route.name or "").partition(":")
        svc = self.store.get("Service", route.namespace or "", name)
        ports = k8s.get_in(svc, "spec", "ports", default=[]) or []
        entry = next((p for p in ports if str(p.get("port")) == port
                      or p.get("name") == port), None) if port else None
        if port and entry is None:
            self._send_error_status(
                503, "ServiceUnavailable",
                f"no port {port!r} on service {name}")
            return
        # per-port annotation first (by the requested spelling, the
        # port's name, and its number), then the bare fallback
        candidates = [port]
        if entry is not None:
            candidates += [entry.get("name"), str(entry.get("port"))]
        keys = [f"{names.PROXY_BACKEND_ANNOTATION}-{c}"
                for c in dict.fromkeys(c for c in candidates if c)]
        keys.append(names.PROXY_BACKEND_ANNOTATION)
        backend = next((v for v in (k8s.get_annotation(svc, k)
                                    for k in keys) if v), None)
        if not backend:
            self._send_error_status(
                503, "ServiceUnavailable",
                f"service {name} has no resolvable endpoints (the "
                f"in-process facade resolves through the "
                f"{names.PROXY_BACKEND_ANNOTATION}[-<port>] annotations)")
            return
        if not backend.startswith(("http://", "https://")):
            # annotations are author-ish input (same stance as
            # k8s.parse_port): a file:// or ftp:// backend must not
            # reach urllib's non-HTTP handlers
            self._send_error_status(
                503, "ServiceUnavailable",
                f"service {name} proxy backend must be http(s), "
                f"got {backend.split(':', 1)[0]!r}")
            return
        url = backend.rstrip("/") + "/" + "/".join(route.tail)
        if self._raw_query:
            url += "?" + self._raw_query

        def relay(status: int, headers, body: bytes) -> None:
            self.send_response(status)
            self.send_header("Content-Type",
                             headers.get("Content-Type",
                                         "application/octet-stream"))
            if headers.get("Location"):  # relayed 3xx keeps its target
                self.send_header("Location", headers["Location"])
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        class _NoRedirect(urllib.request.HTTPRedirectHandler):
            # the real subresource RELAYS 3xx; following it here could
            # also walk off the annotated backend entirely
            def redirect_request(self, *args, **kwargs):
                return None

        opener = urllib.request.build_opener(_NoRedirect)
        try:
            with opener.open(url, timeout=10.0) as resp:
                relay(resp.status, resp.headers, resp.read())
        except urllib.error.HTTPError as err:
            # the backend's OWN status (errors AND unfollowed redirects)
            relay(err.code, err.headers, err.read())
        except (urllib.error.URLError, OSError) as err:
            self._send_error_status(
                502, "BadGateway",
                f"proxy to {name} failed: {err}")

    # ---------------------------------------------------------------- verbs
    def _serve_cache_for(self, kind: str, rv_raw: str | None):
        """The kind's server-side watch cache when the request is rv-gated
        ('any state at least this fresh is acceptable') and the backing
        store supports the frame-relay handshake; None → store path.
        A positive rv waits until the cache is at least that fresh
        (kube's consistent-read-from-cache); a wait timeout falls back to
        the authoritative store rather than erroring."""
        if rv_raw is None or not rv_raw.isdigit():
            return None  # no rv (quorum-read semantics) → store path
        factory = getattr(self.server, "serve_cache", None)
        if factory is None:
            return None
        cache = factory(kind)
        if cache is None:
            return None
        min_rv = int(rv_raw)
        if min_rv > 0 and not cache.wait_for_rv(min_rv):
            return None
        return cache

    def _handle_GET(self, route: _Route, query: dict) -> None:
        kind = route.mapping.kind
        if route.subresource == "proxy":
            self._handle_service_proxy(route)
            return
        if route.name:
            cache = self._serve_cache_for(kind, query.get("resourceVersion"))
            if cache is not None:
                # rv-gated GET: served lock-free from the watch cache —
                # the cache is complete from birth, so a miss is an
                # authoritative NotFound, exactly like the store's
                obj = cache.get(route.namespace or "", route.name)
                if obj is None:
                    raise NotFoundError(
                        f"{kind} {route.namespace or ''}/{route.name}")
                self._send_json(200, obj)
                return
            obj = self.store.get(kind, route.namespace or "", route.name)
            self._send_json(200, obj)
            return
        selector = _parse_label_selector(query.get("labelSelector"))
        if query.get("watch") in ("true", "1"):
            self._stream_watch(route, selector, query)
            return
        # chunked LIST (?limit=&continue=) + resourceVersion passthrough
        # (rv=0 is the informer cache-ack form — see ClusterStore.list_page)
        try:
            limit = int(query["limit"]) if query.get("limit") else None
        except ValueError:
            self._send_error_status(400, "BadRequest",
                                    f"invalid limit {query['limit']!r}")
            return
        cache = self._serve_cache_for(kind, query.get("resourceVersion"))
        if cache is not None:
            # consistent read from the watch cache: rv=0 (and satisfied
            # rv≥N gates) never touch the store's write-path lock — the
            # path N managers' resyncs and the metrics scrapes ride
            items, next_cont, list_rv = cache.list_page(
                route.namespace, selector, limit=limit,
                continue_token=query.get("continue"))
            metric = getattr(self.server, "cache_list_metric", None)
            if metric is not None:
                metric.inc({"kind": kind})
        elif getattr(self.store, "list_page", None) is not None:
            items, next_cont, list_rv = self.store.list_page(
                kind, route.namespace, selector, limit=limit,
                continue_token=query.get("continue"),
                resource_version=query.get("resourceVersion"))
        else:  # wrapped store without pagination: one full page
            items, next_cont, list_rv = \
                self.store.list(kind, route.namespace, selector), None, "0"
        list_meta: dict = {"resourceVersion": list_rv}
        if next_cont:
            list_meta["continue"] = next_cont
        self._send_json(200, {
            "kind": f"{kind}List",
            "apiVersion": route.mapping.api_version,
            "metadata": list_meta,
            "items": items,
        })

    def _handle_POST(self, route: _Route, query: dict) -> None:
        obj = self._read_body()
        obj.setdefault("kind", route.mapping.kind)
        obj.setdefault("apiVersion", route.mapping.api_version)
        if route.namespace and route.mapping.namespaced:
            k8s.meta(obj).setdefault("namespace", route.namespace)
        created = self.store.create(obj)
        # the collection path carries no name; audit the server-assigned
        # one (generateName included) so the idempotency check can group
        # creates per object
        self._audit_name = k8s.name(created)
        self._send_json(201, created)

    def _handle_PUT(self, route: _Route, query: dict) -> None:
        if not route.name:
            raise NotFoundError("PUT requires a resource name")
        obj = self._read_body()
        obj.setdefault("kind", route.mapping.kind)
        obj.setdefault("apiVersion", route.mapping.api_version)
        if route.subresource == "status":
            self._send_json(200, self.store.update_status(obj))
        else:
            self._send_json(200, self.store.update(obj))

    def _handle_PATCH(self, route: _Route, query: dict) -> None:
        if not route.name:
            raise NotFoundError("PATCH requires a resource name")
        ctype = self.headers.get("Content-Type", "")
        if "merge-patch" not in ctype and "strategic-merge-patch" not in ctype:
            self._send_error_status(
                415, "UnsupportedMediaType",
                f"unsupported patch type {ctype!r}; use "
                f"application/merge-patch+json")
            return
        patch = self._read_body()
        if route.subresource == "status":
            # status-subresource semantics: only .status from the patch is
            # applied (a real apiserver ignores spec fields sent here).
            # Merge-patch re-merges on a racing writer — the same loop
            # store.patch runs for the main resource — but BOUNDED: a
            # pathological hot object (a writer livelocking every re-merge)
            # must back off and surface 409, not spin a handler thread
            # forever with the client timing out blind.
            for attempt in range(STATUS_PATCH_MAX_RETRIES):
                old = self.store.get(route.mapping.kind,
                                     route.namespace or "", route.name)
                old["status"] = k8s.json_merge_patch(
                    old.get("status") or {}, patch.get("status") or {})
                try:
                    self._send_json(200, self.store.update_status(old))
                    return
                except ConflictError:
                    time.sleep(min(0.001 * (2 ** attempt), 0.1))
            raise ConflictError(
                f"{route.mapping.kind} {route.namespace}/{route.name}: "
                f"status patch kept conflicting after "
                f"{STATUS_PATCH_MAX_RETRIES} attempts")
        self._send_json(200, self.store.patch(
            route.mapping.kind, route.namespace or "", route.name, patch))

    def _handle_DELETE(self, route: _Route, query: dict) -> None:
        if not route.name:
            raise NotFoundError("DELETE requires a resource name")
        self.store.delete(route.mapping.kind, route.namespace or "", route.name)
        self._send_json(200, {"kind": "Status", "apiVersion": "v1",
                              "status": "Success"})

    # ---------------------------------------------------------------- watch
    def _stream_watch(self, route: _Route, selector, query: dict) -> None:
        """Stream watch events as newline-delimited JSON, the real watch wire
        format. The connection closes when the client goes away (detected on
        the next write — idle bookmarks bound the detection latency) or the
        server shuts down.

        ``?resourceVersion=N`` resumes: the retained event window after N
        replays from the store's watch cache before live streaming — no
        LIST, no gap — and a window already evicted answers ``410 Gone``
        (reason Expired), the client's signal to fall back to the full
        LIST+diff resync. Frames are encoded once per event (EventFrame)
        and fanned out through a bounded, MODIFIED-coalescing per-watcher
        queue, so a slow or stalled watcher costs bounded memory and never
        slows the others. BOOKMARK frames carry the resourceVersion the
        stream is complete through — the resume anchor on an idle watch."""
        kind = route.mapping.kind
        # wire negotiation: a binary-accepting watcher gets length-prefixed
        # codec frames (cached once per event alongside the JSON bytes —
        # serialize-once fan-out holds for a mixed fleet); everyone else
        # gets the NDJSON default
        binary = codec.accepts_binary(self.headers.get("Accept"))
        encoding = "binary" if binary else "json"
        # plain attribute reads (__init__ pre-sets both to None): the
        # observability label-pin scan resolves these aliases to their
        # registered families
        fan_bytes = self.server.watch_fanout_bytes_metric
        fan_frames = self.server.watch_frames_metric

        def account(payload: bytes) -> None:
            # fan-out cost accounting per stream encoding: the bytes/event
            # ratio between the two series is the measured codec win
            if fan_bytes is not None:
                fan_bytes.inc({"encoding": encoding}, by=len(payload))
            if fan_frames is not None:
                fan_frames.inc({"encoding": encoding})

        resume_raw = query.get("resourceVersion")
        since_rv = None
        if resume_raw:
            # rv 0 included: a client whose stream anchored on an empty
            # store (list rv 0 / connect bookmark 0) resumes from 0 —
            # servable iff the kind's ring never evicted, else 410 →
            # relist, exactly like any other evicted cursor
            try:
                since_rv = int(resume_raw)
            except ValueError:
                self._send_error_status(
                    400, "BadRequest",
                    f"invalid resourceVersion {resume_raw!r}")
                return
        register = getattr(self.store, "watch_frames", None)
        legacy_q: queue.Queue | None = None
        if register is not None:

            def count_coalesce(_kind=kind):
                metric = getattr(self.server, "watch_coalesced_metric", None)
                if metric is not None:
                    metric.inc({"kind": _kind})

            frame_q = _WatcherQueue(on_coalesce=count_coalesce)
            relay = frame_q.put
            try:
                replay, stream_rv = register(
                    kind, relay, namespace=route.namespace,
                    label_selector=selector, since_rv=since_rv)
            except GoneError as err:
                self._send_api_error(err)
                return
        elif since_rv is not None:
            # wrapped store without the frame API: nothing retained to
            # replay from — a resume here would silently skip events, so
            # force the client's relist path instead
            self._send_error_status(
                410, "Expired",
                "watch cache unavailable on this store; relist")
            return
        else:
            legacy_q = queue.Queue()
            relay = legacy_q.put
            self.store.watch(kind, relay, namespace=route.namespace,
                             label_selector=selector)
            replay, stream_rv = [], 0
        queues = getattr(self.server, "active_watch_queues", None)

        def bookmark_bytes() -> bytes:
            obj = {"metadata": {"resourceVersion": str(stream_rv)}}
            if binary:
                return codec.frame_event("BOOKMARK", codec.encode(obj))
            return json.dumps({"type": "BOOKMARK", "object": obj},
                              separators=(",", ":")).encode() + b"\n"

        # the relay is registered: EVERYTHING from here on — the header
        # write included (a client that connected and instantly went away
        # raises BrokenPipeError there) — must reach the finally, or the
        # store would relay every future event of this kind into a dead
        # queue forever
        try:
            if queues is not None and legacy_q is None:
                with self.server.watch_queues_lock:  # type: ignore[attr-defined]
                    queues.add(frame_q)
            self.send_response(200)
            self.send_header("Content-Type",
                             codec.BINARY_CONTENT_TYPE if binary
                             else "application/json")
            self.send_header("Connection", "close")
            self.end_headers()
            self.close_connection = True
            # injected watch kill (FaultPlan): close the stream after its
            # armed lifetime — the client sees EOF mid-watch and must
            # reconnect (resuming from its last-delivered resourceVersion)
            kill_at = None
            if getattr(self, "_watch_kill_after", None) is not None:
                kill_at = time.monotonic() + self._watch_kill_after
            for frame in replay:
                line = (_frame_line_binary(frame.type, frame) if binary
                        else _frame_line(frame.type, frame))
                self.wfile.write(line)
                account(line)
                stream_rv = max(stream_rv, frame.rv)
            # connect-time BOOKMARK: hand the client its resume anchor
            # immediately (the real apiserver's initial-events bookmark) —
            # a stream killed while idle, before the periodic bookmark,
            # would otherwise have no cursor and pay a full relist on
            # reconnect. Sent even at rv 0: an empty store is a valid
            # anchor, not a missing one.
            connect_bookmark = bookmark_bytes()
            self.wfile.write(connect_bookmark)
            account(connect_bookmark)
            self.wfile.flush()
            while not self.server.shutting_down:  # type: ignore[attr-defined]
                timeout = WATCH_BOOKMARK_INTERVAL_S
                if kill_at is not None:
                    remaining = kill_at - time.monotonic()
                    if remaining <= 0:
                        return  # injected stream kill (finally unwatches)
                    timeout = min(timeout, remaining)
                payload = None
                if legacy_q is not None:
                    try:
                        event: WatchEvent = legacy_q.get(timeout=timeout)
                        if binary:
                            payload = codec.frame_event(
                                event.type, codec.encode(event.obj))
                        else:
                            payload = json.dumps(
                                {"type": event.type,
                                 "object": event.obj}).encode() + b"\n"
                    except queue.Empty:
                        pass
                else:
                    etype, frame = frame_q.get(timeout)
                    if frame_q.overflowed:
                        # too-slow watcher (hard cap hit on edge churn):
                        # close the stream — the client resumes by rv
                        # from the watch-cache ring, or relists on 410
                        return
                    if frame is not None:
                        payload = (_frame_line_binary(etype, frame)
                                   if binary else _frame_line(etype, frame))
                        stream_rv = max(stream_rv, frame.rv)
                if payload is None:
                    if kill_at is not None and time.monotonic() >= kill_at:
                        return
                    # idle BOOKMARK: the rv through which this stream is
                    # complete — what a client records as its resume
                    # anchor when no events are flowing
                    payload = bookmark_bytes()
                self.wfile.write(payload)
                account(payload)
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self.store.unwatch(relay)
            if queues is not None and legacy_q is None:
                with self.server.watch_queues_lock:  # type: ignore[attr-defined]
                    queues.discard(frame_q)


class ApiServerProxy:
    """The HTTP front door for a ClusterStore. Optional bearer-token auth and
    TLS (certfile/keyfile) — the same knobs a real apiserver endpoint has."""

    def __init__(self, store, port: int = 0, host: str = "127.0.0.1",
                 token: str | None = None, certfile: str | None = None,
                 keyfile: str | None = None,
                 audit_log: str | None = None,
                 latency_s: float = 0.0,
                 fault_plan: "faults.FaultPlan | None" = None,
                 apf: "apf_mod.APFDispatcher | bool | None" = None) -> None:
        self.store = store
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.store = store  # type: ignore[attr-defined]
        self._httpd.token = token  # type: ignore[attr-defined]
        self._httpd.shutting_down = False  # type: ignore[attr-defined]
        # priority & fairness (cluster/apf.py): on by default with the
        # generous default seat count — it only engages under genuine
        # overload. Pass apf=False to disable, or a configured dispatcher.
        if apf is None:
            apf = apf_mod.APFDispatcher()
        self.apf = apf or None
        self._httpd.apf = self.apf  # type: ignore[attr-defined]
        # server-side watch caches (consistent-read-from-cache): created
        # lazily per kind on the first rv-gated read; requires the
        # frame-relay handshake on the backing store
        self._serve_caches_lock = sanitizer.tracked_lock(
            "apiserver.serve_caches", order=sanitizer.ORDER_CACHE,
            no_blocking=True)
        self._serve_caches: dict[str, _KindServeCache] = sanitizer.guarded_by(
            {}, self._serve_caches_lock, "apiserver.serve_caches")
        # copy-on-write published snapshot for the lock-free read fast path
        # (the guarded master dict is only ever touched under its lock)
        self._serve_caches_ro: dict[str, _KindServeCache] = {}
        if hasattr(store, "snapshot_with_frames"):
            self._httpd.serve_cache = self._serve_cache  # type: ignore[attr-defined]
        self._httpd.cache_list_metric = None  # type: ignore[attr-defined]
        # programmable wire-fault seam (cluster/faults.py): per-verb/kind
        # 429/5xx/reset/watch-kill/latency — the chaos runner and soaks
        # flip this live via set_fault_plan()
        self._httpd.fault_plan = fault_plan  # type: ignore[attr-defined]
        # emulated request round-trip latency (loadtest knob: a localhost
        # facade has ~0 RTT while a production apiserver has 1-10 ms; the
        # dispatch worker-pool measurements need the real shape)
        self._httpd.latency_s = latency_s  # type: ignore[attr-defined]
        # serve-side watch fan-out introspection + metrics:
        # watch_queue_coalesced_total lands here via attach_metrics();
        # active_watch_queues lets tests assert a stalled watcher's queue
        # stays bounded while coalescing
        self._httpd.watch_coalesced_metric = None  # type: ignore[attr-defined]
        self._httpd.watch_fanout_bytes_metric = None  # type: ignore[attr-defined]
        self._httpd.watch_frames_metric = None  # type: ignore[attr-defined]
        # per-frontend request counter (leaf lock: taken for a single
        # increment, nothing acquired under it)
        self._httpd.requests_total = 0  # type: ignore[attr-defined]
        self._httpd.req_count_lock = sanitizer.tracked_lock(  # type: ignore[attr-defined]
            "apiserver.req_count", order=sanitizer.ORDER_LEAF,
            no_blocking=True)
        self._httpd.watch_queues_lock = sanitizer.tracked_lock(  # type: ignore[attr-defined]
            "apiserver.watch_queues", order=sanitizer.ORDER_WATCH,
            no_blocking=True)
        self._httpd.active_watch_queues = sanitizer.guarded_by(  # type: ignore[attr-defined]
            set(), self._httpd.watch_queues_lock,  # type: ignore[attr-defined]
            "apiserver.active_watch_queues")
        # accepted sockets, so stop() tears down keep-alive connections
        # (pooled clients would otherwise keep talking to a "stopped"
        # apiserver through handler threads that survive shutdown())
        self._httpd.open_connections = set()  # type: ignore[attr-defined]
        self._httpd.conn_lock = sanitizer.tracked_lock(  # type: ignore[attr-defined]
            "apiserver.conns", order=sanitizer.ORDER_WATCH,
            no_blocking=True)
        # optional mutating-request audit trail (suite_test.go:127-157
        # analog); opened append so restarts extend the trail
        self._audit_file = open(audit_log, "a") if audit_log else None
        self._httpd.audit_log = self._audit_file  # type: ignore[attr-defined]
        self._httpd.audit_lock = sanitizer.tracked_lock(  # type: ignore[attr-defined]
            "apiserver.audit", order=sanitizer.ORDER_WATCH)
        self.scheme = "http"
        if certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile, keyfile)
            self._httpd.socket = ctx.wrap_socket(self._httpd.socket,
                                                 server_side=True)
            self.scheme = "https"
        self._thread: threading.Thread | None = None

    def _serve_cache(self, kind: str) -> "_KindServeCache | None":
        """Get-or-create the kind's server-side watch cache (the
        consistent-read store for rv-gated reads)."""
        cache = self._serve_caches_ro.get(kind)
        if cache is not None:
            return cache
        # Build the candidate OUTSIDE the registry lock: _KindServeCache's
        # __init__ performs the snapshot_with_frames handshake, which takes
        # the STORE lock — holding the cache-tier registry lock across a
        # store-tier acquisition inverts the declared store→cache order
        # (and serialized every first-read of a new kind behind one store
        # snapshot). Losing a creation race costs one throwaway snapshot.
        candidate = _KindServeCache(self.store, kind)
        with self._serve_caches_lock:
            cache = self._serve_caches.get(kind)
            if cache is None:
                cache = self._serve_caches[kind] = candidate
                self._serve_caches_ro = dict(self._serve_caches)
        if cache is not candidate:
            self.store.unwatch(candidate._on_frame)
        return cache

    def attach_metrics(self, registry) -> None:
        """Register the server-side watch fan-out counter, the APF flow
        control family, the cache-served LIST counter, and pass the
        registry down to the backing store (watch-cache evictions + LIST
        lock-hold) — the loadtest attaches its controller registry here so
        the whole watch/read path is measured in one exposition."""
        self._httpd.watch_coalesced_metric = registry.counter(  # type: ignore[attr-defined]
            "watch_queue_coalesced_total",
            "MODIFIED watch frames coalesced per key in a backpressured "
            "per-watcher queue (latest state wins), by kind.")
        self._httpd.cache_list_metric = registry.counter(  # type: ignore[attr-defined]
            "apiserver_cache_lists_total",
            "LISTs served lock-free from the server-side watch cache "
            "(rv-gated consistent reads), by kind — the store-lock "
            "traffic the consistent-read path removed.")
        self._httpd.watch_fanout_bytes_metric = registry.counter(  # type: ignore[attr-defined]
            "watch_fanout_bytes_total",
            "Watch-stream bytes written, by wire encoding — the "
            "bytes/event ratio between the binary and json series is the "
            "measured codec win the negotiation is judged by.")
        self._httpd.watch_frames_metric = registry.counter(  # type: ignore[attr-defined]
            "watch_frames_sent_total",
            "Watch frames written (events, replays, and bookmarks), by "
            "wire encoding — the denominator for "
            "watch_fanout_bytes_total's bytes/event ratio.")
        if self.apf is not None:
            self.apf.attach_metrics(registry)
        if hasattr(self.store, "attach_metrics"):
            self.store.attach_metrics(registry)

    @property
    def requests_served(self) -> int:
        """Total HTTP requests this frontend dispatched (watch connects
        included) — the per-frontend load-spread number the replicated
        soak tables report."""
        with self._httpd.req_count_lock:  # type: ignore[attr-defined]
            return self._httpd.requests_total  # type: ignore[attr-defined]

    @property
    def active_watch_queues(self) -> list:
        """Snapshot of the live per-watcher frame queues (introspection
        for the bounded-backpressure tests)."""
        with self._httpd.watch_queues_lock:  # type: ignore[attr-defined]
            return list(self._httpd.active_watch_queues)  # type: ignore[attr-defined]

    @property
    def fault_plan(self):
        return self._httpd.fault_plan  # type: ignore[attr-defined]

    def set_fault_plan(self, plan) -> None:
        """Swap the active FaultPlan (None = heal). Takes effect on the
        next request; in-flight watch streams keep any armed kill."""
        self._httpd.fault_plan = plan  # type: ignore[attr-defined]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"{self.scheme}://127.0.0.1:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="kubeflow-tpu-apiserver")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutting_down = True  # type: ignore[attr-defined]
        self._httpd.shutdown()
        self._httpd.server_close()
        # drop every live connection: handler threads unblock on EOF and
        # exit; pooled clients see the close and reconnect (getting ECONNREFUSED
        # until a restart) — real apiserver restart semantics
        with self._httpd.conn_lock:  # type: ignore[attr-defined]
            open_conns = list(self._httpd.open_connections)  # type: ignore[attr-defined]
        for sock in open_conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._audit_file is not None:
            # under the lock so a late handler's write either lands before
            # the close or hits the guarded ValueError path, never a race
            with self._httpd.audit_lock:  # type: ignore[attr-defined]
                self._httpd.audit_log = None  # type: ignore[attr-defined]
                self._audit_file.close()
                self._audit_file = None
