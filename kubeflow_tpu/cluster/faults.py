"""Wire-level fault plans for the apiserver facade.

The reference's chaos tooling injects at two seams: in-process client
wrappers (sdk.NewChaosClient, odh chaostests/chaos_test.go:42-54) and the
cluster network (the ChaosExperiment CRs under chaos/experiments). The
in-process seam lives in ``cluster/chaos.py``; this module is the *wire*
seam — a ``FaultPlan`` handed to ``ApiServerProxy`` makes the facade
misbehave exactly the way a stressed or partitioned kube-apiserver does:

- ``429 Too Many Requests`` with a ``Retry-After`` header (apiserver
  priority-and-fairness rejecting the request before processing it);
- ``500``/``503`` Status responses (overloaded or restarting apiserver);
- connection reset mid-body (LB killed the stream; the client saw headers
  but the body truncates — the *ambiguous* failure mode for mutations);
- watch-stream kills after a configurable lifetime (the drop that forces
  the client's resourceVersion-diff resync);
- latency spikes (slow etcd / fsync stalls).

Faults are decided per request from a seeded RNG, so a given plan + seed
replays the same fault sequence — the property the chaos suite's
reconvergence assertions depend on. Rules match on verb and kind; the
first rule that fires wins. Every injected fault is counted per
(fault, verb) so soaks can assert the plan actually fired.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field

from ..utils import sanitizer

#: the wire verbs a rule can match (client-go's request verbs; ``watch``
#: is a GET with ``?watch=true``, ``list`` a GET without a resource name)
VERBS = frozenset({"get", "list", "create", "update", "patch", "delete",
                   "watch"})
#: mutation verbs — what the uniform() convenience keeps reset faults on
MUTATING_VERBS = frozenset({"create", "update", "patch", "delete"})

FAULT_HTTP = "http"            # a Status error response (429/500/503/…)
FAULT_RESET = "reset"          # connection reset mid-body
FAULT_LATENCY = "latency"      # added per-request latency
FAULT_WATCH_KILL = "watch_kill"  # kill the watch stream after after_s
FAULTS = frozenset({FAULT_HTTP, FAULT_RESET, FAULT_LATENCY,
                    FAULT_WATCH_KILL})

_REASON_BY_STATUS = {429: "TooManyRequests", 500: "InternalError",
                     503: "ServiceUnavailable"}


@dataclass(frozen=True)
class FaultRule:
    """One match-and-inject rule. ``verbs``/``kinds`` of ``None`` match
    everything (watch_kill rules only ever fire on the watch verb)."""

    fault: str                        # one of FAULTS
    rate: float                       # probability in [0, 1]
    verbs: frozenset[str] | None = None
    kinds: frozenset[str] | None = None
    status: int = 503                 # FAULT_HTTP: the wire status
    retry_after_s: float | None = None  # FAULT_HTTP: Retry-After header
    latency_s: float = 0.0            # FAULT_LATENCY: added delay
    after_s: float = 0.0              # FAULT_WATCH_KILL: stream lifetime
    times: int | None = None          # fire at most N times (None = ∞) —
    #                                   deterministic burst scripting
    #                                   ("first 3 requests 429, then heal")

    def __post_init__(self) -> None:
        if self.fault not in FAULTS:
            raise ValueError(f"unknown fault {self.fault!r}; "
                             f"expected one of {sorted(FAULTS)}")
        if self.verbs is not None:
            unknown = set(self.verbs) - VERBS
            if unknown:
                raise ValueError(f"unknown verbs {sorted(unknown)}; "
                                 f"expected a subset of {sorted(VERBS)}")
        if not 0.0 <= self.rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {self.rate}")

    @property
    def reason(self) -> str:
        return _REASON_BY_STATUS.get(self.status, "InjectedFault")

    def matches(self, verb: str, kind: str | None) -> bool:
        if self.fault == FAULT_WATCH_KILL and verb != "watch":
            return False
        if self.verbs is not None and verb not in self.verbs:
            return False
        if self.kinds is not None and (kind is None or
                                       kind not in self.kinds):
            return False
        return True


@dataclass
class FaultPlan:
    """An ordered rule set + seeded RNG. Thread-safe: the apiserver decides
    faults from many handler threads; injected-fault counters and the RNG
    share one lock so a seeded run stays replayable under the ThreadingHTTPServer
    (per-request ordering still depends on arrival order, as on a real wire).
    """

    rules: list[FaultRule] = field(default_factory=list)
    seed: int | None = None
    active: bool = True

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)
        self._lock = sanitizer.tracked_lock(
            "faults.plan", order=sanitizer.ORDER_LEAF)
        self._injected: dict[tuple[str, str], int] = {}
        self._fired_per_rule: dict[int, int] = {}

    # ------------------------------------------------------------- control
    def deactivate(self) -> None:
        """The chaos suite's Deactivate(): stop injecting, keep counters."""
        self.active = False

    def activate(self) -> None:
        self.active = True

    # -------------------------------------------------------------- decide
    def decide(self, verb: str, kind: str | None = None) -> FaultRule | None:
        """The rule that fires for this request, else None. Matching rules
        compose CUMULATIVELY on one draw: a request's total fault
        probability is the sum of its matching rules' rates (capped at 1),
        so a plan that splits rate R across three fault shapes injects at
        exactly R — independent per-rule draws would compound to less."""
        if not self.active or not self.rules:
            return None
        with self._lock:
            matching = []
            for i, rule in enumerate(self.rules):
                if rule.times is not None and \
                        self._fired_per_rule.get(i, 0) >= rule.times:
                    continue  # burst budget spent
                if rule.matches(verb, kind) and rule.rate > 0:
                    matching.append((i, rule))
            if not matching:
                return None
            draw = self._rng.random()
            cumulative = 0.0
            for i, rule in matching:
                cumulative += rule.rate
                if draw < cumulative:
                    key = (rule.fault, verb)
                    self._injected[key] = self._injected.get(key, 0) + 1
                    self._fired_per_rule[i] = \
                        self._fired_per_rule.get(i, 0) + 1
                    return rule
        return None

    def injected(self) -> dict[tuple[str, str], int]:
        """Counts of injected faults by (fault, verb) — soaks assert the
        plan actually fired; zero injections would vacuously 'pass'."""
        with self._lock:
            return dict(self._injected)

    def injected_total(self) -> int:
        with self._lock:
            return sum(self._injected.values())

    # -------------------------------------------------------- constructors
    @classmethod
    def uniform(cls, rate: float, seed: int | None = None, *,
                kinds: frozenset[str] | None = None,
                retry_after_s: float = 0.05,
                watch_kill_after_s: float = 1.0,
                latency_spike_s: float = 0.0) -> "FaultPlan":
        """The standard mixed plan the soaks use: ``rate`` per verb
        (decide() composes matching rules cumulatively, so each verb's
        total IS ``rate``), split evenly across 429-with-Retry-After,
        503, and connection reset — reset kept on mutating verbs, where
        the ambiguity actually bites; reads take that share as extra
        503s — plus watch-stream kills at ``rate`` and an optional
        latency spike."""
        third = rate / 3.0
        rest_verbs = VERBS - {"watch"}       # REST verbs total exactly rate
        read_verbs = rest_verbs - MUTATING_VERBS
        rules = [
            FaultRule(FAULT_HTTP, third, status=429, verbs=rest_verbs,
                      retry_after_s=retry_after_s, kinds=kinds),
            FaultRule(FAULT_HTTP, third, status=503, verbs=rest_verbs,
                      kinds=kinds),
            FaultRule(FAULT_RESET, third, verbs=MUTATING_VERBS, kinds=kinds),
            FaultRule(FAULT_HTTP, third, status=503, verbs=read_verbs,
                      kinds=kinds),
            FaultRule(FAULT_WATCH_KILL, rate, after_s=watch_kill_after_s,
                      kinds=kinds),
        ]
        if latency_spike_s > 0:
            rules.append(FaultRule(FAULT_LATENCY, rate,
                                   latency_s=latency_spike_s, kinds=kinds))
        return cls(rules=rules, seed=seed)

    @classmethod
    def outage(cls, seed: int | None = None) -> "FaultPlan":
        """Total outage: every request (watch connects included) is reset.
        The wire analog of stopping the apiserver without losing the
        listening socket — what trips the manager's circuit breaker."""
        return cls(rules=[FaultRule(FAULT_RESET, 1.0)], seed=seed)

    @classmethod
    def from_dict(cls, doc: dict) -> "FaultPlan":
        """Build from a YAML/JSON document::

            seed: 7
            rules:
              - fault: http
                rate: 0.05
                status: 429
                retryAfterS: 0.1
                verbs: [get, list]
                kinds: [Notebook]
              - fault: watch_kill
                rate: 0.1
                afterS: 2.0
        """
        rules = []
        for raw in doc.get("rules", []):
            rules.append(FaultRule(
                fault=raw["fault"],
                rate=float(raw["rate"]),
                verbs=frozenset(raw["verbs"]) if raw.get("verbs") else None,
                kinds=frozenset(raw["kinds"]) if raw.get("kinds") else None,
                status=int(raw.get("status", 503)),
                retry_after_s=(float(raw["retryAfterS"])
                               if raw.get("retryAfterS") is not None else None),
                latency_s=float(raw.get("latencyS", 0.0)),
                after_s=float(raw.get("afterS", 0.0)),
                times=(int(raw["times"])
                       if raw.get("times") is not None else None),
            ))
        return cls(rules=rules, seed=doc.get("seed"))

    @classmethod
    def from_file(cls, path) -> "FaultPlan":
        import yaml
        from pathlib import Path
        doc = yaml.safe_load(Path(path).read_text()) or {}
        return cls.from_dict(doc)
