"""Read-cache client layer: data-stripping transforms + indexed stores.

Reference: odh main.go builds its manager cache with transforms that strip
``data``/``binaryData``/``stringData`` from every cached Secret and ConfigMap
(stripSecretData/stripConfigMapData, main.go:95-125) — the controller lists
hundreds of them across namespaces but only ever reads metadata from cache —
and disables client-side caching for those kinds entirely
(client.Options.Cache.DisableFor, main.go:248-268) so that code paths needing
actual payloads (CA bundle PEM, runtime-image JSON) read straight from the
apiserver.

``CachingClient`` wraps a ClusterStore with exactly that split:

- watch-fed local cache for every kind, transforms applied on ingest;
- ``get``/``list`` serve from cache EXCEPT kinds in ``disable_for`` which go
  direct to the store (fresh, untransformed);
- writes always pass through.

Reads are served from **per-kind stores carrying client-go-style indexers**
(controller-runtime's informer cache registers namespace/label/field
indexers behind every cached List; the reference's ``_find_owned_sts``-shape
lookups never scan the world):

- ``by-namespace`` — every namespaced list;
- ``by-label`` — one index per hot label key (``DEFAULT_LABEL_INDEXES``:
  the selectors the controllers actually use), equality AND existence form;
- ``by-owner`` — ownerReferences UID, serving ``get_owned`` (the
  Owns()-style lookup).

Indexes are maintained incrementally on ingest/delete, so ``list`` and
``get_owned`` are O(result), not O(cache). The lock guards ONLY the index
lookup; label predicates and deepcopies run outside it (a big fleet's list
must never stall ingestion). ``cache_index_lookups_total`` /
``cache_full_scans_total`` prove the hot path stays scan-free.

This is also where the framework's memory ceiling for big fleets is enforced:
the cache never holds Secret/ConfigMap payloads, the same reason the
reference added the transforms.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable

from ..utils import k8s, names, sanitizer
from .store import WatchEvent

DEFAULT_DISABLE_FOR = ("Secret", "ConfigMap")

#: label keys indexed by default — the selectors the controllers actually
#: use on hot paths: the notebook-name fleet label (``_find_owned_sts``,
#: culling's pod scrape, the notebook_running metrics scrape), the STS pod
#: selector, the runtime-image inventory, and the k8s part-of grouping
DEFAULT_LABEL_INDEXES = (
    names.NOTEBOOK_NAME_LABEL,
    "statefulset",
    names.POOL_LABEL,
    names.RUNTIME_IMAGE_LABEL,
    names.PART_OF_LABEL,
)

#: object-field paths indexed by default (controller-runtime's
#: FieldIndexer analog): ``spec.nodeName`` serves the node-event fan-in
#: (slice repair + kubelet sim map a Node to the pods bound to it) in
#: O(pods on that node) instead of O(fleet pods) per node event
DEFAULT_FIELD_INDEXES = ("spec.nodeName",)

LAST_APPLIED_ANNOTATION = names.LAST_APPLIED_ANNOTATION


def _strip_metadata_bulk(obj: dict) -> dict:
    """Drop managedFields and the kubectl last-applied-configuration
    annotation (which duplicates the whole payload) while preserving every
    other label/annotation — the reference's cache transforms do the same
    (main_test.go:33-45,70-86); tolerates absent/None metadata maps."""
    meta = obj.get("metadata")
    if not isinstance(meta, dict):
        return obj
    meta = dict(meta)
    meta.pop("managedFields", None)
    anns = meta.get("annotations")
    if isinstance(anns, dict) and LAST_APPLIED_ANNOTATION in anns:
        anns = dict(anns)
        anns.pop(LAST_APPLIED_ANNOTATION)
        meta["annotations"] = anns
    obj = dict(obj)
    obj["metadata"] = meta
    return obj


def strip_secret_data(obj: dict) -> dict:
    """Transform analog of stripSecretData (main.go:95-109): drops data/
    stringData/managedFields/last-applied, preserves type, labels, and
    other annotations; non-Secret objects pass through unchanged."""
    if obj.get("kind") == "Secret":
        obj = dict(obj)
        obj.pop("data", None)
        obj.pop("stringData", None)
        obj = _strip_metadata_bulk(obj)
    return obj


def strip_configmap_data(obj: dict) -> dict:
    """Transform analog of stripConfigMapData (main.go:111-125): drops
    data/binaryData/managedFields/last-applied, preserves labels and other
    annotations; non-ConfigMap objects pass through unchanged."""
    if obj.get("kind") == "ConfigMap":
        obj = dict(obj)
        obj.pop("data", None)
        obj.pop("binaryData", None)
        obj = _strip_metadata_bulk(obj)
    return obj


DEFAULT_TRANSFORMS = (strip_secret_data, strip_configmap_data)


def live_reader(client):
    """The live (uncached) client behind a reconciler's wrapper chain —
    EchoTrackingClient delegates ``store`` to the CachingClient, whose
    ``store`` is the real apiserver client; a bare store has no ``store``
    attribute and IS the live client. Conflict-retry paths re-read through
    this: after a 409 the foreign write's watch event may not have reached
    the cache yet, and a cached re-read would resend the same stale
    resourceVersion (RetryOnConflict re-reads from the apiserver for the
    same reason)."""
    return getattr(client, "store", None) or client


def owned_objects(client, kind: str, owner: dict) -> list[dict]:
    """``get_owned`` through ANY client: the indexed lookup when the client
    carries the informer index (CachingClient behind the usual wrapper
    chain), else a namespace LIST filtered by ownerReferences — the SAME
    result set either way (ownership is the one filter; a label selector
    here would silently drop an owned-but-mislabeled object on one path
    and adopt it on the other)."""
    fn = getattr(client, "get_owned", None)
    if fn is not None:
        return fn(kind, owner)
    return [o for o in client.list(kind, k8s.namespace(owner))
            if k8s.is_owned_by(o, k8s.uid(owner))]


def pods_on_node(client, node_name: str) -> list[dict]:
    """Pods bound to ``node_name`` through ANY client — the by-field
    ``spec.nodeName`` index when the client carries one (O(pods on this
    node)), else a label-existence LIST filtered in Python. The one
    node→pods fan-in both the slice-repair Node mapper and the kubelet
    simulator use, so their fallbacks cannot drift apart."""
    fn = getattr(client, "list_by_field", None)
    if fn is not None:
        return fn("Pod", "spec.nodeName", node_name)
    return [p for p in client.list("Pod", None, {"statefulset": None})
            if k8s.get_in(p, "spec", "nodeName") == node_name]


def _owner_uids(obj: dict) -> list[str]:
    return [r.get("uid") for r in
            (k8s.get_in(obj, "metadata", "ownerReferences",
                        default=[]) or [])
            if r.get("uid")]


class _KindStore:
    """One kind's objects plus its incrementally-maintained indexers (the
    client-go Indexer shape: ``by-namespace``, ``by-label`` per registered
    key, ``by-owner`` on ownerReferences UID). All mutation happens under
    the CachingClient lock; object dicts are replaced, never mutated, so
    references handed out under the lock are safe to read outside it."""

    __slots__ = ("label_keys", "field_paths", "objects", "by_namespace",
                 "by_owner", "by_label", "by_field")

    def __init__(self, label_keys: tuple[str, ...],
                 field_paths: tuple[str, ...] = ()):
        self.label_keys = label_keys
        # dot-paths into the object (e.g. "spec.nodeName"), pre-split once
        self.field_paths = {p: tuple(p.split(".")) for p in field_paths}
        self.objects: dict[tuple[str, str], dict] = {}  # (ns, name) → obj
        self.by_namespace: dict[str, set] = {}
        self.by_owner: dict[str, set] = {}
        self.by_label: dict[str, dict[str, set]] = {k: {} for k in label_keys}
        self.by_field: dict[str, dict[str, set]] = {p: {} for p in field_paths}

    # --------------------------------------------------------- maintenance
    def replace(self, key: tuple[str, str], obj: dict) -> None:
        old = self.objects.get(key)
        if old is not None:
            self._unindex(key, old)
        self.objects[key] = obj
        self._index(key, obj)

    def remove(self, key: tuple[str, str]) -> None:
        old = self.objects.pop(key, None)
        if old is not None:
            self._unindex(key, old)

    def _index(self, key: tuple[str, str], obj: dict) -> None:
        self.by_namespace.setdefault(key[0], set()).add(key)
        for uid in _owner_uids(obj):
            self.by_owner.setdefault(uid, set()).add(key)
        labels = k8s.get_in(obj, "metadata", "labels", default=None) or {}
        for lk in self.label_keys:
            if lk in labels:
                self.by_label[lk].setdefault(labels[lk], set()).add(key)
        for path, parts in self.field_paths.items():
            value = k8s.get_in(obj, *parts)
            if isinstance(value, str) and value:
                self.by_field[path].setdefault(value, set()).add(key)

    def _unindex(self, key: tuple[str, str], obj: dict) -> None:
        self._drop(self.by_namespace, key[0], key)
        for uid in _owner_uids(obj):
            self._drop(self.by_owner, uid, key)
        labels = k8s.get_in(obj, "metadata", "labels", default=None) or {}
        for lk in self.label_keys:
            if lk in labels:
                self._drop(self.by_label[lk], labels[lk], key)
        for path, parts in self.field_paths.items():
            value = k8s.get_in(obj, *parts)
            if isinstance(value, str) and value:
                self._drop(self.by_field[path], value, key)

    @staticmethod
    def _drop(index: dict, value, key) -> None:
        bucket = index.get(value)
        if bucket is not None:
            bucket.discard(key)
            if not bucket:  # empty buckets would leak one set per old value
                del index[value]

    # -------------------------------------------------------------- lookup
    def select(self, namespace: str | None,
               selector: dict | None) -> tuple[list[dict], str]:
        """Candidate objects via the narrowest applicable index. Returns
        (object refs, access path); the caller re-applies the FULL
        namespace+selector predicate outside the lock, so over-selection
        here is a perf concern only, never a correctness one."""
        if selector:
            for lk in self.label_keys:
                if lk in selector:
                    idx = self.by_label[lk]
                    val = selector[lk]
                    if val is None:  # existence term: every indexed value
                        keys = [k for bucket in idx.values() for k in bucket]
                    else:
                        keys = list(idx.get(val, ()))
                    return [self.objects[k] for k in keys], "by-label"
        if namespace is not None:
            return [self.objects[k]
                    for k in self.by_namespace.get(namespace, ())], \
                "by-namespace"
        if not selector:
            # unfiltered list-all IS the result set: O(result) by definition
            return list(self.objects.values()), "all"
        # selector carries no indexed key and no namespace bound: the one
        # shape that still walks the whole kind (cache_full_scans_total)
        return list(self.objects.values()), "scan"

    def owned(self, owner_uid: str) -> list[dict]:
        return [self.objects[k] for k in self.by_owner.get(owner_uid, ())]

    def field(self, path: str, value: str) -> tuple[list[dict], bool]:
        """Objects whose indexed field ``path`` equals ``value``; second
        element False when the path carries no index (caller must scan)."""
        idx = self.by_field.get(path)
        if idx is None:
            return [], False
        return [self.objects[k] for k in idx.get(value, ())], True


class CachingClient:
    """Same client surface as ClusterStore for reads/writes/watches, with the
    manager-cache semantics described above.

    ``disable_for`` kinds are payload kinds: their ``get``/``list`` payload
    reads go to the live store. They are still INGESTED (transforms strip
    the payload first, so a cached Secret/ConfigMap is metadata-sized —
    exactly the reference's stripped manager cache) so that a warm cache
    can answer EXISTENCE authoritatively: a miss on a warm payload kind is
    NotFound without a wire GET. Controllers probing optional ConfigMaps
    (CA bundles, runtime-images) every reconcile otherwise turn a big
    fan-out into a GET-404 storm. ``Event`` is the exception (never cached,
    never warm): the stream is high-churn and Events are read rarely."""

    NEVER_CACHE = frozenset(("Event",))

    def __init__(self, store,
                 transforms: Iterable[Callable[[dict], dict]] =
                 DEFAULT_TRANSFORMS,
                 disable_for: Iterable[str] = DEFAULT_DISABLE_FOR,
                 auto_informer: bool = True,
                 label_indexes: Iterable[str] = DEFAULT_LABEL_INDEXES,
                 field_indexes: Iterable[str] = DEFAULT_FIELD_INDEXES) -> None:
        self.store = store
        self.transforms = tuple(transforms)
        self.disable_for = frozenset(disable_for)
        self.label_indexes = tuple(label_indexes)
        self.field_indexes = tuple(field_indexes)
        # auto_informer=False: the cache opens NO watch streams of its own —
        # it is fed from watches its owner already holds (``feed``) plus an
        # explicit ``backfill`` per kind. This is how a reconciler shares
        # its manager watch streams with its read cache instead of
        # duplicating every stream + LIST (the reference likewise has ONE
        # informer layer serving both dispatch and cached reads).
        self.auto_informer = auto_informer
        # cache tier: taken for index/bucket bookkeeping only — live wire
        # reads (the miss fall-through, backfill LISTs) happen outside it
        self._lock = sanitizer.tracked_lock(
            "cache.index", order=sanitizer.ORDER_CACHE, no_blocking=True)
        self._kinds: dict[str, _KindStore] = sanitizer.guarded_by(
            {}, self._lock, "cache.kinds")
        # key → deletion time for keys DELETED by the watch stream; guards
        # the backfill (and the cache-miss fall-through) against resurrecting
        # an object whose DELETED event raced the list/get. The race window
        # is milliseconds, so entries expire after TOMBSTONE_TTL_S — without
        # the TTL this set would grow with every deletion for the process
        # lifetime
        self._tombstones: dict[tuple[str, str, str], float] = {}
        self._watched: set[str] = set()
        # kinds whose backfill LIST has completed: for these a cache miss is
        # an authoritative NotFound (informer semantics) — falling through
        # to a live GET would re-create the per-frame GET storm for every
        # lookup of a deleted object (e.g. Events outliving their Pod)
        self._warm: set[str] = set()
        # kind → count of currently-broken watch streams (mark_watch_gap/
        # mark_watch_recovered, fed by the transport's stream health): while
        # any stream for a kind is down, cached reads of it fall back LIVE —
        # the informer can be arbitrarily stale until the reconnect resync
        # lands, and an authoritative NotFound from a stale cache is wrong
        self._gapped: dict[str, int] = {}
        self._index_lookups = None  # cache_index_lookups_total
        self._full_scans = None     # cache_full_scans_total

    # ------------------------------------------------------------- ingest
    def _transform(self, obj: dict) -> dict:
        for t in self.transforms:
            obj = t(obj)
        return obj

    def _live_list(self, kind: str, namespace: str | None = None,
                   label_selector: dict | None = None) -> list[dict]:
        """A LIST that must leave this cache (gap/unfed/payload
        fallbacks, backfills, resyncs): prefer the backing client's
        rv-gated ``list_cached`` — over the wire that's the
        consistent-read-from-cache form served lock-free from the
        apiserver's watch cache (never stale: the facade's cache is fed
        synchronously under the store lock), so N managers' fallback
        LISTs can't stampede the store's write path. A backing store
        without the method (bare ClusterStore behind another wrapper)
        keeps the plain LIST."""
        lister = getattr(self.store, "list_cached", None)
        if lister is not None:
            return lister(kind, namespace, label_selector)
        return self.store.list(kind, namespace, label_selector)

    def _ensure_informer(self, kind: str) -> None:
        if not self.auto_informer:
            return  # externally fed: owner registers watches + backfills
        with self._lock:
            if kind in self._watched:
                return
            self._watched.add(kind)
        # register the watch BEFORE backfilling: an update landing between a
        # list snapshot and watch registration would otherwise never be
        # delivered, leaving the cache stale forever. The overlap is made
        # safe by (a) the resourceVersion guard in _ingest (a newer watched
        # copy is never overwritten by the older snapshot) and (b) the
        # tombstone set (a DELETED racing the snapshot is not resurrected).
        self.store.watch(kind, self._on_event)
        for obj in self._live_list(kind):
            self._ingest(obj)
        with self._lock:
            self._warm.add(kind)

    # ---------------------------------------------------- external feeding
    def feed(self, event: WatchEvent) -> None:
        """Ingest one watch event from a stream the OWNER holds (tee from a
        manager watch). Only meaningful with auto_informer=False.
        Payload (disable_for) kinds are ingested STRIPPED — the transforms
        drop data/binaryData/stringData — so the cache can answer existence
        without ever holding payloads; Event is dropped at the door (high
        churn, never served from cache).

        The event object may be SHARED with every other watcher of the
        store (serialize-once fan-out deepcopies once per event, not per
        consumer): this cache honors that by never mutating what it
        ingests — transforms copy-on-write, stores replace whole objects,
        reads deepcopy on the way out. A DELETED synthesized after an
        outage may carry only a skeleton (rv + routing metadata, the
        transport's slim ``seen`` record); removal needs only its key."""
        if event.obj.get("kind") in self.NEVER_CACHE:
            return
        self._on_event(event)

    def backfill(self, kind: str) -> None:
        """Snapshot-list ``kind`` into the cache and mark it warm. Call
        AFTER the external watch feeding this cache is registered (same
        watch-then-list ordering _ensure_informer uses, same staleness
        guards). Idempotent: a kind already warm (a second controller
        watching it) skips the redundant LIST.

        The LIST always runs on first backfill, even for clients whose
        watch streams resync initial state on connect (HttpApiClient):
        warm means "a complete snapshot has landed", and the resync is
        delivered asynchronously AFTER watch() returns — marking warm on
        the promise of a resync would turn existing objects into
        authoritative NotFounds for the gap (and for the whole outage if
        the stream never connected). The overlap with a delivered resync
        is idempotent ingestion.

        Payload (disable_for) kinds backfill too — stripped — so their
        existence checks turn authoritative; Event never does."""
        if kind in self.NEVER_CACHE:
            return  # never cached, never warm
        with self._lock:
            if kind in self._warm:
                return
        for obj in self._live_list(kind):
            self._ingest(obj)
        with self._lock:
            self._watched.add(kind)
            self._warm.add(kind)

    # -------------------------------------------------- watch-gap fallback
    def mark_watch_gap(self, kind: str) -> None:
        """A watch stream feeding ``kind`` dropped (transport stream-health
        callback): until it recovers, cached reads of the kind serve LIVE —
        the satellite contract for periodic scrapes (serve from the
        informer index while the watch is healthy, fall back to a real
        LIST only across a gap)."""
        with self._lock:
            self._gapped[kind] = self._gapped.get(kind, 0) + 1

    def mark_watch_recovered(self, kind: str) -> None:
        """The dropped stream reconnected AND its resync diff was delivered
        (the cache is converged again): resume serving from the index."""
        with self._lock:
            n = self._gapped.get(kind, 0) - 1
            if n > 0:
                self._gapped[kind] = n
            else:
                self._gapped.pop(kind, None)

    def _is_gapped(self, kind: str) -> bool:
        with self._lock:
            return kind in self._gapped

    # -------------------------------------------------------------- metrics
    def attach_metrics(self, registry) -> None:
        """Register the index-vs-scan counter pair (and pass the registry
        down to the backing store). ``cache_full_scans_total`` staying at 0
        is the loadtest/smoke proof that no reconcile-hot read walks the
        whole cache."""
        self._index_lookups = registry.counter(
            "cache_index_lookups_total",
            "Cached reads served via an informer index, by kind and "
            "index (by-label / by-namespace / by-owner / all).")
        self._full_scans = registry.counter(
            "cache_full_scans_total",
            "Cached LISTs that had to walk a whole kind store because no "
            "index covered the query. Must be 0 on the reconcile hot path.")
        if hasattr(self.store, "attach_metrics"):
            self.store.attach_metrics(registry)

    def _count_access(self, kind: str, via: str) -> None:
        if via == "scan":
            if self._full_scans is not None:
                self._full_scans.inc({"kind": kind})
        elif self._index_lookups is not None:
            self._index_lookups.inc({"kind": kind, "index": via})

    TOMBSTONE_TTL_S = 10.0

    def _prune_tombstones_locked(self) -> None:
        cutoff = time.monotonic() - self.TOMBSTONE_TTL_S
        stale = [k for k, t in self._tombstones.items() if t < cutoff]
        for k in stale:
            del self._tombstones[k]

    def _on_event(self, event: WatchEvent) -> None:
        key = self._key(event.obj)
        if event.type == "DELETED":
            with self._lock:
                ks = self._kinds.get(key[0])
                if ks is not None:
                    ks.remove((key[1], key[2]))
                self._prune_tombstones_locked()
                self._tombstones[key] = time.monotonic()
        else:
            self._ingest(event.obj, from_watch=True)

    @staticmethod
    def _rv(obj: dict) -> int:
        try:
            return int((obj.get("metadata") or {})
                       .get("resourceVersion", 0))
        except (TypeError, ValueError):
            return 0

    def _ingest(self, obj: dict, from_watch: bool = False) -> None:
        key = self._key(obj)
        with self._lock:
            if from_watch:
                # an ADDED after DELETED is a genuine recreate
                self._tombstones.pop(key, None)
            elif self._tombstones.get(key, 0) > \
                    time.monotonic() - self.TOMBSTONE_TTL_S:
                return  # stale snapshot of a deleted object
            ks = self._kinds.get(key[0])
            if ks is None:
                ks = self._kinds[key[0]] = _KindStore(self.label_indexes,
                                                      self.field_indexes)
            cached = ks.objects.get((key[1], key[2]))
            if cached is not None:
                cached_rv, new_rv = self._rv(cached), self._rv(obj)
                # never replace a newer watched copy with older state — an
                # rv-less snapshot (rv 0) must NOT clobber a versioned one
                if cached_rv > new_rv:
                    return
                # and skip EQUAL-rv re-ingestion (both versioned): several
                # controllers watching one kind deliver the same frame once
                # per stream; re-transform/re-store under the lock is waste
                if new_rv and cached_rv == new_rv:
                    return
            ks.replace((key[1], key[2]), self._transform(obj))

    @staticmethod
    def _key(obj: dict) -> tuple[str, str, str]:
        return (obj.get("kind", ""), k8s.namespace(obj), k8s.name(obj))

    # -------------------------------------------------------------- reads
    def cached_object(self, kind: str, namespace: str,
                      name: str) -> dict | None:
        """Introspection: the cache's current copy (deepcopy) or None —
        what a cache consumer WOULD see, without live fall-through. Tests
        assert payload-stripping and tombstone behavior through this."""
        with self._lock:
            ks = self._kinds.get(kind)
            obj = ks.objects.get((namespace, name)) if ks else None
        return k8s.deepcopy(obj) if obj is not None else None

    def get(self, kind: str, namespace: str, name: str) -> dict:
        if self._is_gapped(kind):
            # watch gap: the cache may be missing foreign writes until the
            # resync lands — neither a cached copy nor an authoritative
            # NotFound is trustworthy, so read live (no ingest: event
            # ordering during the gap is unknown; the resync repairs)
            return self.store.get(kind, namespace, name)
        if kind in self.disable_for:
            # payload kind: a HIT still reads live (the caller wants the
            # data the cache deliberately strips), but a MISS on a warm,
            # watch-fed kind is an authoritative NotFound — no wire GET
            # for every optional ConfigMap probed per reconcile
            with self._lock:
                warm = kind in self._warm
                ks = self._kinds.get(kind)
                present = ks is not None and (namespace, name) in ks.objects
            if warm and not present:
                from .errors import NotFoundError
                raise NotFoundError(f"{kind} {namespace}/{name}")
            return self.store.get(kind, namespace, name)  # live read
        with self._lock:
            unfed = kind not in self._watched
        if unfed and not self.auto_informer:
            # nobody feeds this kind: live read WITHOUT ingest — a cached
            # copy no watch updates would be served stale forever
            return self.store.get(kind, namespace, name)
        self._ensure_informer(kind)
        with self._lock:
            ks = self._kinds.get(kind)
            obj = ks.objects.get((namespace, name)) if ks else None
            warm = kind in self._warm
        if obj is not None:
            return k8s.deepcopy(obj)
        if warm:
            # informer-authoritative miss: the kind is fully backfilled and
            # watch-fed, so absence from the cache IS NotFound. Falling
            # through live would issue one GET per lookup of every deleted
            # object — the teardown-storm case (Events outlive their Pod).
            from .errors import NotFoundError
            raise NotFoundError(f"{kind} {namespace}/{name}")
        # not yet warm (external-feed kind before backfill): live, ingest
        obj = self.store.get(kind, namespace, name)
        self._ingest(obj)
        return self._transform(k8s.deepcopy(obj))

    def get_or_none(self, kind: str, namespace: str, name: str) -> dict | None:
        from .errors import NotFoundError
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict | None = None) -> list[dict]:
        with self._lock:
            unfed = kind not in self._watched
        if kind in self.disable_for or (unfed and not self.auto_informer) \
                or self._is_gapped(kind):
            # external-feed mode never auto-opens informers, so a LIST of a
            # kind nobody backfilled must go live, not return an empty
            # cache; a watch gap likewise bypasses the (possibly stale)
            # index until the reconnect resync converges it
            return self._live_list(kind, namespace, label_selector)
        self._ensure_informer(kind)
        # index lookup under the lock is O(result); the label predicate and
        # the deepcopying run OUTSIDE it — object dicts are replaced (never
        # mutated) on ingest, so the refs stay safe to read, and list() on
        # a big fleet never stalls ingestion on per-object predicate work
        with self._lock:
            ks = self._kinds.get(kind)
            candidates, via = (ks.select(namespace, label_selector)
                               if ks is not None else ([], "all"))
        self._count_access(kind, via)
        matched = [o for o in candidates
                   if (namespace is None or k8s.namespace(o) == namespace)
                   and k8s.matches_labels(o, label_selector)]
        return [k8s.deepcopy(o) for o in matched]

    def list_cached(self, kind: str, namespace: str | None = None,
                    label_selector: dict | None = None,
                    min_resource_version: int | None = None) -> list[dict]:
        """Interface parity with HttpApiClient.list_cached: this cache's
        index IS the consistent-read store (watch-fed, rv-guarded), and
        every fallback inside list() already rides the backing client's
        rv=0 form — so the resync path can ask any client for a
        cache-acceptable LIST without caring about the wrapper chain."""
        return self.list(kind, namespace, label_selector)

    def list_by_field(self, kind: str, path: str, value: str,
                      namespace: str | None = None) -> list[dict]:
        """Objects of ``kind`` whose field ``path`` (dot-path, e.g.
        "spec.nodeName") equals ``value`` — the FieldIndexer lookup,
        O(result) when the path is indexed (``field_indexes``). Falls back
        to a filtered live LIST for payload/unfed/gapped kinds and to a
        counted full scan when the path carries no index, so the result
        set is identical regardless of wiring."""
        parts = tuple(path.split("."))
        with self._lock:
            unfed = kind not in self._watched
        if kind in self.disable_for or (unfed and not self.auto_informer) \
                or self._is_gapped(kind):
            return [o for o in self._live_list(kind, namespace)
                    if k8s.get_in(o, *parts) == value]
        self._ensure_informer(kind)
        with self._lock:
            ks = self._kinds.get(kind)
            if ks is None:
                candidates, indexed = [], True
            else:
                candidates, indexed = ks.field(path, value)
                if not indexed:
                    candidates = list(ks.objects.values())
        self._count_access(kind, "by-field" if indexed else "scan")
        # the full predicate re-applies OUTSIDE the lock on both paths
        # (same over-selection contract as select())
        matched = [o for o in candidates
                   if k8s.get_in(o, *parts) == value
                   and (namespace is None or k8s.namespace(o) == namespace)]
        return [k8s.deepcopy(o) for o in matched]

    def get_owned(self, kind: str, owner: dict | str) -> list[dict]:
        """Objects of ``kind`` whose ownerReferences carry the owner's UID —
        the by-owner index lookup (client-go's cache.OwnerIndex shape), the
        O(result) replacement for list-by-label + Python ownership filter.
        ``owner`` is the owner object (preferred: its namespace scopes the
        live fallback) or a bare UID string. Ownership is the ONLY filter,
        on the index path and the live fallback alike — identical result
        sets regardless of wiring."""
        owner_uid = k8s.uid(owner) if isinstance(owner, dict) else owner
        owner_ns = k8s.namespace(owner) if isinstance(owner, dict) else None
        with self._lock:
            unfed = kind not in self._watched
        if kind in self.disable_for or (unfed and not self.auto_informer) \
                or self._is_gapped(kind):
            return [o for o in self._live_list(kind, owner_ns)
                    if k8s.is_owned_by(o, owner_uid)]
        self._ensure_informer(kind)
        with self._lock:
            ks = self._kinds.get(kind)
            candidates = ks.owned(owner_uid) if ks is not None else []
        self._count_access(kind, "by-owner")
        return [k8s.deepcopy(o) for o in candidates]

    # ---------------------------------------- writes + watches: passthrough
    def _ingest_write(self, obj, recreate: bool = False):
        """Feed a write's RESPONSE (fresh rv) straight into the cache for
        kinds this cache tracks — read-your-writes for the author. Over a
        real wire the watch event confirming our own write arrives
        milliseconds later; without this, a warm payload kind would report
        a just-created object as authoritative NotFound for that window,
        and any re-read would serve the pre-write copy. The rv guard in
        _ingest keeps the overlap with the eventual watch event idempotent.

        ``recreate`` (create responses only) clears a DELETE tombstone — a
        create after delete is a genuine recreate. Update/patch responses
        must NOT: an update racing a delete would pop the tombstone and
        resurrect the deleted object in the cache forever (no later watch
        event would ever evict it)."""
        if isinstance(obj, dict):
            kind = obj.get("kind")
            if kind and kind not in self.NEVER_CACHE:
                with self._lock:
                    tracked = kind in self._watched or kind in self._warm
                if tracked:
                    # deepcopy: the same response dict goes back to the
                    # caller, who may mutate it (copy-fields helpers do) —
                    # the cache must hold its own copy
                    self._ingest(k8s.deepcopy(obj), from_watch=recreate)
        return obj

    def create(self, obj: dict) -> dict:
        return self._ingest_write(self.store.create(obj), recreate=True)

    def update(self, obj: dict) -> dict:
        return self._ingest_write(self.store.update(obj))

    def update_status(self, obj: dict) -> dict:
        return self._ingest_write(self.store.update_status(obj))

    def patch(self, kind: str, namespace: str, name: str, patch: dict) -> dict:
        return self._ingest_write(self.store.patch(kind, namespace, name,
                                                   patch))

    def delete(self, kind: str, namespace: str, name: str) -> None:
        return self.store.delete(kind, namespace, name)

    def watch(self, kind: str, callback, **kw) -> None:
        return self.store.watch(kind, callback, **kw)

    def register_admission(self, kind: str, fn) -> None:
        return self.store.register_admission(kind, fn)

    @property
    def supports_inprocess_admission(self) -> bool:
        return getattr(self.store, "supports_inprocess_admission", True)
