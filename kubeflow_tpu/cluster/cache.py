"""Read-cache client layer with data-stripping transforms.

Reference: odh main.go builds its manager cache with transforms that strip
``data``/``binaryData``/``stringData`` from every cached Secret and ConfigMap
(stripSecretData/stripConfigMapData, main.go:95-125) — the controller lists
hundreds of them across namespaces but only ever reads metadata from cache —
and disables client-side caching for those kinds entirely
(client.Options.Cache.DisableFor, main.go:248-268) so that code paths needing
actual payloads (CA bundle PEM, runtime-image JSON) read straight from the
apiserver.

``CachingClient`` wraps a ClusterStore with exactly that split:

- watch-fed local cache for every kind, transforms applied on ingest;
- ``get``/``list`` serve from cache EXCEPT kinds in ``disable_for`` which go
  direct to the store (fresh, untransformed);
- writes always pass through.

This is also where the framework's memory ceiling for big fleets is enforced:
the cache never holds Secret/ConfigMap payloads, the same reason the
reference added the transforms.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable

from ..utils import k8s
from .store import WatchEvent

DEFAULT_DISABLE_FOR = ("Secret", "ConfigMap")


LAST_APPLIED_ANNOTATION = "kubectl.kubernetes.io/last-applied-configuration"


def _strip_metadata_bulk(obj: dict) -> dict:
    """Drop managedFields and the kubectl last-applied-configuration
    annotation (which duplicates the whole payload) while preserving every
    other label/annotation — the reference's cache transforms do the same
    (main_test.go:33-45,70-86); tolerates absent/None metadata maps."""
    meta = obj.get("metadata")
    if not isinstance(meta, dict):
        return obj
    meta = dict(meta)
    meta.pop("managedFields", None)
    anns = meta.get("annotations")
    if isinstance(anns, dict) and LAST_APPLIED_ANNOTATION in anns:
        anns = dict(anns)
        anns.pop(LAST_APPLIED_ANNOTATION)
        meta["annotations"] = anns
    obj = dict(obj)
    obj["metadata"] = meta
    return obj


def strip_secret_data(obj: dict) -> dict:
    """Transform analog of stripSecretData (main.go:95-109): drops data/
    stringData/managedFields/last-applied, preserves type, labels, and
    other annotations; non-Secret objects pass through unchanged."""
    if obj.get("kind") == "Secret":
        obj = dict(obj)
        obj.pop("data", None)
        obj.pop("stringData", None)
        obj = _strip_metadata_bulk(obj)
    return obj


def strip_configmap_data(obj: dict) -> dict:
    """Transform analog of stripConfigMapData (main.go:111-125): drops
    data/binaryData/managedFields/last-applied, preserves labels and other
    annotations; non-ConfigMap objects pass through unchanged."""
    if obj.get("kind") == "ConfigMap":
        obj = dict(obj)
        obj.pop("data", None)
        obj.pop("binaryData", None)
        obj = _strip_metadata_bulk(obj)
    return obj


DEFAULT_TRANSFORMS = (strip_secret_data, strip_configmap_data)


def live_reader(client):
    """The live (uncached) client behind a reconciler's wrapper chain —
    EchoTrackingClient delegates ``store`` to the CachingClient, whose
    ``store`` is the real apiserver client; a bare store has no ``store``
    attribute and IS the live client. Conflict-retry paths re-read through
    this: after a 409 the foreign write's watch event may not have reached
    the cache yet, and a cached re-read would resend the same stale
    resourceVersion (RetryOnConflict re-reads from the apiserver for the
    same reason)."""
    return getattr(client, "store", None) or client


class CachingClient:
    """Same client surface as ClusterStore for reads/writes/watches, with the
    manager-cache semantics described above.

    ``disable_for`` kinds are payload kinds: their ``get``/``list`` payload
    reads go to the live store. They are still INGESTED (transforms strip
    the payload first, so a cached Secret/ConfigMap is metadata-sized —
    exactly the reference's stripped manager cache) so that a warm cache
    can answer EXISTENCE authoritatively: a miss on a warm payload kind is
    NotFound without a wire GET. Controllers probing optional ConfigMaps
    (CA bundles, runtime-images) every reconcile otherwise turn a big
    fan-out into a GET-404 storm. ``Event`` is the exception (never cached,
    never warm): the stream is high-churn and Events are read rarely."""

    NEVER_CACHE = frozenset(("Event",))

    def __init__(self, store,
                 transforms: Iterable[Callable[[dict], dict]] =
                 DEFAULT_TRANSFORMS,
                 disable_for: Iterable[str] = DEFAULT_DISABLE_FOR,
                 auto_informer: bool = True) -> None:
        self.store = store
        self.transforms = tuple(transforms)
        self.disable_for = frozenset(disable_for)
        # auto_informer=False: the cache opens NO watch streams of its own —
        # it is fed from watches its owner already holds (``feed``) plus an
        # explicit ``backfill`` per kind. This is how a reconciler shares
        # its manager watch streams with its read cache instead of
        # duplicating every stream + LIST (the reference likewise has ONE
        # informer layer serving both dispatch and cached reads).
        self.auto_informer = auto_informer
        self._cache: dict[tuple[str, str, str], dict] = {}
        # key → deletion time for keys DELETED by the watch stream; guards
        # the backfill (and the cache-miss fall-through) against resurrecting
        # an object whose DELETED event raced the list/get. The race window
        # is milliseconds, so entries expire after TOMBSTONE_TTL_S — without
        # the TTL this set would grow with every deletion for the process
        # lifetime
        self._tombstones: dict[tuple[str, str, str], float] = {}
        self._lock = threading.Lock()
        self._watched: set[str] = set()
        # kinds whose backfill LIST has completed: for these a cache miss is
        # an authoritative NotFound (informer semantics) — falling through
        # to a live GET would re-create the per-frame GET storm for every
        # lookup of a deleted object (e.g. Events outliving their Pod)
        self._warm: set[str] = set()

    # ------------------------------------------------------------- ingest
    def _transform(self, obj: dict) -> dict:
        for t in self.transforms:
            obj = t(obj)
        return obj

    def _ensure_informer(self, kind: str) -> None:
        if not self.auto_informer:
            return  # externally fed: owner registers watches + backfills
        with self._lock:
            if kind in self._watched:
                return
            self._watched.add(kind)
        # register the watch BEFORE backfilling: an update landing between a
        # list snapshot and watch registration would otherwise never be
        # delivered, leaving the cache stale forever. The overlap is made
        # safe by (a) the resourceVersion guard in _ingest (a newer watched
        # copy is never overwritten by the older snapshot) and (b) the
        # tombstone set (a DELETED racing the snapshot is not resurrected).
        self.store.watch(kind, self._on_event)
        for obj in self.store.list(kind):
            self._ingest(obj)
        with self._lock:
            self._warm.add(kind)

    # ---------------------------------------------------- external feeding
    def feed(self, event: WatchEvent) -> None:
        """Ingest one watch event from a stream the OWNER holds (tee from a
        manager watch). Only meaningful with auto_informer=False.
        Payload (disable_for) kinds are ingested STRIPPED — the transforms
        drop data/binaryData/stringData — so the cache can answer existence
        without ever holding payloads; Event is dropped at the door (high
        churn, never served from cache)."""
        if event.obj.get("kind") in self.NEVER_CACHE:
            return
        self._on_event(event)

    def backfill(self, kind: str) -> None:
        """Snapshot-list ``kind`` into the cache and mark it warm. Call
        AFTER the external watch feeding this cache is registered (same
        watch-then-list ordering _ensure_informer uses, same staleness
        guards). Idempotent: a kind already warm (a second controller
        watching it) skips the redundant LIST.

        The LIST always runs on first backfill, even for clients whose
        watch streams resync initial state on connect (HttpApiClient):
        warm means "a complete snapshot has landed", and the resync is
        delivered asynchronously AFTER watch() returns — marking warm on
        the promise of a resync would turn existing objects into
        authoritative NotFounds for the gap (and for the whole outage if
        the stream never connected). The overlap with a delivered resync
        is idempotent ingestion.

        Payload (disable_for) kinds backfill too — stripped — so their
        existence checks turn authoritative; Event never does."""
        if kind in self.NEVER_CACHE:
            return  # never cached, never warm
        with self._lock:
            if kind in self._warm:
                return
        for obj in self.store.list(kind):
            self._ingest(obj)
        with self._lock:
            self._watched.add(kind)
            self._warm.add(kind)

    TOMBSTONE_TTL_S = 10.0

    def _prune_tombstones_locked(self) -> None:
        cutoff = time.monotonic() - self.TOMBSTONE_TTL_S
        stale = [k for k, t in self._tombstones.items() if t < cutoff]
        for k in stale:
            del self._tombstones[k]

    def _on_event(self, event: WatchEvent) -> None:
        key = self._key(event.obj)
        if event.type == "DELETED":
            with self._lock:
                self._cache.pop(key, None)
                self._prune_tombstones_locked()
                self._tombstones[key] = time.monotonic()
        else:
            self._ingest(event.obj, from_watch=True)

    @staticmethod
    def _rv(obj: dict) -> int:
        try:
            return int((obj.get("metadata") or {})
                       .get("resourceVersion", 0))
        except (TypeError, ValueError):
            return 0

    def _ingest(self, obj: dict, from_watch: bool = False) -> None:
        key = self._key(obj)
        with self._lock:
            if from_watch:
                # an ADDED after DELETED is a genuine recreate
                self._tombstones.pop(key, None)
            elif self._tombstones.get(key, 0) > \
                    time.monotonic() - self.TOMBSTONE_TTL_S:
                return  # stale snapshot of a deleted object
            cached = self._cache.get(key)
            if cached is not None:
                cached_rv, new_rv = self._rv(cached), self._rv(obj)
                # never replace a newer watched copy with older state — an
                # rv-less snapshot (rv 0) must NOT clobber a versioned one
                if cached_rv > new_rv:
                    return
                # and skip EQUAL-rv re-ingestion (both versioned): several
                # controllers watching one kind deliver the same frame once
                # per stream; re-transform/re-store under the lock is waste
                if new_rv and cached_rv == new_rv:
                    return
            self._cache[key] = self._transform(obj)

    @staticmethod
    def _key(obj: dict) -> tuple[str, str, str]:
        return (obj.get("kind", ""), k8s.namespace(obj), k8s.name(obj))

    # -------------------------------------------------------------- reads
    def get(self, kind: str, namespace: str, name: str) -> dict:
        if kind in self.disable_for:
            # payload kind: a HIT still reads live (the caller wants the
            # data the cache deliberately strips), but a MISS on a warm,
            # watch-fed kind is an authoritative NotFound — no wire GET
            # for every optional ConfigMap probed per reconcile
            with self._lock:
                warm = kind in self._warm
                present = (kind, namespace, name) in self._cache
            if warm and not present:
                from .errors import NotFoundError
                raise NotFoundError(f"{kind} {namespace}/{name}")
            return self.store.get(kind, namespace, name)  # live read
        with self._lock:
            unfed = kind not in self._watched
        if unfed and not self.auto_informer:
            # nobody feeds this kind: live read WITHOUT ingest — a cached
            # copy no watch updates would be served stale forever
            return self.store.get(kind, namespace, name)
        self._ensure_informer(kind)
        with self._lock:
            obj = self._cache.get((kind, namespace, name))
            warm = kind in self._warm
        if obj is not None:
            return k8s.deepcopy(obj)
        if warm:
            # informer-authoritative miss: the kind is fully backfilled and
            # watch-fed, so absence from the cache IS NotFound. Falling
            # through live would issue one GET per lookup of every deleted
            # object — the teardown-storm case (Events outlive their Pod).
            from .errors import NotFoundError
            raise NotFoundError(f"{kind} {namespace}/{name}")
        # not yet warm (external-feed kind before backfill): live, ingest
        obj = self.store.get(kind, namespace, name)
        self._ingest(obj)
        return self._transform(k8s.deepcopy(obj))

    def get_or_none(self, kind: str, namespace: str, name: str) -> dict | None:
        from .errors import NotFoundError
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict | None = None) -> list[dict]:
        with self._lock:
            unfed = kind not in self._watched
        if kind in self.disable_for or (unfed and not self.auto_informer):
            # external-feed mode never auto-opens informers, so a LIST of a
            # kind nobody backfilled must go live, not return an empty cache
            return self.store.list(kind, namespace, label_selector)
        self._ensure_informer(kind)
        # filter first, deepcopy only the matches, and do the copying
        # outside the lock — list() on a big fleet must not stall ingestion
        with self._lock:
            matched = [o for (k, ns, _), o in self._cache.items()
                       if k == kind
                       and (namespace is None or ns == namespace)
                       and k8s.matches_labels(o, label_selector)]
        return [k8s.deepcopy(o) for o in matched]

    # ---------------------------------------- writes + watches: passthrough
    def _ingest_write(self, obj, recreate: bool = False):
        """Feed a write's RESPONSE (fresh rv) straight into the cache for
        kinds this cache tracks — read-your-writes for the author. Over a
        real wire the watch event confirming our own write arrives
        milliseconds later; without this, a warm payload kind would report
        a just-created object as authoritative NotFound for that window,
        and any re-read would serve the pre-write copy. The rv guard in
        _ingest keeps the overlap with the eventual watch event idempotent.

        ``recreate`` (create responses only) clears a DELETE tombstone — a
        create after delete is a genuine recreate. Update/patch responses
        must NOT: an update racing a delete would pop the tombstone and
        resurrect the deleted object in the cache forever (no later watch
        event would ever evict it)."""
        if isinstance(obj, dict):
            kind = obj.get("kind")
            if kind and kind not in self.NEVER_CACHE:
                with self._lock:
                    tracked = kind in self._watched or kind in self._warm
                if tracked:
                    # deepcopy: the same response dict goes back to the
                    # caller, who may mutate it (copy-fields helpers do) —
                    # the cache must hold its own copy
                    self._ingest(k8s.deepcopy(obj), from_watch=recreate)
        return obj

    def create(self, obj: dict) -> dict:
        return self._ingest_write(self.store.create(obj), recreate=True)

    def update(self, obj: dict) -> dict:
        return self._ingest_write(self.store.update(obj))

    def update_status(self, obj: dict) -> dict:
        return self._ingest_write(self.store.update_status(obj))

    def patch(self, kind: str, namespace: str, name: str, patch: dict) -> dict:
        return self._ingest_write(self.store.patch(kind, namespace, name,
                                                   patch))

    def delete(self, kind: str, namespace: str, name: str) -> None:
        return self.store.delete(kind, namespace, name)

    def watch(self, kind: str, callback, **kw) -> None:
        return self.store.watch(kind, callback, **kw)

    def register_admission(self, kind: str, fn) -> None:
        return self.store.register_admission(kind, fn)

    @property
    def supports_inprocess_admission(self) -> bool:
        return getattr(self.store, "supports_inprocess_admission", True)

    def attach_metrics(self, registry) -> None:
        if hasattr(self.store, "attach_metrics"):
            self.store.attach_metrics(registry)
