from .errors import ApiError, ConflictError, NotFoundError, AlreadyExistsError, InvalidError
from .store import ClusterStore, WatchEvent
from .chaos import ChaosClient, FaultConfig

__all__ = [
    "ApiError", "ConflictError", "NotFoundError", "AlreadyExistsError",
    "InvalidError", "ClusterStore", "WatchEvent", "ChaosClient", "FaultConfig",
]
