"""Fault-injecting client wrapper — the operator-chaos SDK analog.

The reference's chaos tests wrap the envtest client with per-operation error
rates (sdk.NewChaosClient, odh chaostests/chaos_test.go:42-54) and assert both
error propagation and reconvergence after Deactivate(). This wrapper provides
the same seam over ClusterStore for our chaos tests."""

from __future__ import annotations

import random
from dataclasses import dataclass, field

from .errors import ApiError
from .store import ClusterStore


class InjectedFault(ApiError):
    code = 500
    reason = "InjectedFault"


@dataclass
class FaultConfig:
    """Per-verb error probabilities in [0, 1]."""
    get: float = 0.0
    list: float = 0.0
    create: float = 0.0
    update: float = 0.0
    patch: float = 0.0
    delete: float = 0.0
    active: bool = True
    seed: int | None = None
    _rng: random.Random = field(init=False, repr=False, default=None)  # type: ignore

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)

    def deactivate(self) -> None:
        self.active = False

    def activate(self) -> None:
        self.active = True

    def should_fail(self, verb: str) -> bool:
        rate = getattr(self, verb, 0.0)
        return self.active and rate > 0 and self._rng.random() < rate


class ChaosClient:
    """Duck-types ClusterStore's verb surface; controllers take either."""

    def __init__(self, store: ClusterStore, config: FaultConfig):
        self._store = store
        self.config = config

    def _maybe_fail(self, verb: str) -> None:
        if self.config.should_fail(verb):
            raise InjectedFault(f"injected {verb} fault")

    def create(self, obj):
        self._maybe_fail("create")
        return self._store.create(obj)

    def get(self, kind, namespace, name):
        self._maybe_fail("get")
        return self._store.get(kind, namespace, name)

    def get_or_none(self, kind, namespace, name):
        self._maybe_fail("get")
        return self._store.get_or_none(kind, namespace, name)

    def list(self, kind, namespace=None, label_selector=None):
        self._maybe_fail("list")
        return self._store.list(kind, namespace, label_selector)

    def update(self, obj):
        self._maybe_fail("update")
        return self._store.update(obj)

    def update_status(self, obj):
        self._maybe_fail("update")
        return self._store.update_status(obj)

    def patch(self, kind, namespace, name, patch):
        self._maybe_fail("patch")
        return self._store.patch(kind, namespace, name, patch)

    def delete(self, kind, namespace, name):
        self._maybe_fail("delete")
        return self._store.delete(kind, namespace, name)

    def watch(self, *args, **kwargs):
        return self._store.watch(*args, **kwargs)

    def register_admission(self, *args, **kwargs):
        return self._store.register_admission(*args, **kwargs)

    @property
    def supports_inprocess_admission(self) -> bool:
        # composes over HttpApiClient too (chaos across the real transport)
        return getattr(self._store, "supports_inprocess_admission", True)

    def attach_metrics(self, registry) -> None:
        attach = getattr(self._store, "attach_metrics", None)
        if attach is not None:
            attach(registry)

    def close(self) -> None:
        close = getattr(self._store, "close", None)
        if close is not None:
            close()
