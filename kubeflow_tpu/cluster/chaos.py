"""Fault-injecting client wrapper — the operator-chaos SDK analog.

The reference's chaos tests wrap the envtest client with per-operation error
rates (sdk.NewChaosClient, odh chaostests/chaos_test.go:42-54) and assert both
error propagation and reconvergence after Deactivate(). This wrapper provides
the same seam over ClusterStore for our chaos tests.

Two injection surfaces share one ``FaultConfig``:

- **in-process** (this module): ``ChaosClient`` raises ``InjectedFault``
  per verb, and — new — injects on the WATCH path too: events are dropped
  with probability ``watch`` and/or delivered late by ``watch_delay_s``
  (the informer-lag / dropped-edge failure mode the reference's chaos SDK
  cannot produce, because its client wrapper passes watches through);
- **wire** (``FaultConfig.wire_plan()`` → ``cluster/faults.FaultPlan``):
  the same per-verb rates compiled into a plan for ``ApiServerProxy``, so
  a chaos run can hit a manager over the REAL transport with
  429/503/reset/watch-kill instead of in-process exceptions.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field

from .errors import ApiError
from .store import ClusterStore


class InjectedFault(ApiError):
    code = 500
    reason = "InjectedFault"


@dataclass
class FaultConfig:
    """Per-verb error probabilities in [0, 1]. ``watch`` is the
    probability an individual watch EVENT is dropped before delivery;
    ``watch_delay_s`` delays every delivered event (0 = synchronous)."""
    get: float = 0.0
    list: float = 0.0
    create: float = 0.0
    update: float = 0.0
    patch: float = 0.0
    delete: float = 0.0
    watch: float = 0.0
    watch_delay_s: float = 0.0
    active: bool = True
    seed: int | None = None
    _rng: random.Random = field(init=False, repr=False, default=None)  # type: ignore

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)

    def deactivate(self) -> None:
        self.active = False

    def activate(self) -> None:
        self.active = True

    def should_fail(self, verb: str) -> bool:
        rate = getattr(self, verb, 0.0)
        return self.active and rate > 0 and self._rng.random() < rate

    def wire_plan(self, *, reset_share: float = 0.34,
                  retry_after_s: float = 0.05,
                  watch_kill_after_s: float = 1.0):
        """Compile these rates into a ``FaultPlan`` for ``ApiServerProxy``
        — the same chaos config driving the real transport. Each verb's
        rate splits between a 429-with-Retry-After/503 mix and (for
        mutations) connection resets; the ``watch`` rate becomes
        watch-stream kills. The plan gets its own RNG seeded from
        ``seed`` so in-process and wire runs don't consume one stream."""
        from .faults import (FAULT_HTTP, FAULT_RESET, FAULT_WATCH_KILL,
                             MUTATING_VERBS, FaultPlan, FaultRule)
        rules = []
        for verb in ("get", "list", "create", "update", "patch", "delete"):
            rate = getattr(self, verb)
            if rate <= 0:
                continue
            resettable = verb in MUTATING_VERBS
            reset_rate = rate * reset_share if resettable else 0.0
            http_rate = rate - reset_rate
            rules.append(FaultRule(FAULT_HTTP, http_rate / 2, status=429,
                                   retry_after_s=retry_after_s,
                                   verbs=frozenset({verb})))
            rules.append(FaultRule(FAULT_HTTP, http_rate / 2, status=503,
                                   verbs=frozenset({verb})))
            if reset_rate > 0:
                rules.append(FaultRule(FAULT_RESET, reset_rate,
                                       verbs=frozenset({verb})))
        if self.watch > 0:
            rules.append(FaultRule(FAULT_WATCH_KILL, self.watch,
                                   after_s=watch_kill_after_s))
        plan = FaultPlan(rules=rules, seed=self.seed)
        plan.active = self.active
        return plan


class ChaosClient:
    """Duck-types ClusterStore's verb surface; controllers take either."""

    def __init__(self, store: ClusterStore, config: FaultConfig):
        self._store = store
        self.config = config
        # original callback → injection wrapper, so unwatch() can
        # deregister by the identity the consumer holds
        self._watch_wrappers: dict = {}

    def _maybe_fail(self, verb: str) -> None:
        if self.config.should_fail(verb):
            raise InjectedFault(f"injected {verb} fault")

    def create(self, obj):
        self._maybe_fail("create")
        return self._store.create(obj)

    def get(self, kind, namespace, name):
        self._maybe_fail("get")
        return self._store.get(kind, namespace, name)

    def get_or_none(self, kind, namespace, name):
        self._maybe_fail("get")
        return self._store.get_or_none(kind, namespace, name)

    def list(self, kind, namespace=None, label_selector=None):
        self._maybe_fail("list")
        return self._store.list(kind, namespace, label_selector)

    def list_cached(self, kind, namespace=None, label_selector=None,
                    min_resource_version=None):
        # the rv=0 consistent-read LIST (resync/backfill path) is still a
        # LIST on the wire — it must take list faults, not slip through
        # the __getattr__ passthrough uninjected
        self._maybe_fail("list")
        fn = getattr(self._store, "list_cached", None)
        if fn is None:
            return self._store.list(kind, namespace, label_selector)
        return fn(kind, namespace, label_selector,
                  min_resource_version=min_resource_version)

    def update(self, obj):
        self._maybe_fail("update")
        return self._store.update(obj)

    def update_status(self, obj):
        self._maybe_fail("update")
        return self._store.update_status(obj)

    def patch(self, kind, namespace, name, patch):
        self._maybe_fail("patch")
        return self._store.patch(kind, namespace, name, patch)

    def delete(self, kind, namespace, name):
        self._maybe_fail("delete")
        return self._store.delete(kind, namespace, name)

    def watch(self, kind, callback, *args, **kwargs):
        """Watch with event-level fault injection: each event is dropped
        with probability ``config.watch`` (a lossy informer edge — the
        consumer must reconverge off a later event or resync, exactly the
        level-triggered contract) and/or delivered ``watch_delay_s`` late
        on a timer thread (informer lag: the consumer observes genuinely
        stale world state). Injection is decided per event at delivery
        time, so deactivate() heals live watches immediately."""
        config = self.config

        def injected(event):
            if config.should_fail("watch"):
                return  # dropped edge
            if config.active and config.watch_delay_s > 0:
                timer = threading.Timer(config.watch_delay_s, callback,
                                        args=(event,))
                timer.daemon = True
                timer.start()
            else:
                callback(event)

        self._watch_wrappers[callback] = injected
        return self._store.watch(kind, injected, *args, **kwargs)

    def unwatch(self, callback):
        wrapped = self._watch_wrappers.pop(callback, callback)
        return self._store.unwatch(wrapped)

    def register_admission(self, *args, **kwargs):
        return self._store.register_admission(*args, **kwargs)

    @property
    def supports_inprocess_admission(self) -> bool:
        # composes over HttpApiClient too (chaos across the real transport)
        return getattr(self._store, "supports_inprocess_admission", True)

    def attach_metrics(self, registry) -> None:
        attach = getattr(self._store, "attach_metrics", None)
        if attach is not None:
            attach(registry)

    def close(self) -> None:
        close = getattr(self._store, "close", None)
        if close is not None:
            close()

    def __getattr__(self, name):
        # transport extras (ping, set_health_tracker, …) pass through to
        # the wrapped client so the manager's breaker wiring composes
        # over chaos: hasattr() answers exactly what the inner client
        # supports. Note __getattr__ only fires for names NOT defined
        # above — the fault-injecting verbs always win.
        return getattr(self._store, name)
