"""Compact binary wire codec for the JSON object model (the apiserver's
``Accept``/``Content-Type``-negotiated alternative to JSON).

The PR-7 100k soak was CPU-bound on encode time and wire bytes: every
watch frame and LIST item crossed the wire as UTF-8 JSON, where a
Kubernetes object spends most of its bytes on the *same few dozen key
strings* repeated in every object ("metadata", "resourceVersion",
"ownerReferences", ...). This codec keeps the JSON data model exactly
(null/bool/int/float/str/list/dict — ``decode(encode(x)) == x`` for
anything ``json.dumps`` accepts) but encodes it as a tagged token stream
with string interning:

- a STATIC intern table of the common k8s key/value strings, shared by
  encoder and decoder (a table change is a wire-protocol change — bump
  ``BINARY_CONTENT_TYPE``);
- DYNAMIC interning per message: the first occurrence of any other
  string is sent inline and appended to the table, later occurrences
  are a 1-2 byte back-reference — so a name repeated through
  labels/ownerReferences/selector costs its bytes once;
- varint (LEB128) lengths/counts and zigzag varint ints;
- an outer 1-byte envelope that DEFLATE-compresses large token streams
  when that wins (watch fan-out is serialize-once per event — see
  ``EventFrame.obj_bytes_binary`` — so the compression cost is paid
  once per event, not per watcher).

Every message is self-contained (the dynamic table resets per message):
cached frame encodings decode independently, in any order, on any
frontend. Malformed input raises ``CodecError`` (a ``ValueError``) —
the HTTP client maps it to a retryable transport error (PR-2
semantics), never a silent partial decode.

Wire framing for watch streams (the NDJSON analog): each event is
``u32 total-length (big-endian) | u8 type-length | type (ascii) |
object payload``, where the object payload is exactly the cached
``encode()`` output — the envelope splices around it without
re-encoding.
"""

from __future__ import annotations

import struct
import zlib

#: negotiated media type for request/response bodies and watch streams;
#: the version tag is the compatibility contract for the static table
BINARY_CONTENT_TYPE = "application/vnd.ktpu.v1+binary"
#: merge-patch flavor (the apiserver's PATCH handler keys on the
#: "merge-patch" substring, mirroring application/merge-patch+json)
BINARY_PATCH_CONTENT_TYPE = "application/merge-patch+vnd.ktpu.v1.binary"

# token tags
_T_NULL = 0x00
_T_FALSE = 0x01
_T_TRUE = 0x02
_T_INT = 0x03      # zigzag LEB128
_T_FLOAT = 0x04    # 8-byte IEEE-754 big-endian
_T_STR = 0x05      # varint byte length + UTF-8; appends to intern table
_T_STRREF = 0x06   # varint intern-table index
_T_LIST = 0x07     # varint count + items
_T_DICT = 0x08     # varint count + (key, value) pairs

# envelope flags (first byte of every encoded message)
_ENV_RAW = 0x00
_ENV_DEFLATE = 0x01

#: compress only when the token stream is big enough for DEFLATE to
#: plausibly win (headers cost ~11 bytes; tiny objects stay raw)
_DEFLATE_THRESHOLD = 160

# The static intern table: common k8s key strings plus ubiquitous
# values. ORDER IS WIRE FORMAT — append-only; reordering or removing
# entries breaks decoding of peer-encoded messages.
STATIC_STRINGS = (
    "apiVersion", "kind", "metadata", "name", "namespace", "generateName",
    "labels", "annotations", "resourceVersion", "uid", "generation",
    "creationTimestamp", "deletionTimestamp", "finalizers",
    "ownerReferences", "controller", "blockOwnerDeletion", "spec",
    "status", "conditions", "type", "reason", "message",
    "lastTransitionTime", "replicas", "readyReplicas", "selector",
    "template", "containers", "image", "resources", "limits", "requests",
    "env", "value", "ports", "containerPort", "volumeMounts", "mountPath",
    "volumes", "serviceName", "items", "data", "v1", "apps/v1",
    "kubeflow.org/v1", "Notebook", "StatefulSet", "Service", "Pod",
    "ConfigMap", "Event", "Secret", "SlicePool", "True", "False",
    "Running", "Ready", "Pending", "default", "matchLabels",
    "notebook-name", "cpu", "memory", "phase",
)

_STATIC_INDEX = {s: i for i, s in enumerate(STATIC_STRINGS)}
_N_STATIC = len(STATIC_STRINGS)


class CodecError(ValueError):
    """Malformed or truncated binary payload (or an unencodable value).
    The HTTP client converts decode-side instances into a retryable
    transport error, mirroring json.JSONDecodeError handling."""


def _write_varint(buf: bytearray, n: int) -> None:
    while n > 0x7F:
        buf.append((n & 0x7F) | 0x80)
        n >>= 7
    buf.append(n)


def _encode_value(buf: bytearray, value, interned: dict[str, int]) -> None:
    if value is None:
        buf.append(_T_NULL)
    elif value is True:
        buf.append(_T_TRUE)
    elif value is False:
        buf.append(_T_FALSE)
    elif isinstance(value, int):
        buf.append(_T_INT)
        # zigzag: arbitrary-precision-safe form (no fixed-width shifts)
        _write_varint(buf, value * 2 if value >= 0 else -value * 2 - 1)
    elif isinstance(value, float):
        buf.append(_T_FLOAT)
        buf += struct.pack(">d", value)
    elif isinstance(value, str):
        idx = interned.get(value)
        if idx is not None:
            buf.append(_T_STRREF)
            _write_varint(buf, idx)
        else:
            raw = value.encode()
            buf.append(_T_STR)
            _write_varint(buf, len(raw))
            buf += raw
            interned[value] = len(interned)
    elif isinstance(value, (list, tuple)):
        buf.append(_T_LIST)
        _write_varint(buf, len(value))
        for item in value:
            _encode_value(buf, item, interned)
    elif isinstance(value, dict):
        buf.append(_T_DICT)
        _write_varint(buf, len(value))
        for k, v in value.items():
            if not isinstance(k, str):
                raise CodecError(f"non-string dict key {k!r}")
            _encode_value(buf, k, interned)
            _encode_value(buf, v, interned)
    else:
        raise CodecError(f"unencodable type {type(value).__name__}")


def encode(value) -> bytes:
    """Encode one JSON-model value into a self-contained binary message."""
    buf = bytearray()
    _encode_value(buf, value, dict(_STATIC_INDEX))
    if len(buf) >= _DEFLATE_THRESHOLD:
        packed = zlib.compress(bytes(buf), 1)
        if len(packed) < len(buf):
            return b"%c%s" % (_ENV_DEFLATE, packed)
    return b"%c%s" % (_ENV_RAW, bytes(buf))


class _Reader:
    """Cursor over one message's token bytes with bounds checking —
    truncation at any point surfaces CodecError, never an IndexError
    or a silently short value."""

    __slots__ = ("data", "pos", "strings")

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0
        self.strings = list(STATIC_STRINGS)

    def take(self, n: int) -> bytes:
        end = self.pos + n
        if end > len(self.data):
            raise CodecError("truncated binary payload")
        out = self.data[self.pos:end]
        self.pos = end
        return out

    def varint(self) -> int:
        shift = 0
        out = 0
        while True:  # bounded: take() raises on truncation, 10-byte cap
            byte = self.take(1)[0]
            out |= (byte & 0x7F) << shift
            if not byte & 0x80:
                return out
            shift += 7
            if shift > 2048:  # DoS guard, far above any real int
                raise CodecError("varint too long")

    def value(self):
        tag = self.take(1)[0]
        if tag == _T_NULL:
            return None
        if tag == _T_TRUE:
            return True
        if tag == _T_FALSE:
            return False
        if tag == _T_INT:
            z = self.varint()
            return (z >> 1) if not z & 1 else -((z + 1) >> 1)
        if tag == _T_FLOAT:
            return struct.unpack(">d", self.take(8))[0]
        if tag == _T_STR:
            try:
                s = self.take(self.varint()).decode()
            except UnicodeDecodeError as exc:
                raise CodecError(f"invalid UTF-8 in string: {exc}") from None
            self.strings.append(s)
            return s
        if tag == _T_STRREF:
            idx = self.varint()
            if idx >= len(self.strings):
                raise CodecError(f"string ref {idx} out of range")
            return self.strings[idx]
        if tag == _T_LIST:
            return [self.value() for _ in range(self.varint())]
        if tag == _T_DICT:
            out = {}
            for _ in range(self.varint()):
                key = self.value()
                if not isinstance(key, str):
                    raise CodecError(f"non-string dict key {key!r}")
                out[key] = self.value()
            return out
        raise CodecError(f"unknown tag 0x{tag:02x}")


def decode(data: bytes):
    """Decode one message produced by ``encode``. Raises CodecError on
    any malformed, truncated, or trailing-garbage input."""
    if not data:
        raise CodecError("empty binary payload")
    env = data[0]
    body = data[1:]
    if env == _ENV_DEFLATE:
        try:
            body = zlib.decompress(body)
        except zlib.error as exc:
            raise CodecError(f"bad deflate envelope: {exc}") from None
    elif env != _ENV_RAW:
        raise CodecError(f"unknown envelope 0x{env:02x}")
    reader = _Reader(body)
    out = reader.value()
    if reader.pos != len(body):
        raise CodecError(f"{len(body) - reader.pos} trailing bytes after "
                         f"value")
    return out


def frame_event(etype: str, obj_payload: bytes) -> bytes:
    """Splice one watch event around an already-encoded object payload
    (the serialize-once fan-out path): ``u32 length | u8 type-len |
    type | payload``."""
    type_raw = etype.encode()
    return struct.pack(">IB", 1 + len(type_raw) + len(obj_payload),
                       len(type_raw)) + type_raw + obj_payload


def parse_event(payload: bytes) -> tuple[str, object]:
    """Inverse of ``frame_event`` given the payload AFTER the u32 length
    prefix (the stream reader consumed it). Returns ``(type, object)``."""
    if not payload:
        raise CodecError("empty watch frame")
    tlen = payload[0]
    if 1 + tlen > len(payload):
        raise CodecError("truncated watch frame type")
    etype = payload[1:1 + tlen].decode("ascii", errors="replace")
    return etype, decode(payload[1 + tlen:])


def accepts_binary(header_value: str | None) -> bool:
    """Does an ``Accept``/``Content-Type`` header name the binary media
    type? Negotiation is exact-ish (parameters ignored); anything else
    stays on the JSON default/debug path."""
    if not header_value:
        return False
    return BINARY_CONTENT_TYPE in header_value or \
        "vnd.ktpu.v1.binary" in header_value
