"""Kubernetes Event recording + the Notebook re-emission helpers.

The reference controllers surface workload failures on the CR by re-emitting
StatefulSet/Pod events as Notebook events (notebook_controller.go:99-126) and
by recording first-party events (e.g. the MLflow ClusterRole-pending warning,
odh notebook_mlflow.go:259-260). controller-runtime provides the recorder;
here it is an explicit ``EventRecorder`` over the in-process apiserver with
the same aggregation semantics the k8s event machinery gives Eventf: repeated
identical events bump ``count``/``lastTimestamp`` instead of piling up new
objects.
"""

from __future__ import annotations

import calendar
import hashlib
import threading
import time
from typing import Callable

from ..utils import k8s, sanitizer

EVENT_KIND = "Event"

TYPE_NORMAL = "Normal"
TYPE_WARNING = "Warning"

# the real apiserver expires Events after --event-ttl (1h default); the
# in-process store has no leases, so the recorder prunes on write instead
EVENT_TTL_SECONDS = 3600.0
_PRUNE_INTERVAL_SECONDS = 60.0


def _aggregation_suffix(uid: str, type_: str, reason: str,
                        message: str) -> str:
    """Deterministic name suffix keyed on the aggregation identity — repeated
    identical events hash to the same Event name, so the aggregation lookup is
    a single get instead of a namespace list scan (the k8s event machinery
    similarly keys its aggregator on a hashed tuple)."""
    h = hashlib.sha256(
        "\x00".join((uid, type_, reason, message)).encode()).hexdigest()
    return h[:16]


def _parse_iso(ts: str) -> float | None:
    """RFC3339 seconds ("...:00Z") or MicroTime ("...:00.000000Z", the
    events.k8s.io eventTime shape) → epoch seconds; None if unparseable."""
    if isinstance(ts, str) and "." in ts:
        ts = ts.split(".")[0] + "Z"
    try:
        return calendar.timegm(time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ"))
    except (ValueError, TypeError):
        return None


class EventRecorder:
    """record.EventRecorder analog: writes ``Event`` objects to the store.

    Event names follow the kubelet convention ``<involved>.<suffix>``; the
    suffix is the aggregation hash (upstream uses the nanosecond clock plus a
    separate aggregator — fusing them keeps lookups O(1) and tests
    deterministic). Expired events are pruned opportunistically on write,
    standing in for the apiserver's --event-ttl lease expiry.
    """

    def __init__(self, client, component: str = "notebook-controller",
                 ttl_seconds: float = EVENT_TTL_SECONDS,
                 clock: Callable[[], float] = time.time):
        self.client = client
        self.component = component
        self.ttl_seconds = ttl_seconds
        # injected wall clock: TTL pruning compares Event timestamps, so
        # tests can age events without sleeping
        self.clock = clock
        self._lock = sanitizer.tracked_lock(
            "events.recorder", order=sanitizer.ORDER_LEAF)
        self._last_prune: dict[str, float] = {}  # namespace → monotonic time

    def eventf(self, involved: dict, type_: str, reason: str,
               message: str) -> dict:
        """Record an event on ``involved``; aggregates with an existing event
        carrying the same (involvedObject.uid, type, reason, message)."""
        namespace = k8s.namespace(involved) or "default"
        ref = {
            "kind": k8s.kind(involved),
            "namespace": namespace,
            "name": k8s.name(involved),
            "uid": k8s.uid(involved),
            "apiVersion": k8s.get_in(involved, "apiVersion", default=""),
        }
        now = k8s.now_iso()
        suffix = _aggregation_suffix(ref["uid"], type_, reason, message)
        event_name = f"{ref['name']}.{suffix}"
        self._maybe_prune(namespace)
        # CREATE-first: a fresh event (the fan-out common case — every bind
        # or repair transition emits one) costs ONE wire round trip; only
        # an aggregation (AlreadyExists) pays the read-modify-update. The
        # write races under concurrent reconcile workers keep the same
        # convergence: a lost create falls into the update branch and a
        # conflicting update re-reads — bounded retries, never an
        # exception for an aggregation race.
        from .errors import AlreadyExistsError, ConflictError, NotFoundError
        existing = None
        first_attempt = True
        for _attempt in range(3):
            existing = None if first_attempt else \
                self.client.get_or_none(EVENT_KIND, namespace, event_name)
            first_attempt = False
            if existing is not None:
                existing = k8s.deepcopy(existing)
                existing["count"] = int(existing.get("count", 1)) + 1
                existing["lastTimestamp"] = now
                try:
                    return self.client.update(existing)
                except (ConflictError, NotFoundError):
                    continue  # concurrent bump or prune; re-read
            event = {
                "apiVersion": "v1",
                "kind": EVENT_KIND,
                "metadata": {
                    "name": event_name,
                    "namespace": namespace,
                },
                "involvedObject": ref,
                "type": type_,
                "reason": reason,
                "message": message,
                "count": 1,
                "firstTimestamp": now,
                "lastTimestamp": now,
                "source": {"component": self.component},
            }
            try:
                return self.client.create(event)
            except AlreadyExistsError:
                continue  # lost the create race; aggregate onto the winner
        # kept racing; events are best-effort telemetry — surface the last
        # observed aggregate rather than raising into the reconcile loop
        return existing or {}

    def _maybe_prune(self, namespace: str) -> None:
        """Delete events whose lastTimestamp is past the TTL. Amortized: at
        most one namespace scan per _PRUNE_INTERVAL_SECONDS, so steady-state
        eventf stays O(1)."""
        now_mono = time.monotonic()
        with self._lock:
            last = self._last_prune.get(namespace, 0.0)
            if now_mono - last < _PRUNE_INTERVAL_SECONDS:
                return
            self._last_prune[namespace] = now_mono
        cutoff = self.clock() - self.ttl_seconds
        for ev in self.client.list(EVENT_KIND, namespace):
            # externally-created Events may carry only eventTime (events.k8s.io
            # shape) or none of the timestamps; never prune what we can't date
            stamp = (_parse_iso(ev.get("lastTimestamp", ""))
                     or _parse_iso(ev.get("firstTimestamp", ""))
                     or _parse_iso(ev.get("eventTime", "")))
            if stamp is not None and stamp < cutoff:
                try:
                    self.client.delete(EVENT_KIND, namespace, k8s.name(ev))
                except Exception:  # noqa: BLE001 — racing deletes are fine
                    pass


def is_sts_or_pod_event(event: dict) -> bool:
    """Reference isStsOrPodEvent (notebook_controller.go:700-702)."""
    kind = k8s.get_in(event, "involvedObject", "kind")
    return kind in ("Pod", "StatefulSet")


def nb_name_from_involved_object(client, event: dict,
                                 notebook_name_label: str) -> str | None:
    """Reference nbNameFromInvolvedObject (notebook_controller.go:704-731),
    hardened two ways:

    - STS events resolve through the STS's notebook-name label (the reference
      returns the STS name directly, which loses events for notebooks whose
      STS fell back to GenerateName "nb-" and misattributes events from
      foreign STSs that happen to share a notebook's name). The raw STS name
      is used only when the STS itself is already gone.
    - Pod events fall back to the pod's owning STS (pods are named
      ``<sts>-<ordinal>``) when the pod is already deleted — terminal events
      (OOMKilled, Evicted, Killing) usually outlive their pod.
    """
    involved = event.get("involvedObject", {})
    kind = involved.get("kind")
    name = involved.get("name")
    namespace = involved.get("namespace") or k8s.namespace(event)
    if kind == "StatefulSet":
        sts = client.get_or_none("StatefulSet", namespace, name)
        if sts is None:
            return name  # deleted STS: assume reference naming (STS = CR name)
        return k8s.get_label(sts, notebook_name_label)
    if kind == "Pod":
        pod = client.get_or_none("Pod", namespace, name)
        if pod is not None:
            return k8s.get_label(pod, notebook_name_label)
        sts_name, dash, ordinal = (name or "").rpartition("-")
        if dash and ordinal.isdigit():
            sts = client.get_or_none("StatefulSet", namespace, sts_name)
            if sts is not None:
                return k8s.get_label(sts, notebook_name_label)
        return None
    return None
