"""Re-export of the manager's Request/Result for cluster-side simulators,
avoiding a circular import (controllers.manager imports cluster.store)."""

from dataclasses import dataclass


@dataclass(frozen=True)
class Request:
    namespace: str
    name: str


@dataclass
class Result:
    requeue_after: float | None = None
