"""API Priority & Fairness for the apiserver facade (kube APF shape).

The real apiserver classifies every request into a FlowSchema, maps it to a
PriorityLevel with a share of the server's concurrency, and queues excess
demand in shuffle-sharded per-flow queues drained fairly — so a misbehaving
tenant's LIST storm saturates its own level's queues while repair, culling
and pool controllers keep their seats. This module reproduces that shape
in-process:

- ``FlowSchema`` — ordered match rules over (user-agent, verb, kind); the
  first match wins and its ``distinguisher`` buckets the request into a
  FLOW (default: the user agent — one tenant/client = one flow).
- ``PriorityLevel`` — a named share of the total seat count plus its queue
  discipline (queue count, per-queue length bound, shuffle-shard hand
  size). ``exempt`` levels bypass queuing entirely (health probes; watch
  streams are exempted by the caller — a seat held for a stream's lifetime
  would be a permanent leak of concurrency).
- ``APFDispatcher`` — seats + queues + fair dispatch:

  * a request is admitted immediately while its level is below its nominal
    limit AND has no queued backlog (FIFO within a level);
  * BORROWING: when every other level's queues are empty, an over-limit
    level may take idle seats up to the server total — an idle server
    never makes anyone wait (kube's borrowing, simplified to
    whole-seat granularity);
  * otherwise it waits in one of the level's queues — the queue is chosen
    by shuffle sharding (``hand_size`` candidate queues per flow, shortest
    wins), so one elephant flow can poison at most ``hand_size`` queues
    while mice hash around it;
  * seats freed by completions dispatch queued work fairly: levels below
    their limit first (round-robin), then borrowing levels; within a
    level, queues drain round-robin (each queue is FIFO per flow);
  * a full queue or an over-deadline wait REJECTS with 429 + Retry-After —
    the client's standard flow-control retry path (RetryPolicy retries
    429 on every verb).

Metrics (attach_metrics; pinned in tests/test_observability.py):
``apf_dispatched_total{priority_level}``,
``apf_rejected_total{priority_level}``,
``apf_current_inqueue{priority_level}``.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable

from ..utils import sanitizer

#: default seat count: concurrency the facade will execute simultaneously.
#: Sized well above a healthy control plane's in-flight request count (a
#: 4-worker manager keeps ≤ ~6 requests in flight) so APF only engages
#: under genuine overload — exactly when it should.
DEFAULT_TOTAL_SEATS = 16
#: how long a queued request may wait for a seat before 429
DEFAULT_QUEUE_WAIT_S = 5.0
#: Retry-After hint on rejections — long enough to shed load, short enough
#: that a healthy retry lands inside the same reconcile attempt
REJECT_RETRY_AFTER_S = 0.5


class RejectedError(Exception):
    """Request rejected by priority & fairness (queue full or wait
    deadline exceeded) — surfaces as 429 + Retry-After on the wire."""

    def __init__(self, level: str, reason: str,
                 retry_after_s: float = REJECT_RETRY_AFTER_S) -> None:
        super().__init__(f"APF rejected ({level}): {reason}")
        self.level = level
        self.retry_after_s = retry_after_s


@dataclass(frozen=True)
class PriorityLevel:
    name: str
    shares: int                 # nominal fraction of total seats
    queues: int = 16            # shuffle-sharded queue count
    queue_length: int = 128     # per-queue bound; full → 429
    hand_size: int = 2          # candidate queues per flow
    exempt: bool = False        # bypass seats/queues entirely


@dataclass(frozen=True)
class FlowSchema:
    """First-match-wins classification rule. ``match`` sees a request meta
    dict ({user_agent, verb, kind}); ``distinguisher`` buckets matching
    requests into flows (fairness is per flow within a level)."""

    name: str
    priority_level: str
    match: Callable[[dict], bool]
    distinguisher: Callable[[dict], str] = \
        field(default=lambda meta: meta.get("user_agent") or "anonymous")


#: our manager transport identifies itself with this prefix (HttpApiClient
#: user_agent default); anything else is tenant/tooling traffic
CONTROLLER_UA_PREFIX = "kubeflow-tpu"

DEFAULT_LEVELS: tuple[PriorityLevel, ...] = (
    # election heartbeats: starving Lease renewals collapses shard/leader
    # ownership fleet-wide, so they get their own guaranteed seats
    PriorityLevel("leader-election", shares=10, queues=8, queue_length=64),
    # controller reconcile traffic (the repair/culling/pool hot path)
    PriorityLevel("workload-high", shares=40),
    # everything else: tenants, dashboards, kubectl-ish tooling
    PriorityLevel("global-default", shares=20),
)

DEFAULT_SCHEMAS: tuple[FlowSchema, ...] = (
    FlowSchema("system-leases", "leader-election",
               match=lambda meta: meta.get("kind") == "Lease"),
    FlowSchema("kubeflow-controllers", "workload-high",
               match=lambda meta: (meta.get("user_agent") or "").startswith(
                   CONTROLLER_UA_PREFIX)),
    FlowSchema("catch-all", "global-default", match=lambda meta: True),
)


class _Level:
    """Runtime state for one priority level (guarded by the dispatcher
    lock): in-flight seat count + the shuffle-sharded wait queues."""

    __slots__ = ("config", "limit", "in_flight", "queues", "queued",
                 "rr_next")

    def __init__(self, config: PriorityLevel, limit: int) -> None:
        self.config = config
        self.limit = limit
        self.in_flight = 0
        self.queues: list[deque] = [deque() for _ in range(config.queues)]
        self.queued = 0          # total waiters across queues
        self.rr_next = 0         # round-robin drain cursor


class _Waiter:
    __slots__ = ("event", "admitted", "abandoned")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.admitted = False
        self.abandoned = False


class APFDispatcher:
    def __init__(self,
                 levels: tuple[PriorityLevel, ...] = DEFAULT_LEVELS,
                 schemas: tuple[FlowSchema, ...] = DEFAULT_SCHEMAS,
                 total_seats: int = DEFAULT_TOTAL_SEATS,
                 queue_wait_s: float = DEFAULT_QUEUE_WAIT_S) -> None:
        self.total_seats = max(1, int(total_seats))
        self.queue_wait_s = queue_wait_s
        self.schemas = tuple(schemas)
        self._lock = sanitizer.tracked_lock(
            "apf.dispatcher", order=sanitizer.ORDER_WATCH, no_blocking=True)
        active = [lv for lv in levels if not lv.exempt]
        total_shares = sum(lv.shares for lv in active) or 1
        self._levels: dict[str, _Level] = {}
        for lv in levels:
            limit = max(1, round(self.total_seats * lv.shares /
                                 total_shares)) if not lv.exempt else 0
            self._levels[lv.name] = _Level(lv, limit)
        self._total_in_flight = 0
        # classification fallback for a schema naming an unknown level
        # (or no schema matching): global-default when configured, else
        # the first non-exempt level — never a KeyError mid-request
        self._fallback_level = self._levels.get("global-default") or \
            next((lv for lv in self._levels.values()
                  if not lv.config.exempt),
                 next(iter(self._levels.values())))
        self._rr_levels = itertools.cycle(
            [lv.name for lv in levels if not lv.exempt])
        self._dispatched = None
        self._rejected = None
        self._inqueue = None

    # ------------------------------------------------------------- metrics
    def attach_metrics(self, registry) -> None:
        self._dispatched = registry.counter(
            "apf_dispatched_total",
            "Requests that got a seat, by priority level (borrowed seats "
            "included).")
        self._rejected = registry.counter(
            "apf_rejected_total",
            "Requests rejected with 429 by priority & fairness (queue "
            "full or wait deadline), by priority level.")
        self._inqueue = registry.gauge(
            "apf_current_inqueue",
            "Requests currently waiting in this priority level's queues.")

    def _set_inqueue(self, level: _Level) -> None:
        if self._inqueue is not None:
            self._inqueue.set(level.queued,
                              {"priority_level": level.config.name})

    # -------------------------------------------------------------- policy
    def classify(self, meta: dict) -> tuple[str, str]:
        """(priority level name, flow key) for a request meta dict."""
        for schema in self.schemas:
            try:
                if schema.match(meta):
                    return schema.priority_level, schema.distinguisher(meta)
            except Exception:  # noqa: BLE001 — a broken rule must not 500
                continue       # every request; fall through to the next
        return "global-default", meta.get("user_agent") or "anonymous"

    def _others_idle_locked(self, name: str) -> bool:
        return all(lv.queued == 0 for n, lv in self._levels.items()
                   if n != name and not lv.config.exempt)

    def _admit_locked(self, level: _Level) -> bool:
        """Seat available for a NEW arrival at this level right now? The
        server-wide seat total binds BOTH branches: a level below its
        nominal limit still queues while borrowers hold the last seats —
        the dispatch loop prefers under-limit levels as completions
        reclaim the borrowed seats, so the guarantee is restored one
        completion at a time rather than by over-admitting past the cap."""
        if self._total_in_flight >= self.total_seats:
            return False
        if level.in_flight < level.limit and level.queued == 0:
            return True
        # borrowing: idle seats serve an over-limit level only while no
        # other level has backlog those seats should serve first
        return (level.queued == 0
                and self._others_idle_locked(level.config.name))

    def _shuffle_queue_locked(self, level: _Level, flow: str) -> deque:
        """Shuffle sharding: hash the flow onto ``hand_size`` candidate
        queues, take the shortest — an elephant flow fills its hand while
        other flows almost surely have an uncontended candidate."""
        from ..controllers.sharding import fnv1a
        cfg = level.config
        hand = [fnv1a(f"{flow}\x00{i}") % cfg.queues
                for i in range(max(1, cfg.hand_size))]
        return min((level.queues[i] for i in hand), key=len)

    # ------------------------------------------------------------ lifecycle
    def acquire(self, meta: dict) -> str:
        """Block until the request holds a seat; returns the level name
        (the ticket for release()). Raises RejectedError → 429."""
        return self.acquire_info(meta)[0]

    def acquire_info(self, meta: dict) -> tuple[str, bool]:
        """``acquire`` plus dispatch provenance: ``(ticket, queued)`` where
        ``queued`` is True when the request sat in a priority-level queue
        before getting its seat (what the server's ``apf.wait`` span
        records) rather than being admitted immediately."""
        name, flow = self.classify(meta)
        level = self._levels.get(name) or self._fallback_level
        name = level.config.name  # the release ticket must name a REAL level
        if level.config.exempt:
            return name, False
        waiter = None
        with self._lock:
            if self._admit_locked(level):
                level.in_flight += 1
                self._total_in_flight += 1
                if self._dispatched is not None:
                    self._dispatched.inc({"priority_level": name})
                return name, False
            queue = self._shuffle_queue_locked(level, flow)
            if len(queue) >= level.config.queue_length:
                if self._rejected is not None:
                    self._rejected.inc({"priority_level": name})
                raise RejectedError(name, "queue full")
            waiter = _Waiter()
            queue.append(waiter)
            level.queued += 1
            self._set_inqueue(level)
        if waiter.event.wait(self.queue_wait_s):
            return name, True  # dispatched by a releasing request
        with self._lock:
            if waiter.admitted:
                # the dispatch raced our timeout and won: we hold a seat
                return name, True
            waiter.abandoned = True  # lazily skipped at dispatch
            level.queued -= 1
            self._set_inqueue(level)
            if self._rejected is not None:
                self._rejected.inc({"priority_level": name})
        raise RejectedError(name, "queue wait deadline exceeded")

    def release(self, ticket: str) -> None:
        level = self._levels.get(ticket)
        if level is None or level.config.exempt:
            return
        with self._lock:
            level.in_flight = max(0, level.in_flight - 1)
            self._total_in_flight = max(0, self._total_in_flight - 1)
            self._dispatch_locked()

    def _pop_waiter_locked(self, level: _Level) -> _Waiter | None:
        """Next live waiter from the level's queues, round-robin across
        queues (per-queue FIFO = per-flow FIFO after shuffle sharding)."""
        cfg = level.config
        for off in range(cfg.queues):
            queue = level.queues[(level.rr_next + off) % cfg.queues]
            while queue:
                waiter = queue.popleft()
                if waiter.abandoned:
                    continue  # timed out while queued; already uncounted
                level.rr_next = (level.rr_next + off + 1) % cfg.queues
                return waiter
        return None

    def _dispatch_locked(self) -> None:
        """Hand freed seats to queued work: levels below their nominal
        limit first, then borrowing levels while seats stay idle."""
        while self._total_in_flight < self.total_seats:
            candidate = None
            # one full rotation over levels below their limit with backlog
            for _ in range(len(self._levels)):
                name = next(self._rr_levels)
                lv = self._levels[name]
                if lv.queued > 0 and lv.in_flight < lv.limit:
                    candidate = lv
                    break
            if candidate is None:
                # no under-limit backlog: borrow for any backlog at all
                for _ in range(len(self._levels)):
                    name = next(self._rr_levels)
                    lv = self._levels[name]
                    if lv.queued > 0:
                        candidate = lv
                        break
            if candidate is None:
                return
            waiter = self._pop_waiter_locked(candidate)
            if waiter is None:
                candidate.queued = 0  # defensive: queues were all ghosts
                self._set_inqueue(candidate)
                continue
            candidate.queued -= 1
            candidate.in_flight += 1
            self._total_in_flight += 1
            waiter.admitted = True
            self._set_inqueue(candidate)
            if self._dispatched is not None:
                self._dispatched.inc(
                    {"priority_level": candidate.config.name})
            waiter.event.set()

    # --------------------------------------------------------- introspection
    def snapshot(self) -> dict:
        """{level: {in_flight, queued, limit}} — test/debug introspection."""
        with self._lock:
            return {name: {"in_flight": lv.in_flight, "queued": lv.queued,
                           "limit": lv.limit}
                    for name, lv in self._levels.items()}

