"""Declarative chaos-experiment schema validation.

Reference: chaos/experiments/*.yaml are ChaosExperiment CRs for an external
chaos operator (pod-kill tier 1 … webhook-disrupt tier 4) against a
steady-state/recovery model in chaos/knowledge/workbenches.yaml; CI only
schema-validates them (.github/workflows/operator_chaos_validation.yaml).
This module is that validator, used by tests/test_chaos_experiments.py (and
usable from CI directly: ``python -m kubeflow_tpu.cluster.experiments``).
"""

from __future__ import annotations

import sys
from pathlib import Path

import yaml

EXPERIMENT_KIND = "ChaosExperiment"
VALID_INJECTIONS = {"PodKill", "NetworkPartition", "WebhookDisrupt",
                    "RBACRevoke", "DeploymentScaleZero", "SliceWorkerKill"}
VALID_CHECK_TYPES = {"conditionTrue", "resourceExists", "httpGet",
                     "sliceAtomic"}


def _require(cond: bool, errors: list[str], msg: str) -> None:
    if not cond:
        errors.append(msg)


def validate_experiment(doc: dict) -> list[str]:
    """Returns a list of schema violations (empty = valid)."""
    errors: list[str] = []
    _require(doc.get("kind") == EXPERIMENT_KIND, errors,
             f"kind must be {EXPERIMENT_KIND}")
    _require(bool((doc.get("metadata") or {}).get("name")), errors,
             "metadata.name required")
    spec = doc.get("spec") or {}
    _require(isinstance(spec.get("tier"), int) and 1 <= spec["tier"] <= 4,
             errors, "spec.tier must be an int in 1..4")
    target = spec.get("target") or {}
    for key in ("operator", "component", "resource"):
        _require(bool(target.get(key)), errors, f"spec.target.{key} required")
    steady = spec.get("steadyState") or {}
    _require(bool(steady.get("timeout")), errors,
             "spec.steadyState.timeout required")
    checks = steady.get("checks") or []
    _require(bool(checks), errors, "spec.steadyState.checks must be non-empty")
    for i, check in enumerate(checks):
        _require(check.get("type") in VALID_CHECK_TYPES, errors,
                 f"checks[{i}].type must be one of {sorted(VALID_CHECK_TYPES)}")
    injection = spec.get("injection") or {}
    _require(injection.get("type") in VALID_INJECTIONS, errors,
             f"spec.injection.type must be one of {sorted(VALID_INJECTIONS)}")
    hypothesis = spec.get("hypothesis") or {}
    _require(bool(hypothesis.get("description")), errors,
             "spec.hypothesis.description required")
    _require(bool(hypothesis.get("recoveryTimeout")), errors,
             "spec.hypothesis.recoveryTimeout required")
    blast = spec.get("blastRadius") or {}
    _require(bool(blast.get("allowedNamespaces")), errors,
             "spec.blastRadius.allowedNamespaces required")
    return errors


def validate_file(path: str | Path) -> list[str]:
    errors = []
    for doc in yaml.safe_load_all(Path(path).read_text()):
        if doc is None:
            continue
        errors.extend(f"{path}: {e}" for e in validate_experiment(doc))
    return errors


def validate_dir(path: str | Path) -> list[str]:
    errors = []
    files = sorted(Path(path).glob("*.yaml"))
    if not files:
        errors.append(f"{path}: no experiment files found")
    for f in files:
        errors.extend(validate_file(f))
    return errors


if __name__ == "__main__":
    target = sys.argv[1] if len(sys.argv) > 1 else "chaos/experiments"
    problems = validate_dir(target)
    for p in problems:
        print(p)
    raise SystemExit(1 if problems else 0)
