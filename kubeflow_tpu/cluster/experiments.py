"""Declarative chaos experiments: schema validation AND an executable runner.

Reference: chaos/experiments/*.yaml are ChaosExperiment CRs for an external
chaos operator (pod-kill tier 1 … webhook-disrupt tier 4) against a
steady-state/recovery model in chaos/knowledge/workbenches.yaml; the
reference CI only schema-validates them
(.github/workflows/operator_chaos_validation.yaml). This module keeps that
validator (used by tests/test_chaos_experiments.py and the
chaos_validation workflow) and adds what the reference never had: a RUNNER
that interprets the same documents against the in-process cluster over the
real-wire transport — ``python -m kubeflow_tpu.cluster.experiments --run``.

Runner model (one ephemeral cluster per experiment):

- the "cluster" is ClusterStore + server-side admission webhooks + the
  StatefulSet simulator behind an ``ApiServerProxy`` (audit tap on);
- the "controller Deployment" is a full ``setup_controllers`` manager —
  reconcilers, read cache, circuit breaker, healthz/readyz — speaking
  REAL HTTP through ``HttpApiClient``;
- injections map to the wire/process seams: NetworkPartition stops the
  proxy (socket gone), WebhookDisrupt and RBACRevoke arm a ``FaultPlan``
  (admission path 500s / blanket 403s), PodKill and DeploymentScaleZero
  stop/start the manager, SliceWorkerKill deletes a worker pod;
- steadyState checks translate: ``conditionTrue`` on Notebook → the
  driven notebooks' conditions; on Deployment → the manager pool is
  alive; ``httpGet`` → the manager's health endpoints; ``resourceExists``
  → the store; ``sliceAtomic`` → every notebook StatefulSet sits at 0 or
  its full worker count;
- durations and ``recoveryTimeout`` scale by ``--time-scale`` (cluster
  minutes → in-process seconds) with floors, and the audit trail is
  checked for duplicate creates (no double side-effect writes) at the
  end of every experiment.
"""

from __future__ import annotations

import json
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path

import yaml

EXPERIMENT_KIND = "ChaosExperiment"
VALID_INJECTIONS = {"PodKill", "NetworkPartition", "WebhookDisrupt",
                    "RBACRevoke", "DeploymentScaleZero", "SliceWorkerKill",
                    "NodePreemption", "PoolDrainPreemption",
                    "ElasticPreemption", "SchedulerPreemptionCascade"}
VALID_CHECK_TYPES = {"conditionTrue", "resourceExists", "httpGet",
                     "sliceAtomic", "notQuarantined", "notebookMigrated",
                     "poolRewarmed", "elasticResized", "gangAdmitted",
                     "noReservationLeak"}


def _require(cond: bool, errors: list[str], msg: str) -> None:
    if not cond:
        errors.append(msg)


def validate_experiment(doc: dict) -> list[str]:
    """Returns a list of schema violations (empty = valid)."""
    errors: list[str] = []
    _require(doc.get("kind") == EXPERIMENT_KIND, errors,
             f"kind must be {EXPERIMENT_KIND}")
    _require(bool((doc.get("metadata") or {}).get("name")), errors,
             "metadata.name required")
    spec = doc.get("spec") or {}
    _require(isinstance(spec.get("tier"), int) and 1 <= spec["tier"] <= 4,
             errors, "spec.tier must be an int in 1..4")
    target = spec.get("target") or {}
    for key in ("operator", "component", "resource"):
        _require(bool(target.get(key)), errors, f"spec.target.{key} required")
    steady = spec.get("steadyState") or {}
    _require(bool(steady.get("timeout")), errors,
             "spec.steadyState.timeout required")
    checks = steady.get("checks") or []
    _require(bool(checks), errors, "spec.steadyState.checks must be non-empty")
    for i, check in enumerate(checks):
        _require(check.get("type") in VALID_CHECK_TYPES, errors,
                 f"checks[{i}].type must be one of {sorted(VALID_CHECK_TYPES)}")
    injection = spec.get("injection") or {}
    _require(injection.get("type") in VALID_INJECTIONS, errors,
             f"spec.injection.type must be one of {sorted(VALID_INJECTIONS)}")
    hypothesis = spec.get("hypothesis") or {}
    _require(bool(hypothesis.get("description")), errors,
             "spec.hypothesis.description required")
    _require(bool(hypothesis.get("recoveryTimeout")), errors,
             "spec.hypothesis.recoveryTimeout required")
    blast = spec.get("blastRadius") or {}
    _require(bool(blast.get("allowedNamespaces")), errors,
             "spec.blastRadius.allowedNamespaces required")
    return errors


def validate_file(path: str | Path) -> list[str]:
    errors = []
    for doc in yaml.safe_load_all(Path(path).read_text()):
        if doc is None:
            continue
        errors.extend(f"{path}: {e}" for e in validate_experiment(doc))
    return errors


def validate_dir(path: str | Path) -> list[str]:
    errors = []
    files = sorted(Path(path).glob("*.yaml"))
    if not files:
        errors.append(f"{path}: no experiment files found")
    for f in files:
        errors.extend(validate_file(f))
    return errors


# --------------------------------------------------------------------------
# executable runner
# --------------------------------------------------------------------------

def parse_duration_s(raw) -> float:
    """'30s' / '2m' / bare numbers → seconds."""
    if isinstance(raw, (int, float)):
        return float(raw)
    raw = str(raw).strip()
    if raw.endswith("ms"):
        return float(raw[:-2]) / 1000.0
    if raw.endswith("s"):
        return float(raw[:-1])
    if raw.endswith("m"):
        return float(raw[:-1]) * 60.0
    return float(raw)


def audit_duplicate_creates(audit_path: str | Path) -> list[str]:
    """Replay an apiserver audit trail and report duplicate side-effect
    writes: a second 201 for the same (collection, name) without an
    intervening successful DELETE means a retried create double-applied —
    exactly the bug the ambiguous-retry disambiguation exists to prevent.
    (A kill-then-recreate of the same pod is NOT a duplicate: the DELETE
    resets the slot.)"""
    problems: list[str] = []
    live: dict[tuple[str, str], bool] = {}
    path = Path(audit_path)
    if not path.exists():
        return problems
    for line in path.read_text().splitlines():
        try:
            entry = json.loads(line)
        except ValueError:
            problems.append(f"unparseable audit line: {line[:80]}")
            continue
        verb, status = entry.get("verb"), entry.get("status")
        if verb == "POST" and status == 201:
            key = (entry.get("path", ""), entry.get("name", ""))
            if live.get(key):
                problems.append(
                    f"duplicate create: {key[0]}/{key[1]} got a second 201 "
                    f"with no delete in between")
            live[key] = True
        elif verb == "DELETE" and status == 200:
            collection, _, name = entry.get("path", "").rpartition("/")
            live[(collection, name)] = False
    return problems


@dataclass
class ExperimentResult:
    name: str
    passed: bool
    failures: list[str] = field(default_factory=list)
    duration_s: float = 0.0
    injected_faults: int = 0

    def __str__(self) -> str:
        status = "PASS" if self.passed else "FAIL"
        line = f"{status}  {self.name}  ({self.duration_s:.1f}s)"
        for failure in self.failures:
            line += f"\n      - {failure}"
        return line


class _MiniCluster:
    """One ephemeral in-process cluster + a real-wire manager, torn down
    per experiment so injections can't leak across runs."""

    CONTROLLER_CRB = "kubeflow-tpu-notebook-controller"

    def __init__(self, namespace: str, accelerator: str,
                 audit_path: str, workers: int = 4) -> None:
        # heavy imports stay lazy: the schema-validation CLI must run in
        # a pyyaml-only environment (the chaos_validation workflow)
        from ..api import types as api
        from ..controllers import setup_controllers
        from ..controllers.manager import Manager
        from ..utils.config import ControllerConfig
        from ..utils.metrics import MetricsRegistry
        from ..webhook import (NotebookMutatingWebhook,
                               NotebookValidatingWebhook)
        from .apiserver import ApiServerProxy
        from .http_client import HttpApiClient
        from .kubelet import StatefulSetSimulator
        from .store import ClusterStore

        self.api = api
        self.namespace = namespace
        self.accelerator = accelerator
        self.audit_path = audit_path
        self.config = ControllerConfig()
        self.store = ClusterStore()
        api.install_notebook_crd(self.store)
        from ..api.slicepool import install_slicepool_crd
        install_slicepool_crd(self.store)
        from ..api.tpuquota import install_tpuquota_crd
        install_tpuquota_crd(self.store)
        # set by the PoolDrainPreemption injection: (notebook, old bound
        # slice, identity, checkpointed step) the migrated check verifies
        self.expect_migrated_from: tuple | None = None
        # set by the ElasticPreemption injection: the simulated
        # trainer-side agent the elasticResized check reads
        self.elastic_agent = None
        # server-side admission, where kube-apiserver runs it — remote
        # managers get mutated objects and denials over the wire
        NotebookMutatingWebhook(self.store, self.config).install(self.store)
        NotebookValidatingWebhook(self.config).install(self.store)
        # the controller's own RBAC, so resourceExists checks have a
        # real object to find (and RBACRevoke has something to 'revoke')
        self.store.create({
            "kind": "ClusterRoleBinding",
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "metadata": {"name": self.CONTROLLER_CRB},
            "roleRef": {"kind": "ClusterRole",
                        "name": self.CONTROLLER_CRB},
            "subjects": [{"kind": "ServiceAccount",
                          "name": "kubeflow-tpu-controller",
                          "namespace": "kubeflow-tpu-system"}],
        })
        self._proxy_cls = ApiServerProxy
        self._client_cls = HttpApiClient
        self._setup_controllers = setup_controllers
        self._metrics_cls = MetricsRegistry
        self._workers = workers
        self.sim_mgr = None
        self.proxy = None
        self.client = None
        self.mgr = None
        self.notebooks: list[str] = []
        try:
            self.sim_mgr = Manager(self.store)
            StatefulSetSimulator(self.store,
                                 boot_delay_s=0.0).setup(self.sim_mgr)
            self.sim_mgr.start()
            self.proxy = ApiServerProxy(self.store, audit_log=audit_path)
            self.proxy.start()
            self.start_manager()
        except Exception:
            # partial construction (port bind failure, …): stop whatever
            # already started before letting the caller see the error
            self.close()
            raise

    def start_manager(self) -> None:
        """(Re)build the full manager 'pod': fresh transport client, fresh
        setup_controllers composition (reconcilers, read cache, breaker,
        health endpoints), started. The PodKill/scale-up analog — a new
        pod IS a new process with new watches."""
        self.client = self._client_cls(self.proxy.url)
        self.metrics = self._metrics_cls()
        self.mgr = self._setup_controllers(
            self.client, self.config, metrics=self.metrics, health_port=0,
            max_concurrent_reconciles=self._workers)
        self.mgr.start()

    def stop_manager(self) -> None:
        """Scale-to-zero / pod-kill: stop the pool AND close the client
        (a dead pod holds no watch connections)."""
        try:
            self.mgr.stop()
        finally:
            self.client.close()

    # ------------------------------------------------------------ driving
    def create_notebooks(self, count: int, prefix: str = "chaos-nb") -> None:
        from ..utils import names
        for i in range(count):
            name = f"{prefix}-{i}"
            self.store.create(self.api.new_notebook(
                name, self.namespace,
                annotations={names.TPU_ACCELERATOR_ANNOTATION:
                             self.accelerator}))
            self.notebooks.append(name)

    def expected_workers(self) -> int:
        from ..tpu import topology
        return topology.parse_short_name(self.accelerator).num_workers

    # ---------------------------------------------------------- warm pools
    def setup_pool(self, name: str, warm: int) -> None:
        from ..api.slicepool import new_slice_pool
        self.store.create(new_slice_pool(name, self.accelerator, warm))

    def pool_slices(self, state: str | None = None) -> list[dict]:
        from ..utils import k8s, names as nk
        out = []
        for sts in self.store.list("StatefulSet", None,
                                   {nk.POOL_LABEL: None}):
            if state is None or k8s.get_annotation(
                    sts, nk.POOL_STATE_ANNOTATION) == state:
                out.append(sts)
        return out

    def bound_slice_of(self, nb_name: str) -> str | None:
        from ..utils import k8s, names as nk
        nb = self.store.get_or_none(self.api.KIND, self.namespace, nb_name)
        return k8s.get_annotation(nb, nk.BOUND_SLICE_ANNOTATION) \
            if nb else None

    def slice_ready(self, name: str) -> bool:
        nb = self.store.get_or_none(self.api.KIND, self.namespace, name)
        cond = self.api.get_condition(nb, self.api.CONDITION_SLICE_READY) \
            if nb else None
        return bool(cond and cond.get("status") == "True")

    def converged(self) -> bool:
        return all(self.slice_ready(name) for name in self.notebooks)

    def wait(self, predicate, timeout: float, poll: float = 0.05) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(poll)
        return bool(predicate())

    def restart_proxy(self) -> None:
        """Bring the apiserver back on the SAME port (the outage heal)."""
        port = self.proxy.port
        self.proxy = self._proxy_cls(self.store, port=port,
                                     audit_log=self.audit_path)
        self.proxy.start()

    def health_get(self, path: str) -> int:
        import urllib.error
        import urllib.request
        url = f"http://127.0.0.1:{self.mgr.health_server.port}{path}"
        try:
            with urllib.request.urlopen(url, timeout=5.0) as resp:
                return resp.status
        except urllib.error.HTTPError as err:
            return err.code
        except (urllib.error.URLError, OSError):
            return 0

    # ------------------------------------------------------------- checks
    def run_checks(self, checks: list[dict]) -> list[str]:
        """steadyState checks → failure strings (empty = all green)."""
        failures = []
        for check in checks:
            ctype = check.get("type")
            try:
                ok, detail = getattr(self, f"_check_{ctype}")(check)
            except Exception as exc:  # noqa: BLE001 — a crashed check is a failed check
                ok, detail = False, f"check raised: {exc}"
            if not ok:
                failures.append(f"{ctype}: {detail}")
        return failures

    def _check_conditionTrue(self, check: dict):  # noqa: N802 — yaml name
        if check.get("kind") == "Notebook":
            cond_type = check.get("conditionType",
                                  self.api.CONDITION_SLICE_READY)
            for name in self.notebooks:
                nb = self.store.get_or_none(self.api.KIND, self.namespace,
                                            name)
                cond = self.api.get_condition(nb, cond_type) if nb else None
                if not cond or cond.get("status") != "True":
                    return False, f"notebook {name} {cond_type} not True"
            return True, ""
        # Deployment/Available of the controller itself → the manager
        # worker pool is alive (the in-process analog of the Deployment
        # keeping its replica Available)
        alive = self.mgr.is_alive()
        return alive, "" if alive else "manager worker pool not alive"

    def _check_resourceExists(self, check: dict):  # noqa: N802
        kind, name = check.get("kind"), check.get("name")
        namespace = check.get("namespace", "")
        obj = self.store.get_or_none(kind, namespace, name)
        return obj is not None, f"{kind} {name} not found"

    def _check_httpGet(self, check: dict):  # noqa: N802
        from urllib.parse import urlparse
        path = urlparse(check.get("url", "")).path or "/healthz"
        expect = int(check.get("expectStatus", 200))
        got = self.health_get(path)
        return got == expect, f"GET {path} = {got}, want {expect}"

    def _check_sliceAtomic(self, check: dict):  # noqa: N802
        full = self.expected_workers()
        stss = [self.store.get_or_none("StatefulSet", self.namespace, name)
                for name in self.notebooks]
        # pool-owned slices (warm/bound/draining) obey the same invariant:
        # replicas only ever 0 or the full worker count, never partial
        stss += self.pool_slices()
        for sts in stss:
            if sts is None:
                continue  # not created yet / culled — 0 by definition
            replicas = (sts.get("spec") or {}).get("replicas", 0)
            if replicas not in (0, full):
                return False, (f"STS {(sts.get('metadata') or {}).get('name')}"
                               f" at partial scale {replicas} (full={full})")
        return True, ""

    def _check_notQuarantined(self, check: dict):  # noqa: N802
        from ..utils import names as name_keys
        from ..utils.k8s import get_annotation
        for name in self.notebooks:
            nb = self.store.get_or_none(self.api.KIND, self.namespace, name)
            if nb is None:
                continue
            if get_annotation(nb, name_keys.QUARANTINE_ANNOTATION) \
                    is not None:
                return False, f"notebook {name} is quarantined"
            cond = self.api.get_condition(
                nb, self.api.CONDITION_SLICE_QUARANTINED)
            if cond and cond.get("status") == "True":
                return False, f"notebook {name} SliceQuarantined is True"
        return True, ""

    def _check_notebookMigrated(self, check: dict):  # noqa: N802
        """Every notebook is still pool-bound (no cold-roll fallback, no
        quarantine, no migration wedged in flight); when the injection
        recorded a pre-preemption slice, the notebook must now sit on a
        DIFFERENT slice with the SAME hostname identity and the resumed
        step must equal the checkpointed one (step continuity)."""
        from ..utils import names as nk
        from ..utils.k8s import get_annotation
        for name in self.notebooks:
            nb = self.store.get_or_none(self.api.KIND, self.namespace, name)
            if nb is None:
                return False, f"notebook {name} vanished"
            for ann, why in ((nk.QUARANTINE_ANNOTATION, "quarantined"),
                             (nk.MIGRATION_STATE_ANNOTATION,
                              "migration still in flight"),
                             (nk.POOL_BIND_MISS_ANNOTATION,
                              "fell back to a cold roll")):
                if get_annotation(nb, ann) is not None:
                    return False, f"notebook {name} {why}"
            if get_annotation(nb, nk.BOUND_SLICE_ANNOTATION) is None:
                return False, f"notebook {name} not pool-bound"
        if self.expect_migrated_from is not None:
            name, old_slice, identity, step = self.expect_migrated_from
            nb = self.store.get_or_none(self.api.KIND, self.namespace, name)
            bound = get_annotation(nb, nk.BOUND_SLICE_ANNOTATION)
            if bound == old_slice:
                return False, (f"notebook {name} still on pre-preemption "
                               f"slice {old_slice}")
            if get_annotation(nb, nk.SLICE_IDENTITY_ANNOTATION) != identity:
                return False, (f"notebook {name} changed hostname identity "
                               f"across migration")
            resumed = get_annotation(nb, nk.RESUMED_STEP_ANNOTATION)
            if resumed != step:
                return False, (f"notebook {name} resumed at step {resumed}, "
                               f"checkpointed at {step}")
        return True, ""

    def _check_elasticResized(self, check: dict):  # noqa: N802
        """The elastic run shrank AND grew back without a restart: the
        simulated agent saw ≥ 2 resizes, a monotone step counter and a
        continuous loss curve (zero violations), the handshake machine is
        back at Stable with current == requested slices, and virtual MFU
        stayed at/above the floor (default 0.9 of static-mesh)."""
        from ..utils import names as nk
        from ..utils.k8s import get_annotation
        agent = self.elastic_agent
        if agent is None:
            return True, ""  # armed by the injection; vacuous before it
        if agent.violations:
            return False, f"runtime violations: {agent.violations[:3]}"
        if agent.resizes < 2:
            return False, (f"expected a shrink AND a grow-back, saw "
                           f"{agent.resizes} resize(s)")
        nb = self.store.get_or_none(self.api.KIND, self.namespace,
                                    self.notebooks[0])
        if nb is None:
            return False, "elastic notebook vanished"
        if get_annotation(nb, nk.ELASTIC_RESIZE_ANNOTATION) is not None:
            return False, "resize handshake still in flight"
        requested = get_annotation(nb, nk.ELASTIC_SLICES_ANNOTATION)
        current = get_annotation(nb, nk.ELASTIC_CURRENT_SLICES_ANNOTATION)
        if requested != current:
            return False, (f"current slices {current} != requested "
                           f"{requested} — grow-back incomplete")
        min_mfu = float(check.get("minMfu", 0.9))
        if agent.mfu() < min_mfu:
            return False, (f"virtual MFU {agent.mfu():.3f} below the "
                           f"{min_mfu} floor ({agent.steps} steps, "
                           f"{agent.resizes} resizes)")
        return True, ""

    def _check_gangAdmitted(self, check: dict):  # noqa: N802
        """No gang is ever half-admitted, in ANY interleaving the sample
        catches: a Reserving/Admitted notebook carries a reservation
        matching its gang request, a reservation never rides any other
        state, and a gang the scheduler has queued never rolls its
        StatefulSet before the Admitted verdict."""
        from ..controllers.scheduler import (SCHED_ADMITTED, SCHED_RESERVING,
                                             gang_slices, sched_state)
        from ..utils import names as nk
        from ..utils.k8s import get_annotation
        for nb in self.store.list(self.api.KIND, self.namespace):
            name = (nb.get("metadata") or {}).get("name")
            state = sched_state(nb)
            reserved = get_annotation(nb, nk.SCHED_RESERVED_ANNOTATION)
            gang = gang_slices(nb)
            if state in (SCHED_RESERVING, SCHED_ADMITTED):
                if reserved is None:
                    return False, f"{name} is {state} with no reservation"
                if gang is not None and reserved != str(gang):
                    return False, (f"{name} reserved {reserved} for a "
                                   f"{gang}-slice gang — half-admitted")
            elif reserved is not None:
                return False, (f"{name} leaked reservation {reserved} "
                               f"in state {state}")
            if gang is not None and state is not None \
                    and state != SCHED_ADMITTED \
                    and self.store.get_or_none(
                        "StatefulSet", self.namespace, name) is not None:
                # grace-degrade rolls are legal only when the scheduler
                # never stamped ANY state — a queued gang must hold
                return False, f"{name} rolled while {state}, not Admitted"
        return True, ""

    def _check_noReservationLeak(self, check: dict):  # noqa: N802
        """Fleet usage re-derived from annotations never exceeds
        capacity, and every preemption hold names a preemptor that still
        wants the capacity — a cascade crashed at any phase boundary must
        leak neither a reservation nor a grow-back hold."""
        from ..controllers.scheduler import (SCHED_ADMITTED, SCHED_PENDING,
                                             SCHED_RESERVING,
                                             notebook_usage, sched_state)
        from ..utils import names as nk
        from ..utils.k8s import get_annotation
        capacity = int(check.get("capacity",
                                 self.config.sched_default_capacity))
        fleet = self.store.list(self.api.KIND, self.namespace)
        usage = sum(notebook_usage(nb) for nb in fleet)
        if usage > capacity:
            return False, f"fleet usage {usage} exceeds capacity {capacity}"
        for nb in fleet:
            hold = get_annotation(nb, nk.SCHED_PREEMPTED_ANNOTATION)
            if hold is None:
                continue
            ns, _, pname = hold.partition("/")
            preemptor = self.store.get_or_none(self.api.KIND, ns, pname) \
                if ns and pname else None
            if preemptor is None or sched_state(preemptor) not in (
                    SCHED_PENDING, SCHED_RESERVING, SCHED_ADMITTED):
                return False, ((nb.get("metadata") or {}).get("name", "?") +
                               f" carries a stale preemption hold from "
                               f"{hold}")
        return True, ""

    def _check_poolRewarmed(self, check: dict):  # noqa: N802
        """The pool holds warm (or actively re-warming) spare capacity —
        a consumed/drained slice was replaced, the pool did not bleed."""
        from ..utils import names as nk
        spares = self.pool_slices(nk.POOL_STATE_WARM) + \
            self.pool_slices(nk.POOL_STATE_WARMING)
        if not spares:
            return False, "pool has no warm/warming spare slice"
        return True, ""

    def close(self) -> None:
        # the agent thread first: it polls the store this teardown razes
        for attr, method in (("elastic_agent", "stop"), ("mgr", "stop"),
                             ("client", "close"),
                             ("proxy", "stop"), ("sim_mgr", "stop")):
            obj = getattr(self, attr, None)
            if obj is None:
                continue
            try:
                getattr(obj, method)()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


def _scaled(raw, scale: float, floor: float) -> float:
    return max(floor, parse_duration_s(raw) * scale)


def run_experiment(doc: dict, *, notebooks: int = 2,
                   time_scale: float = 0.02,
                   inject_floor_s: float = 0.75,
                   recovery_floor_s: float = 30.0,
                   workers: int = 4,
                   emit=print) -> ExperimentResult:
    """Execute one ChaosExperiment document end to end:
    steady state → injection → recovery + steadyState checks + audit
    idempotency. Returns a result; never raises for an experiment
    failure (the caller aggregates)."""
    import tempfile

    from .faults import FAULT_HTTP, FaultPlan, FaultRule

    name = (doc.get("metadata") or {}).get("name", "<unnamed>")
    spec = doc.get("spec") or {}
    schema_errors = validate_experiment(doc)
    if schema_errors:
        return ExperimentResult(name, False,
                                [f"schema: {e}" for e in schema_errors])
    injection = (spec.get("injection") or {})
    itype = injection.get("type")
    params = injection.get("parameters") or {}
    checks = (spec.get("steadyState") or {}).get("checks") or []
    t0 = time.monotonic()
    failures: list[str] = []
    accelerator = ("v5e-16" if itype in ("SliceWorkerKill", "NodePreemption",
                                         "PoolDrainPreemption",
                                         "ElasticPreemption",
                                         "SchedulerPreemptionCascade")
                   else "v5e-4")
    audit = tempfile.NamedTemporaryFile(suffix=".ndjson", delete=False)
    audit.close()
    duration = _scaled(params.get("duration", "30s"), time_scale,
                       inject_floor_s)
    recovery = _scaled((spec.get("hypothesis") or {})
                       .get("recoveryTimeout", "120s"),
                       time_scale, recovery_floor_s)
    plan = None
    cluster = None
    try:
        # construction INSIDE the try: a bind failure on a loaded CI box
        # must come back as a FAIL result, not abort the whole batch
        cluster = _MiniCluster("chaos-user", accelerator, audit.name,
                               workers=workers)
        # ------------------------------------------------ steady state
        if itype == "PoolDrainPreemption":
            # warm the pool FIRST so every notebook binds instead of
            # cold-rolling; capacity is notebooks + 1, so ONE warm spare
            # slice exists when the preemption lands — the migration
            # target
            from ..utils import names as nk
            cluster.setup_pool("chaos-pool", warm=notebooks + 1)
            if not cluster.wait(
                    lambda: len(cluster.pool_slices(nk.POOL_STATE_WARM))
                    >= notebooks + 1, timeout=60.0):
                failures.append("pool never warmed to target")
        cluster.create_notebooks(notebooks)
        if not cluster.wait(cluster.converged, timeout=60.0):
            failures.append("pre-injection convergence timeout")
        if itype == "PoolDrainPreemption":
            from ..utils import names as nk
            if not cluster.wait(
                    lambda: all(cluster.bound_slice_of(n)
                                for n in cluster.notebooks)
                    and cluster.pool_slices(nk.POOL_STATE_WARM),
                    timeout=60.0):
                failures.append("notebooks not all pool-bound with a warm "
                                "spare before injection")
        failures += [f"pre-injection {f}"
                     for f in cluster.run_checks(checks)]
        emit(f"  [{name}] steady at {notebooks} notebooks; injecting "
             f"{itype} for {duration:.2f}s (recovery bound "
             f"{recovery:.0f}s)")

        # ---------------------------------------------------- injection
        if itype in ("NetworkPartition",):
            cluster.proxy.stop()  # the wire is gone
            cluster.create_notebooks(1, prefix="outage-nb")
            time.sleep(duration)
            if cluster.health_get("/healthz") != 200:
                failures.append("manager healthz failed during partition "
                                "(hypothesis: process stays alive)")
            cluster.restart_proxy()
        elif itype == "DeploymentScaleZero":
            cluster.stop_manager()
            cluster.create_notebooks(1, prefix="scalezero-nb")
            time.sleep(duration)
            nb = cluster.notebooks[-1]
            if cluster.store.get_or_none("StatefulSet", cluster.namespace,
                                         nb) is not None:
                failures.append("notebook reconciled with zero controller "
                                "replicas (hypothesis: admitted but not "
                                "reconciled)")
            cluster.start_manager()  # scale back up: a NEW pod
        elif itype == "PodKill":
            # pod killed → Deployment recreates it: a fresh process with
            # fresh watches; its boot resync must pick up everything
            cluster.stop_manager()
            time.sleep(min(duration, 1.0))
            cluster.start_manager()
            cluster.create_notebooks(1, prefix="postkill-nb")
        elif itype == "WebhookDisrupt":
            # admission unreachable + failurePolicy=Fail ⇒ the apiserver
            # rejects Notebook CREATEs: model the gate at the wire
            plan = FaultPlan([FaultRule(FAULT_HTTP, 1.0, status=500,
                                        verbs=frozenset({"create"}),
                                        kinds=frozenset({"Notebook"}))])
            cluster.proxy.set_fault_plan(plan)
            from .errors import ApiError
            try:
                cluster.client.create(cluster.api.new_notebook(
                    "gated-nb", cluster.namespace))
                failures.append("create was ADMITTED while the webhook "
                                "was down (gate must fail closed)")
            except ApiError:
                pass  # fail-closed, as hypothesized
            time.sleep(duration)
            cluster.proxy.set_fault_plan(None)
            cluster.create_notebooks(1, prefix="postgate-nb")
        elif itype == "RBACRevoke":
            plan = FaultPlan([FaultRule(FAULT_HTTP, 1.0, status=403)])
            cluster.proxy.set_fault_plan(plan)
            cluster.create_notebooks(1, prefix="revoked-nb")
            time.sleep(duration)
            if cluster.mgr.breaker is not None and \
                    cluster.mgr.breaker.state != "closed":
                failures.append("breaker tripped on Forbidden responses "
                                "(403 is a live apiserver, not an outage)")
            cluster.proxy.set_fault_plan(None)
        elif itype == "NodePreemption":
            from .kubelet import kill_node, preempt_node
            ordinal = int(params.get("ordinal", 0))
            victim = f"{cluster.notebooks[0]}-{ordinal}"
            pod = cluster.store.get_or_none("Pod", cluster.namespace, victim)
            node_name = (pod.get("spec") or {}).get("nodeName") if pod \
                else None
            if not node_name:
                failures.append(f"worker {victim} has no node binding — "
                                f"kubelet node lifecycle not active")
            else:
                # GKE sequence: the impending-termination notice taint
                # first, then the node actually dies partway through the
                # injection window. Atomicity is sampled THROUGHOUT: the
                # repair must only ever roll the one STS 0 <-> full.
                preempt_node(cluster.store, node_name)
                deadline = time.monotonic() + duration
                kill_at = time.monotonic() + duration / 2
                killed = False
                while time.monotonic() < deadline:
                    if not killed and time.monotonic() >= kill_at:
                        kill_node(cluster.store, node_name)
                        killed = True
                    atomic = cluster.run_checks([{"type": "sliceAtomic"}])
                    if atomic:
                        failures += [f"during-preemption {f}"
                                     for f in atomic]
                        break
                    time.sleep(0.05)
                if not killed:
                    kill_node(cluster.store, node_name)
        elif itype == "PoolDrainPreemption":
            # preempt the node under worker 0 of a BOUND slice while the
            # pool holds a warm spare: the repair controller must
            # checkpoint, re-bind the spare under the SAME hostname
            # identity, and resume — and the pool must re-warm. Slice
            # atomicity is sampled throughout (pool slices included).
            from ..utils import names as nk
            from ..utils import names
            from ..utils.k8s import get_annotation, get_label
            from .kubelet import kill_node, preempt_node
            nb0 = cluster.notebooks[0]
            # simulate in-pod training progress the checkpoint must carry
            cluster.store.patch(cluster.api.KIND, cluster.namespace, nb0, {
                "metadata": {"annotations": {
                    nk.RUNTIME_STEP_ANNOTATION: "1337"}}})
            bound = cluster.bound_slice_of(nb0)
            nb_obj = cluster.store.get(cluster.api.KIND, cluster.namespace,
                                       nb0)
            cluster.expect_migrated_from = (
                nb0, bound,
                get_annotation(nb_obj, nk.SLICE_IDENTITY_ANNOTATION),
                "1337")
            node_name = None
            if bound:
                pool_ns, sts_name = bound.split("/", 1)
                for pod in cluster.store.list("Pod", pool_ns,
                                              {"statefulset": sts_name}):
                    if get_label(pod, names.POD_INDEX_LABEL) == "0":
                        node_name = (pod.get("spec") or {}).get("nodeName")
                        break
            if not node_name:
                failures.append(f"bound worker-0 of {nb0} has no node "
                                f"binding — nothing to preempt")
            else:
                preempt_node(cluster.store, node_name)
                deadline = time.monotonic() + duration
                kill_at = time.monotonic() + duration / 2
                killed = False
                while time.monotonic() < deadline:
                    if not killed and time.monotonic() >= kill_at:
                        kill_node(cluster.store, node_name)
                        killed = True
                    atomic = cluster.run_checks([{"type": "sliceAtomic"}])
                    if atomic:
                        failures += [f"during-preemption {f}"
                                     for f in atomic]
                        break
                    time.sleep(0.05)
                if not killed:
                    kill_node(cluster.store, node_name)
        elif itype == "ElasticPreemption":
            # preemption notice on one slice of an elastic multi-slice
            # training run: the controller must SHRINK the run (drain →
            # checkpoint → drop a slice) instead of stopping it, repair
            # the slice, then grow back — step counter monotone, loss
            # continuous, handshake machine back at Stable throughout.
            from ..runtime.elastic import SimulatedElasticAgent
            from ..utils import names as nk
            from .kubelet import kill_node, preempt_node
            nb0 = cluster.notebooks[0]
            slices = int(params.get("slices", 3))
            cluster.store.patch(cluster.api.KIND, cluster.namespace, nb0, {
                "metadata": {"annotations": {
                    nk.ELASTIC_ANNOTATION: "true",
                    nk.ELASTIC_SLICES_ANNOTATION: str(slices),
                    nk.ELASTIC_CURRENT_SLICES_ANNOTATION: str(slices),
                }}})
            cluster.elastic_agent = SimulatedElasticAgent(
                cluster.store, cluster.namespace, nb0,
                current_slices=slices).start()
            # let the virtual run bank productive steps before the blip,
            # as a real run would have
            cluster.wait(lambda: cluster.elastic_agent.steps >= 20,
                         timeout=30.0)
            ordinal = int(params.get("ordinal", 0))
            victim = f"{nb0}-{ordinal}"
            pod = cluster.store.get_or_none("Pod", cluster.namespace,
                                            victim)
            node_name = (pod.get("spec") or {}).get("nodeName") if pod \
                else None
            if not node_name:
                failures.append(f"worker {victim} has no node binding — "
                                f"kubelet node lifecycle not active")
            else:
                preempt_node(cluster.store, node_name)
                # the notice alone must drive the shrink handshake to
                # completion BEFORE the node actually dies
                if not cluster.wait(
                        lambda: cluster.elastic_agent.current
                        == slices - 1, timeout=recovery):
                    failures.append(
                        f"shrink to {slices - 1} slice(s) never completed "
                        f"after the preemption notice")
                kill_node(cluster.store, node_name)
                # slice atomicity is sampled while the repair rolls
                deadline = time.monotonic() + duration
                while time.monotonic() < deadline:
                    atomic = cluster.run_checks([{"type": "sliceAtomic"}])
                    if atomic:
                        failures += [f"during-preemption {f}"
                                     for f in atomic]
                        break
                    time.sleep(0.05)
        elif itype == "SchedulerPreemptionCascade":
            # interactive storm against a 3-slice elastic training run,
            # with the controller pod killed and recreated MID-CASCADE:
            # the fleet scheduler's two-phase admission plus the elastic
            # Draining handshake must converge from annotations alone —
            # no gang ever half-admitted, no reservation or grow-back
            # hold leaked, and the trainer sees a monotone step counter
            # with a continuous loss curve through shrink AND grow-back.
            from ..controllers.scheduler import SCHED_ADMITTED as _ADMITTED
            from ..controllers.scheduler import sched_state as _sched_state
            from ..runtime.elastic import SimulatedElasticAgent
            from ..utils import names as nk
            from ..utils.k8s import get_annotation
            nb0 = cluster.notebooks[0]
            slices = int(params.get("slices", 3))
            storm = int(params.get("storm", 2))
            cluster.store.patch(cluster.api.KIND, cluster.namespace, nb0, {
                "metadata": {"annotations": {
                    nk.ELASTIC_ANNOTATION: "true",
                    nk.ELASTIC_SLICES_ANNOTATION: str(slices),
                    nk.ELASTIC_CURRENT_SLICES_ANNOTATION: str(slices),
                }}})
            cluster.elastic_agent = SimulatedElasticAgent(
                cluster.store, cluster.namespace, nb0,
                current_slices=slices).start()
            # bank productive steps before the storm, as a real run would
            cluster.wait(lambda: cluster.elastic_agent.steps >= 20,
                         timeout=30.0)
            storm_names = []
            for i in range(storm):
                nm = f"storm-nb-{i}"
                cluster.store.create(cluster.api.new_notebook(
                    nm, cluster.namespace, annotations={
                        nk.TPU_ACCELERATOR_ANNOTATION: cluster.accelerator,
                        nk.SCHED_GANG_ANNOTATION: "1",
                        nk.SCHED_TIER_ANNOTATION: "interactive"}))
                cluster.notebooks.append(nm)
                storm_names.append(nm)
            # the cascade is in flight once the victim carries the hold
            if not cluster.wait(lambda: get_annotation(
                    cluster.store.get(cluster.api.KIND, cluster.namespace,
                                      nb0),
                    nk.SCHED_PREEMPTED_ANNOTATION) is not None,
                    timeout=recovery):
                failures.append("preemption cascade never started (no "
                                "hold stamped on the elastic victim)")
            # controller crash-restart MID-CASCADE: a new pod with fresh
            # watches — every phase boundary must be recoverable from
            # the persisted annotations, never from controller memory
            cluster.stop_manager()
            time.sleep(min(duration, 1.0))
            cluster.start_manager()
            # sample the admission invariants WHILE the cascade completes
            gate_checks = [{"type": "gangAdmitted"},
                           {"type": "noReservationLeak"},
                           {"type": "sliceAtomic"}]
            deadline = time.monotonic() + recovery
            admitted_all = False
            while time.monotonic() < deadline:
                probs = cluster.run_checks(gate_checks)
                if probs:
                    failures += [f"mid-cascade {f}" for f in probs]
                    break
                admitted_all = all(
                    _sched_state(cluster.store.get_or_none(
                        cluster.api.KIND, cluster.namespace, nm))
                    == _ADMITTED for nm in storm_names)
                if admitted_all:
                    break
                time.sleep(0.05)
            if not admitted_all and not failures:
                failures.append("interactive storm never fully admitted "
                                "after the mid-cascade restart")
            # the storm subsides: withdrawing the gangs sweeps the holds
            # and re-opens grow-back — the recovery-phase checks verify
            # the full round trip (elasticResized: shrink AND grow)
            for nm in storm_names:
                cluster.store.patch(cluster.api.KIND, cluster.namespace,
                                    nm, {"metadata": {"annotations": {
                                        nk.SCHED_GANG_ANNOTATION: None}}})
        elif itype == "SliceWorkerKill":
            ordinal = int(params.get("ordinal", 1))
            victim = f"{cluster.notebooks[0]}-{ordinal}"
            cluster.store.delete("Pod", cluster.namespace, victim)
            # sample slice atomicity WHILE the worker is being replaced:
            # the controller must never scale the survivors individually
            deadline = time.monotonic() + duration
            while time.monotonic() < deadline:
                atomic = cluster.run_checks([{"type": "sliceAtomic"}])
                if atomic:
                    failures += [f"during-kill {f}" for f in atomic]
                    break
                time.sleep(0.05)
            pod = cluster.store.get_or_none("Pod", cluster.namespace,
                                            victim)
            if pod is None:
                # give the simulator its recreate window before failing
                cluster.wait(lambda: cluster.store.get_or_none(
                    "Pod", cluster.namespace, victim) is not None,
                    timeout=recovery)
                pod = cluster.store.get_or_none("Pod", cluster.namespace,
                                                victim)
            if pod is None:
                failures.append(f"worker {victim} never recreated")
        else:
            failures.append(f"runner has no injection mapping for {itype}")

        # ----------------------------------------------------- recovery
        recovered = cluster.wait(
            lambda: cluster.converged() and not cluster.run_checks(checks),
            timeout=recovery, poll=0.1)
        if not recovered:
            failures.append(
                f"not recovered within {recovery:.0f}s: "
                f"converged={cluster.converged()} "
                f"checks={cluster.run_checks(checks)}")
        failures += audit_duplicate_creates(audit.name)
    except Exception as exc:  # noqa: BLE001 — an experiment must not kill the batch
        failures.append(f"runner error: {type(exc).__name__}: {exc}")
    finally:
        if cluster is not None:
            cluster.close()
        try:
            Path(audit.name).unlink()
        except OSError:
            pass
    injected = plan.injected_total() if plan is not None else 0
    return ExperimentResult(name, not failures, failures,
                            time.monotonic() - t0, injected)


def run_file(path: str | Path, **kwargs) -> list[ExperimentResult]:
    results = []
    for doc in yaml.safe_load_all(Path(path).read_text()):
        if doc:
            results.append(run_experiment(doc, **kwargs))
    return results


def run_dir(path: str | Path, **kwargs) -> list[ExperimentResult]:
    results = []
    for f in sorted(Path(path).glob("*.yaml")):
        results.extend(run_file(f, **kwargs))
    return results


def main(argv=None, emit=print) -> int:
    # emit, not print: stdout IS the product for a CLI gate, and the
    # parameter keeps it mockable (and the package lint rule honest)
    import argparse
    ap = argparse.ArgumentParser(
        description="validate (default) or execute chaos experiments")
    ap.add_argument("target", nargs="?", default="chaos/experiments")
    ap.add_argument("--run", action="store_true",
                    help="execute the experiments against the in-process "
                         "cluster over the real-wire transport (default: "
                         "schema validation only, which needs only pyyaml)")
    ap.add_argument("--notebooks", type=int, default=2)
    ap.add_argument("--time-scale", type=float, default=0.02,
                    help="cluster-time → runner-time factor for injection "
                         "durations and recovery bounds")
    ap.add_argument("--recovery-floor-s", type=float, default=30.0)
    args = ap.parse_args(argv)
    problems = validate_dir(args.target)
    for p in problems:
        emit(p)
    if problems or not args.run:
        return 1 if problems else 0
    results = run_dir(args.target, notebooks=args.notebooks,
                      time_scale=args.time_scale,
                      recovery_floor_s=args.recovery_floor_s)
    failed = [r for r in results if not r.passed]
    for r in results:
        emit(r)
    emit(f"{len(results) - len(failed)}/{len(results)} experiments passed")
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
