"""In-process Kubernetes API server.

This is the framework's envtest analog *and* a first-class component: the
reference boots a real apiserver+etcd via sigs.k8s.io/controller-runtime/envtest
(suite_test.go:50-110) because its controllers speak only API-server state
(SURVEY §1: "two independent controller processes cooperate on one CRD purely
through API-server state"). We reproduce the semantics the controllers rely on:

- optimistic concurrency via metadata.resourceVersion (ConflictError on stale
  updates — what retry.RetryOnConflict loops on in the reference,
  culling_controller.go:107,125,144,172);
- GenerateName materialization (apiserver suffixing; notebook_controller.go:444-449
  depends on this for >52-char names);
- finalizers + deletionTimestamp two-phase delete (odh notebook_controller.go:207-333);
- ownerReference cascade GC (the reference leans on GC for STS/Service/SA/CM
  cleanup, SURVEY §3.4);
- watch fan-out with ADDED/MODIFIED/DELETED events feeding controller workqueues
  (SetupWithManager watches, notebook_controller.go:778-826).

Thread-safe and SHARDED, the etcd-style split: object state lives in
per-(kind, namespace-hash) shards, each under its own write lock
(``store.shard[i]``), while resourceVersion allocation and watch plumbing
serialize under one tiny global allocator lock (``store.rv``) — etcd's
per-range state under a single global revision. Writers acquire their
shard lock, then the rv lock for the stamp+emit critical section; watch
order IS rv order because allocation and ring append share one rv-lock
hold. Multi-shard operations (cascade GC, serve-cache snapshots) take
every shard lock in index order first — the canonical order
``shard[0] < shard[1] < ... < store.rv`` that keeps the name-level
acquisition graph acyclic (ARCHITECTURE.md lock-hierarchy table).

Stored objects are IMMUTABLE once published: every write replaces the
shard slot with a fresh dict (delete-marking and DELETED frames use
copy-on-write metadata), so watch frames, serve caches, and LIST walks
share the stored object without a defensive deepcopy — the emit path
copies zero times where it used to copy once per event.
"""

from __future__ import annotations

import base64
import bisect
import contextlib
import itertools
import json
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Iterator

from ..utils import k8s, sanitizer
from ..utils.names import generate_suffix
from . import codec
from .errors import (AlreadyExistsError, ConflictError, GoneError,
                     InvalidError, NotFoundError)

CLUSTER_SCOPED_KINDS = {
    "Namespace", "ClusterRole", "ClusterRoleBinding", "OAuthClient",
    "CustomResourceDefinition", "PriorityClass", "Node", "APIServer",
    "MutatingWebhookConfiguration", "ValidatingWebhookConfiguration",
}

#: default shard count: enough to spread a multi-frontend write load
#: (kinds × namespaces hash well past 8) while keeping the all-shards
#: acquisition of cascade GC cheap
DEFAULT_SHARDS = 8


@dataclass(frozen=True)
class ObjectKey:
    kind: str
    namespace: str
    name: str


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    obj: dict


#: per-kind watch-cache ring capacity: how many recent events a dropped
#: watcher can resume across without a full re-LIST. Sized so a fleet-wide
#: status churn burst (500 notebooks × a handful of writes each) fits in
#: the window at the facade's memory cost of one shared frame per event.
WATCH_CACHE_CAPACITY = 4096


class EventFrame:
    """One watch event, shared by every consumer (the real apiserver's
    watch-cache entry): the object is the STORED object itself — stored
    state is immutable post-publish, so no per-event copy is needed —
    and each wire encoding (JSON and binary) is computed at most once no
    matter how many HTTP watchers fan it out. ``rv`` is the event's
    resourceVersion as an int — the resume cursor."""

    __slots__ = ("rv", "type", "obj", "_obj_bytes", "_obj_bytes_binary")

    def __init__(self, rv: int, type_: str, obj: dict) -> None:
        self.rv = rv
        self.type = type_
        self.obj = obj
        self._obj_bytes: bytes | None = None
        self._obj_bytes_binary: bytes | None = None

    def obj_bytes(self) -> bytes:
        """The object's JSON encoding, computed once and cached (benign
        race under the GIL: two threads may both encode, one wins)."""
        encoded = self._obj_bytes
        if encoded is None:
            encoded = json.dumps(self.obj,
                                 separators=(",", ":")).encode()
            self._obj_bytes = encoded
        return encoded

    def obj_bytes_binary(self) -> bytes:
        """The object's binary-codec encoding, cached like obj_bytes():
        a mixed fleet (JSON + binary watchers on one ring) costs one
        encode per format per event, not per watcher."""
        encoded = self._obj_bytes_binary
        if encoded is None:
            encoded = codec.encode(self.obj)
            self._obj_bytes_binary = encoded
        return encoded


class _WatchRing:
    """Bounded per-kind ring of recent EventFrames in rv order (emission
    happens under the rv-allocator lock where rvs are issued, so append
    order IS rv order). ``evicted_rv`` is the rv of the newest frame
    pushed out: a resume from N is servable iff every kind event with
    rv > N is still present, i.e. N >= evicted_rv."""

    __slots__ = ("frames", "evicted_rv", "capacity")

    def __init__(self, capacity: int) -> None:
        self.frames: deque[EventFrame] = deque()
        self.evicted_rv = 0
        self.capacity = capacity

    def append(self, frame: EventFrame) -> int:
        """Add a frame; returns how many old frames were evicted."""
        self.frames.append(frame)
        evicted = 0
        while len(self.frames) > self.capacity:
            self.evicted_rv = self.frames.popleft().rv
            evicted += 1
        return evicted

    def since(self, rv: int) -> list[EventFrame]:
        """Frames with rv > ``rv`` (caller verified servability)."""
        return [f for f in self.frames if f.rv > rv]


@dataclass
class _Watch:
    kind: str
    callback: Callable[[WatchEvent], None]
    namespace: str | None = None
    label_selector: dict[str, str] | None = None
    #: frame relays (the HTTP facade) receive the shared EventFrame —
    #: cached wire bytes, no per-watcher deepcopy; plain watches receive
    #: a WatchEvent carrying the shared object
    frames: bool = False


class _Shard:
    """One slice of object state: its own write lock plus the objects it
    owns. Shard locks carry per-index names — the sanitizer's acquisition
    graph is name-level, and the canonical multi-shard order (ascending
    index) must be visible to it as distinct nodes."""

    __slots__ = ("lock", "objects")

    def __init__(self, index: int) -> None:
        # store tier — nothing blocking may run under it, and the
        # cache/watch tiers may be acquired under it but never above it
        self.lock = sanitizer.tracked_rlock(
            f"store.shard[{index}]", order=sanitizer.ORDER_STORE,
            no_blocking=True)
        self.objects: dict[ObjectKey, dict] = sanitizer.guarded_by(
            {}, self.lock, f"store.shard[{index}].objects")


_now_iso = k8s.now_iso


def _encode_continue(namespace: str, name: str) -> str:
    """Opaque continue token naming the last key a page served (the real
    apiserver's token is likewise base64 JSON of a positional cursor)."""
    raw = json.dumps([namespace, name]).encode()
    return base64.urlsafe_b64encode(raw).decode().rstrip("=")


def _decode_continue(token: str) -> tuple[str, str]:
    pad = "=" * (-len(token) % 4)
    try:
        ns, nm = json.loads(base64.urlsafe_b64decode(token + pad))
        return (str(ns), str(nm))
    except (ValueError, TypeError):
        raise InvalidError(f"malformed continue token {token!r}") from None


def _shard_index(kind: str, namespace: str, nshards: int) -> int:
    """FNV-1a over the shard key ``kind/namespace`` — deterministic
    across processes and Python hash-randomization (the shard-key
    contract: one (kind, namespace) pair always lands on one shard, so
    a namespaced LIST touches exactly one shard lock)."""
    h = 0x811C9DC5
    for byte in f"{kind}/{namespace}".encode():
        h ^= byte
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h % nshards


class ClusterStore:
    """The in-process apiserver + etcd. All mutating verbs return a deep copy
    of the stored object (as the real apiserver returns the canonical form)."""

    def __init__(self, shards: int = DEFAULT_SHARDS) -> None:
        self._nshards = max(int(shards), 1)
        self._shards = [_Shard(i) for i in range(self._nshards)]
        # the global rv allocator: the ONE serialization point left on
        # the write path — a tiny stamp+emit critical section (rv issue,
        # ring append, relay feed), always acquired AFTER shard locks
        self._rv_lock = sanitizer.tracked_rlock(
            "store.rv", order=sanitizer.ORDER_STORE, no_blocking=True)
        # CRD-schema / webhook-config indexes: written under a shard
        # lock (nested), read standalone during admission
        self._config_lock = sanitizer.tracked_rlock(
            "store.config", order=sanitizer.ORDER_STORE, no_blocking=True)
        self._rv_counter = itertools.count(1)
        self._last_rv = 0  # latest issued rv — reported in LIST metadata
        # one-entry sorted-key snapshot for paginated LISTs: a pager walks
        # the same (kind, namespace) shape page after page, and re-sorting
        # the whole kind per page would make one chunked LIST
        # O(pages × N log N). Keyed on _last_rv, so any write invalidates
        # it (deletes bump rv too, for their DELETED watch frame; the
        # page walk below still tolerates a stale key).
        self._page_snapshot: tuple | None = None  # (kind, ns, rv, pairs)
        self._uid_counter = itertools.count(1)
        self._watches: list[_Watch] = sanitizer.guarded_by(
            [], self._rv_lock, "store.watches")
        # per-kind bounded ring of recent watch frames — the resume window
        # ``?watch=true&resourceVersion=N`` replays from instead of forcing
        # a LIST+diff resync; eviction makes such a resume answer 410 Gone
        self._watch_rings: dict[str, _WatchRing] = sanitizer.guarded_by(
            {}, self._rv_lock, "store.watch_rings")
        self.watch_cache_capacity = WATCH_CACHE_CAPACITY
        self._evictions_metric = None  # watch_cache_evictions_total
        self._list_lock_metric = None  # store_list_lock_seconds
        self._write_lock_metric = None  # store_write_lock_seconds
        # admission hooks: list of (kind, fn(operation, obj, old) -> obj|raise)
        self._admission: list[tuple[str, Callable]] = []
        # CRD structural schemas: kind → {version: openAPIV3Schema}; kept in
        # step with CustomResourceDefinition objects so CRs are validated
        # server-side, as kube-apiserver does for installed CRDs
        self._crd_schemas: dict[str, dict[str, dict]] = sanitizer.guarded_by(
            {}, self._config_lock, "store.crd_schemas")
        # Mutating/ValidatingWebhookConfiguration objects, indexed so writes
        # call out over real HTTPS AdmissionReview (cluster/remote_admission)
        self._webhook_configs: dict[str, dict[ObjectKey, dict]] = \
            sanitizer.guarded_by({}, self._config_lock,
                                 "store.webhook_configs")

    def _next_rv(self) -> str:
        """Issue the next resourceVersion (caller holds the rv lock) and
        remember it — LIST metadata reports the latest issued rv, the
        anchor for informer-style ``resourceVersion=0`` list-then-watch."""
        self._last_rv = next(self._rv_counter)
        return str(self._last_rv)

    # ------------------------------------------------------------------ keys
    def _key(self, kind: str, namespace: str, name: str) -> ObjectKey:
        if kind in CLUSTER_SCOPED_KINDS:
            namespace = ""
        return ObjectKey(kind, namespace, name)

    def _key_of(self, obj: dict) -> ObjectKey:
        return self._key(k8s.kind(obj), k8s.namespace(obj), k8s.name(obj))

    def _shard_of(self, key: ObjectKey) -> _Shard:
        return self._shards[_shard_index(key.kind, key.namespace,
                                         self._nshards)]

    def _shards_for(self, kind: str, namespace: str | None) -> list[_Shard]:
        """The shards a LIST must visit: exactly one for a namespaced
        LIST (the shard key is (kind, namespace)), all of them for a
        cross-namespace LIST."""
        if namespace is None:
            return self._shards
        key = self._key(kind, namespace, "")
        return [self._shard_of(key)]

    @contextlib.contextmanager
    def _all_shards_locked(self):
        """Acquire EVERY shard lock in canonical (ascending index) order
        — the multi-shard entry point for cascade GC and atomic
        snapshots. The rv lock is still acquired after, never before."""
        with contextlib.ExitStack() as stack:
            for shard in self._shards:
                stack.enter_context(shard.lock)
            yield

    def _observe_write(self, kind: str, started: float) -> None:
        if self._write_lock_metric is not None:
            self._write_lock_metric.observe(time.monotonic() - started,
                                            {"kind": kind})

    # ------------------------------------------------------------- admission
    def register_admission(self, kind: str, fn: Callable) -> None:
        """Register an admission plugin invoked before create/update/patch is
        persisted — the seam the mutating/validating webhooks plug into
        (the reference registers these on the manager's webhook server,
        odh main.go:306-331; kube-apiserver calls them in-flight)."""
        self._admission.append((kind, fn))

    def _admit(self, operation: str, obj: dict, old: dict | None) -> dict:
        for kind, fn in self._admission:
            if kind == k8s.kind(obj):
                obj = fn(operation, obj, old)
        obj = self._run_remote_admission(operation, obj, old)
        # schema validation runs AFTER webhooks, on what will be persisted —
        # the apiserver's phase order (mutating admission → schema →
        # persistence)
        self._validate_against_crd(obj)
        return obj

    def _run_remote_admission(self, operation: str, obj: dict,
                              old: dict | None) -> dict:
        """HTTPS AdmissionReview against registered webhook configurations
        (mutating phase, then validating — the apiserver's order). The
        config index is snapshotted under its lock; the HTTP calls run
        outside it (see create())."""
        from . import remote_admission as ra
        if k8s.kind(obj) in ra.CONFIG_KINDS:
            return obj  # configurations themselves are not gated
        with self._config_lock:
            mutating = [k8s.deepcopy(c) for c in
                        self._webhook_configs.get(ra.MUTATING_KIND,
                                                  {}).values()]
            validating = [k8s.deepcopy(c) for c in
                          self._webhook_configs.get(ra.VALIDATING_KIND,
                                                    {}).values()]
        if mutating:
            obj = ra.run_webhooks(mutating, operation, obj, old,
                                  mutating=True)
        if validating:
            ra.run_webhooks(validating, operation, obj, old, mutating=False)
        return obj

    def _index_webhook_config(self, key: ObjectKey, obj: dict) -> None:
        with self._config_lock:
            self._webhook_configs.setdefault(key.kind, {})[key] = \
                k8s.deepcopy(obj)

    def _unindex_webhook_config(self, key: ObjectKey) -> None:
        with self._config_lock:
            self._webhook_configs.get(key.kind, {}).pop(key, None)

    # -------------------------------------------------------- CRD schemas
    def _index_crd(self, crd: dict) -> None:
        kind = k8s.get_in(crd, "spec", "names", "kind")
        if not kind:
            return
        versions = {}
        for v in k8s.get_in(crd, "spec", "versions", default=[]) or []:
            s = k8s.get_in(v, "schema", "openAPIV3Schema")
            if v.get("served") and s:
                versions[v["name"]] = s
        if versions:
            with self._config_lock:
                self._crd_schemas[kind] = versions

    def _unindex_crd(self, crd: dict) -> None:
        kind = k8s.get_in(crd, "spec", "names", "kind")
        with self._config_lock:
            self._crd_schemas.pop(kind, None)

    def _validate_against_crd(self, obj: dict) -> None:
        with self._config_lock:  # schema index is written under this lock
            versions = self._crd_schemas.get(k8s.kind(obj))
        if not versions:
            return
        version = (obj.get("apiVersion") or "").rpartition("/")[2]
        schema = versions.get(version)
        if schema is None:
            return  # unserved/unknown version: caught by typed admission
        from ..api.schema import validate_schema
        errors = validate_schema(obj, schema)
        if errors:
            shown = "; ".join(errors[:5])
            if len(errors) > 5:
                shown += f" (+{len(errors) - 5} more)"
            raise InvalidError(
                f"{k8s.kind(obj)} {k8s.namespace(obj)}/{k8s.name(obj)} "
                f"is invalid: {shown}")

    # ----------------------------------------------------------------- watch
    # emission plumbing: every mutation builds its event frame UNDER the
    # rv-allocator lock, in the same hold that issued the frame's rv —
    # ring order is rv order BY CONSTRUCTION, even with writers on
    # different shards (two writers allocating outside one hold could
    # append inverted). A watcher registering concurrently either lands
    # in the dispatch snapshot or gets the frame via resume replay —
    # exactly once either way. FRAME relays (the HTTP facade's
    # per-watcher queues) are fed under the rv lock too: they are pure
    # queue appends that never re-enter the store, and in-lock delivery
    # is what guarantees every watcher queue receives frames in rv order.
    # Legacy WatchEvent callbacks (in-process manager watches) may
    # re-enter the store, so they still dispatch outside all locks.

    def _emit_locked(self, etype: str, obj: dict) -> tuple:
        """Build the shared frame for one event, append it to the kind's
        resume ring, relay it to frame watchers (in rv order, see above),
        and snapshot matching legacy watchers. Caller holds the rv lock
        and has already stamped ``obj``'s resourceVersion under the same
        hold; returns ``(frame, legacy_targets)`` for _dispatch_all."""
        kind = k8s.kind(obj)
        ns = k8s.namespace(obj)
        try:
            rv = int(k8s.get_in(obj, "metadata", "resourceVersion") or 0)
        except (TypeError, ValueError):
            rv = 0
        frame = EventFrame(rv, etype, obj)
        ring = self._watch_rings.get(kind)
        if ring is None:
            ring = self._watch_rings[kind] = \
                _WatchRing(self.watch_cache_capacity)
        evicted = ring.append(frame)
        if evicted and self._evictions_metric is not None:
            self._evictions_metric.inc({"kind": kind}, by=evicted)
        targets = []
        for w in self._watches:
            if w.kind != kind \
                    or (w.namespace is not None and w.namespace != ns) \
                    or not k8s.matches_labels(obj, w.label_selector):
                continue
            if w.frames:
                w.callback(frame)
            else:
                targets.append(w)
        return frame, targets

    @staticmethod
    def _dispatch_all(emitted: list) -> None:
        """Deliver emitted frames to their snapshotted legacy watchers
        (outside the locks — these callbacks may re-enter the store). The
        object is SHARED across all consumers of one event — it IS the
        immutable stored object, zero copies — and must be treated as
        immutable by callbacks (every in-tree consumer already copies
        before mutating; the read cache replaces, never edits)."""
        for frame, targets in emitted:
            for w in targets:
                w.callback(WatchEvent(frame.type, frame.obj))

    def attach_metrics(self, registry) -> None:
        """Register the watch-cache eviction counter (CachingClient and
        the wrappers pass their registry down here) plus the LIST and
        write lock-hold histograms — both registered EAGERLY here so
        every verb observes from the first call after attachment (the
        lock-stampede measurements the shard split is judged by)."""
        self._evictions_metric = registry.counter(
            "watch_cache_evictions_total",
            "Watch-cache ring frames evicted, by kind — each eviction "
            "narrows the window a reconnecting watcher can resume across "
            "without a full re-LIST.")
        self._list_lock_metric = registry.histogram(
            "store_list_lock_seconds",
            "Wall time a LIST spent acquiring plus holding the store's "
            "shard locks, by kind. "
            "Cache-served (rv=0) LISTs never appear here — this series "
            "growing with manager count means resyncs are stampeding the "
            "write path again.")
        self._write_lock_metric = registry.histogram(
            "store_write_lock_seconds",
            "Wall time a write verb spent acquiring plus holding its "
            "shard's write lock (and the rv allocator nested under it), "
            "by kind — the sibling of store_list_lock_seconds that the "
            "shard split is measured by: per-frontend write rates stay "
            "flat when shards spread contention.")

    # ----------------------------------------------------------------- verbs
    def create(self, obj: dict) -> dict:
        obj = k8s.deepcopy(obj)
        # admission runs OUTSIDE the store locks (kube-apiserver holds no
        # global lock around webhook calls): remote webhooks are HTTPS
        # round-trips whose handlers read back into this store from their
        # own threads — under a lock that is a deadlock. Races admitted
        # here are caught at persist (AlreadyExists / Conflict).
        obj = self._admit("CREATE", obj, None)
        md = k8s.meta(obj)
        if not md.get("name") and md.get("generateName"):
            md["name"] = md["generateName"] + generate_suffix(
                f'{md["generateName"]}{next(self._uid_counter)}', 5)
        if not md.get("name"):
            raise InvalidError("metadata.name or generateName required")
        key = self._key_of(obj)
        shard = self._shard_of(key)
        md.setdefault("creationTimestamp", _now_iso())
        started = time.monotonic()
        with shard.lock:
            if key in shard.objects:
                raise AlreadyExistsError(
                    f"{key.kind} {key.namespace}/{key.name}")
            md["uid"] = f"uid-{next(self._uid_counter)}"
            md["generation"] = 1
            with self._rv_lock:
                md["resourceVersion"] = self._next_rv()
                shard.objects[key] = obj
                emitted = [self._emit_locked("ADDED", obj)]
            if key.kind == "CustomResourceDefinition":
                self._index_crd(obj)
            elif key.kind in ("MutatingWebhookConfiguration",
                              "ValidatingWebhookConfiguration"):
                self._index_webhook_config(key, obj)
        self._observe_write(key.kind, started)
        self._dispatch_all(emitted)
        return k8s.deepcopy(obj)

    def get(self, kind: str, namespace: str, name: str) -> dict:
        key = self._key(kind, namespace, name)
        shard = self._shard_of(key)
        with shard.lock:
            obj = shard.objects.get(key)
        if obj is None:
            raise NotFoundError(f"{kind} {namespace}/{name}")
        # the stored object is immutable: copy outside the lock
        return k8s.deepcopy(obj)

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict[str, str] | None = None) -> list[dict]:
        items, _, _ = self.list_page(kind, namespace, label_selector)
        return items

    def list_page(self, kind: str, namespace: str | None = None,
                  label_selector: dict[str, str] | None = None,
                  limit: int | None = None,
                  continue_token: str | None = None,
                  resource_version: str | None = None,
                  ) -> tuple[list[dict], str | None, str]:
        """LIST with apiserver chunking semantics (``limit``/``continue``,
        apimachinery ListOptions). Returns ``(items, next_continue,
        list_rv)``; ``next_continue`` is None on the final page.

        Keys are served in deterministic ``(namespace, name)`` order and
        the continue token names the last key a page walked, so on a
        quiescent population the pages compose into exactly the
        unpaginated set for every page size (the equivalence the tests
        pin). Objects created/deleted between pages may be missed or seen
        once, as with the real chunked LIST — level-triggered consumers
        tolerate that, and the watch diff repairs it.

        ``resource_version``: ``"0"`` is the informer cache-ack form —
        "any stored state is acceptable, don't require quorum"; this store
        IS the state of record, so it serves current state (the point of
        accepting it is that clients can pipeline list-then-watch without
        a special case). Exact/minimum-rv forms are likewise served from
        current state — there are no historical snapshots here. ``list_rv``
        is the latest issued resourceVersion, the anchor a watch would
        start from — anchored BEFORE the shard walk, so a write racing
        the collection lands with rv > list_rv and a watch from list_rv
        replays it (duplicate-tolerant) rather than losing it."""
        start_after = (_decode_continue(continue_token)
                       if continue_token else None)
        if limit is not None and limit <= 0:
            limit = None  # limit=0 means "no limit", as on the wire
        lock_started = time.monotonic()
        with self._rv_lock:
            list_rv_int = self._last_rv
            snap = self._page_snapshot
        # collect object REFS under brief per-shard locks (a namespaced
        # LIST visits exactly ONE shard); the sort, page walk, and output
        # deepcopies all run OUTSIDE the locks — stored objects are
        # immutable, so the refs stay valid after release
        refs: dict[tuple[str, str], dict] = {}
        for shard in self._shards_for(kind, namespace):
            with shard.lock:
                for okey, oobj in shard.objects.items():
                    if okey.kind == kind and (namespace is None
                                              or okey.namespace == namespace):
                        refs[(okey.namespace, okey.name)] = oobj
        lock_elapsed = time.monotonic() - lock_started
        token = (kind, namespace, list_rv_int)
        if limit is not None and snap is not None and snap[:3] == token:
            pairs = snap[3]
        else:
            pairs = sorted(refs)
            if limit is not None:
                with self._rv_lock:
                    self._page_snapshot = (*token, pairs)
        start = (bisect.bisect_right(pairs, start_after)
                 if start_after is not None else 0)
        out: list[dict] = []
        last_pair: tuple[str, str] | None = None
        next_token: str | None = None
        for pair in pairs[start:]:
            # a key may have been deleted since the pair snapshot was
            # cut: skip — same "objects deleted between pages may be
            # missed" contract as the real chunked LIST
            obj = refs.get(pair)
            if obj is None or not k8s.matches_labels(obj, label_selector):
                continue
            if limit is not None and len(out) >= limit:
                # page full with at least one candidate left: hand out
                # a cursor at the last key actually served
                next_token = _encode_continue(*last_pair)
                break
            out.append(k8s.deepcopy(obj))
            last_pair = pair
        if self._list_lock_metric is not None:
            self._list_lock_metric.observe(lock_elapsed, {"kind": kind})
        return out, next_token, str(list_rv_int)

    def update(self, obj: dict) -> dict:
        obj = k8s.deepcopy(obj)
        key = self._key_of(obj)
        shard = self._shard_of(key)
        # snapshot + early conflict check, then admit OUTSIDE the locks
        # (see create()); the post-admission check below re-validates that
        # the state admitted against is still the state being replaced
        with shard.lock:
            old_snapshot = shard.objects.get(key)
        if old_snapshot is None:
            raise NotFoundError(f"{key.kind} {key.namespace}/{key.name}")
        snapshot_rv = old_snapshot["metadata"]["resourceVersion"]
        new_rv = k8s.get_in(obj, "metadata", "resourceVersion")
        if new_rv is not None and new_rv != snapshot_rv:
            raise ConflictError(
                f"{key.kind} {key.namespace}/{key.name}: stale resourceVersion")
        obj = self._admit("UPDATE", obj, k8s.deepcopy(old_snapshot))
        # a finalizer-stripping update of a deleting object removes the
        # object and cascades — that needs every shard lock. Decide from
        # the snapshot; if the single-shard pass discovers the cascade
        # branch anyway (a concurrent delete marked the object during
        # admission), it retries once with all shard locks.
        take_all = bool(
            (k8s.get_in(obj, "metadata", "deletionTimestamp")
             or k8s.get_in(old_snapshot, "metadata", "deletionTimestamp"))
            and not k8s.get_in(obj, "metadata", "finalizers"))
        started = time.monotonic()
        emitted: list | None = None
        for all_shards in ([True] if take_all else [False, True]):
            emitted = self._apply_update_locked(key, shard, obj, new_rv,
                                                snapshot_rv, all_shards)
            if emitted is not None:
                break
        self._observe_write(key.kind, started)
        self._dispatch_all(emitted)
        return k8s.deepcopy(obj)

    def _apply_update_locked(self, key: ObjectKey, shard: _Shard, obj: dict,
                             new_rv, snapshot_rv,
                             take_all: bool) -> list | None:
        """One locked attempt at applying an update; returns the
        emissions, or None when the cascade branch was reached without
        every shard lock held (the caller retries with all of them)."""
        with contextlib.ExitStack() as stack:
            if take_all:
                stack.enter_context(self._all_shards_locked())
            else:
                stack.enter_context(shard.lock)
            old = shard.objects.get(key)
            if old is None:
                raise NotFoundError(f"{key.kind} {key.namespace}/{key.name}")
            # re-check ONLY for optimistic writers: a no-RV update keeps
            # the apiserver's unconditional last-write-wins semantics even
            # when a concurrent write landed during the out-of-lock
            # admission window
            if new_rv is not None and \
                    old["metadata"]["resourceVersion"] != snapshot_rv:
                raise ConflictError(
                    f"{key.kind} {key.namespace}/{key.name}: object changed "
                    f"during admission")
            md = k8s.meta(obj)
            md["uid"] = old["metadata"]["uid"]
            md["creationTimestamp"] = old["metadata"]["creationTimestamp"]
            if k8s.get_in(old, "metadata", "deletionTimestamp"):
                md["deletionTimestamp"] = old["metadata"]["deletionTimestamp"]
            if obj.get("spec") != old.get("spec"):
                md["generation"] = old["metadata"].get("generation", 1) + 1
            else:
                md["generation"] = old["metadata"].get("generation", 1)
            if (k8s.get_in(obj, "metadata", "deletionTimestamp")
                    and not k8s.get_in(obj, "metadata", "finalizers")):
                # last finalizer stripped → actually remove (two-phase
                # delete, cascading to dependents on other shards)
                if not take_all:
                    return None
                with self._rv_lock:
                    md["resourceVersion"] = self._next_rv()
                return self._remove_and_gc(key, replacement=obj)
            with self._rv_lock:
                md["resourceVersion"] = self._next_rv()
                shard.objects[key] = obj
                emitted = [self._emit_locked("MODIFIED", obj)]
            if key.kind == "CustomResourceDefinition":
                self._index_crd(obj)
            elif key.kind in ("MutatingWebhookConfiguration",
                              "ValidatingWebhookConfiguration"):
                self._index_webhook_config(key, obj)
            return emitted

    # bounds the patch re-merge loop: each retry re-runs admission (possibly
    # remote HTTPS round-trips), so a hot object must back off and eventually
    # surface the conflict rather than livelock
    PATCH_MAX_RETRIES = 20

    def patch(self, kind: str, namespace: str, name: str, patch: dict) -> dict:
        """RFC 7386 JSON merge patch (client.MergeFrom semantics). Unlike
        update(), it re-merges against the current version on a concurrent
        write, as the reference relies on for annotation removal
        (odh notebook_controller.go:516-523) — with bounded backoff now that
        each attempt may spend webhook round-trips outside the lock."""
        key = self._key(kind, namespace, name)
        shard = self._shard_of(key)
        for attempt in range(self.PATCH_MAX_RETRIES):
            with shard.lock:
                old = shard.objects.get(key)
            if old is None:
                raise NotFoundError(f"{kind} {namespace}/{name}")
            merged = k8s.json_merge_patch(old, patch)
            # fresh metadata dict: json_merge_patch shares untouched
            # subtrees with the (immutable) stored object
            merged["metadata"] = {**(merged.get("metadata") or {}),
                                  "resourceVersion":
                                      old["metadata"]["resourceVersion"]}
            try:
                return self.update(merged)
            except ConflictError:
                # raced a concurrent writer; re-merge on the new version
                time.sleep(min(0.001 * (2 ** attempt), 0.1))
        raise ConflictError(f"{kind} {namespace}/{name}: patch kept "
                            f"conflicting after {self.PATCH_MAX_RETRIES} "
                            f"attempts")

    def update_status(self, obj: dict) -> dict:
        """Status subresource semantics: only .status is applied. The
        replacement shares the (immutable) old object's spec/metadata
        subtrees — only .status and the rv-bearing metadata dict are
        fresh."""
        key = self._key_of(obj)
        shard = self._shard_of(key)
        new_status = k8s.deepcopy(obj.get("status", {}))
        new_rv = k8s.get_in(obj, "metadata", "resourceVersion")
        started = time.monotonic()
        with shard.lock:
            old = shard.objects.get(key)
            if old is None:
                raise NotFoundError(f"{key.kind} {key.namespace}/{key.name}")
            if new_rv is not None and \
                    new_rv != old["metadata"]["resourceVersion"]:
                raise ConflictError(f"{key.kind} {key.namespace}/{key.name}")
            with self._rv_lock:
                stored = {**old, "status": new_status,
                          "metadata": {**old["metadata"],
                                       "resourceVersion": self._next_rv()}}
                shard.objects[key] = stored
                emitted = [self._emit_locked("MODIFIED", stored)]
        self._observe_write(key.kind, started)
        self._dispatch_all(emitted)
        return k8s.deepcopy(stored)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        """Two-phase delete: finalizers present → set deletionTimestamp and
        wait for controllers to strip them; else remove + cascade to owned
        objects (background GC)."""
        key = self._key(kind, namespace, name)
        shard = self._shard_of(key)
        with shard.lock:
            snapshot = shard.objects.get(key)
        if snapshot is None:
            raise NotFoundError(f"{kind} {namespace}/{name}")
        snap = k8s.deepcopy(snapshot)
        # DELETE-gating webhooks (operations: ["DELETE"]) fire like the real
        # apiserver's; outside the locks (see create())
        self._run_remote_admission("DELETE", snap, snap)
        # removal cascades across shards → all shard locks; the
        # finalizer-mark path stays on the object's own shard. Decide
        # from the snapshot, retry with all locks if the state flipped
        # during the webhook window.
        take_all = not k8s.get_in(snapshot, "metadata", "finalizers")
        started = time.monotonic()
        emitted: list | None = None
        for all_shards in ([True] if take_all else [False, True]):
            emitted = self._apply_delete_locked(key, shard, all_shards)
            if emitted is not None:
                break
        self._observe_write(key.kind, started)
        self._dispatch_all(emitted)

    def _apply_delete_locked(self, key: ObjectKey, shard: _Shard,
                             take_all: bool) -> list | None:
        """One locked attempt at a delete; returns emissions, or None
        when removal was reached without every shard lock held."""
        with contextlib.ExitStack() as stack:
            if take_all:
                stack.enter_context(self._all_shards_locked())
            else:
                stack.enter_context(shard.lock)
            obj = shard.objects.get(key)
            if obj is None:
                raise NotFoundError(f"{key.kind} {key.namespace}/{key.name}")
            if k8s.get_in(obj, "metadata", "finalizers"):
                emitted: list = []
                if not k8s.get_in(obj, "metadata", "deletionTimestamp"):
                    ts = _now_iso()
                    with self._rv_lock:
                        # copy-on-write delete mark: the stored object is
                        # immutable (frames share it), so the mark is a
                        # fresh dict sharing spec/status
                        marked = {**obj,
                                  "metadata": {**obj["metadata"],
                                               "deletionTimestamp": ts,
                                               "resourceVersion":
                                                   self._next_rv()}}
                        shard.objects[key] = marked
                        emitted.append(self._emit_locked("MODIFIED", marked))
                return emitted
            if not take_all:
                return None
            return self._remove_and_gc(key)

    # ------------------------------------------------------- delete plumbing
    def _remove_and_gc(self, key: ObjectKey,
                       replacement: dict | None = None) -> list:
        """Remove object and cascade-delete dependents via ownerReferences,
        honoring dependents' own finalizers. Caller holds EVERY shard lock
        (canonical index order — dependents live on arbitrary shards);
        returns emissions for _dispatch_all. The DELETED event carries a
        FRESH resourceVersion (as the real apiserver's does — the deletion
        is an etcd revision): the resume ring is rv-ordered, and a DELETED
        frame reusing the object's last-write rv would sort before newer
        events and be skipped by any resume past it — a silently lost
        deletion."""
        shard = self._shard_of(key)
        obj = replacement if replacement is not None \
            else shard.objects.get(key)
        emitted: list = []
        if key in shard.objects:
            del shard.objects[key]
        if obj is None:
            return emitted
        if key.kind == "CustomResourceDefinition":
            self._unindex_crd(obj)
        elif key.kind in ("MutatingWebhookConfiguration",
                          "ValidatingWebhookConfiguration"):
            self._unindex_webhook_config(key)
        with self._rv_lock:
            # copy-on-write DELETED frame: fresh metadata with the fresh
            # rv, sharing the immutable object's spec/status
            final = {**obj, "metadata": {**obj["metadata"],
                                         "resourceVersion":
                                             self._next_rv()}}
            emitted.append(self._emit_locked("DELETED", final))
        owner_uid = k8s.uid(obj)
        if owner_uid:
            dependents = []
            for s in self._shards:
                dependents.extend(
                    dk for dk, dobj in s.objects.items()
                    if k8s.is_owned_by(dobj, owner_uid))
            for dk in dependents:
                dshard = self._shard_of(dk)
                dobj = dshard.objects.get(dk)
                if dobj is None:
                    continue
                if k8s.get_in(dobj, "metadata", "finalizers"):
                    if not k8s.get_in(dobj, "metadata", "deletionTimestamp"):
                        ts = _now_iso()
                        with self._rv_lock:
                            marked = {**dobj,
                                      "metadata": {**dobj["metadata"],
                                                   "deletionTimestamp": ts,
                                                   "resourceVersion":
                                                       self._next_rv()}}
                            dshard.objects[dk] = marked
                            emitted.append(self._emit_locked("MODIFIED",
                                                             marked))
                else:
                    emitted.extend(self._remove_and_gc(dk))
        return emitted

    # ---------------------------------------------------- watch registration
    def watch(self, kind: str, callback: Callable[[WatchEvent], None],
              namespace: str | None = None,
              label_selector: dict[str, str] | None = None) -> None:
        with self._rv_lock:
            self._watches.append(_Watch(kind, callback, namespace,
                                        label_selector))

    def watch_frames(self, kind: str, relay: Callable,
                     namespace: str | None = None,
                     label_selector: dict[str, str] | None = None,
                     since_rv: int | None = None) -> tuple[list, int]:
        """Register a frame relay (the HTTP facade's serialize-once path)
        and, when ``since_rv`` is given, atomically hand back the replay
        of every retained event after it — the RV-resumable reconnect
        that replaces the client's LIST+diff resync. Returns ``(replay,
        anchor_rv)``; ``anchor_rv`` is the resourceVersion through which
        the stream is complete at registration (the idle-stream BOOKMARK
        anchor). Raises GoneError when ``since_rv`` predates the retained
        window — or names a version this store never issued (a resume
        against a different store incarnation must relist, never
        silently skip)."""
        with self._rv_lock:
            replay: list[EventFrame] = []
            if since_rv is not None:
                ring = self._watch_rings.get(kind)
                evicted_rv = ring.evicted_rv if ring is not None else 0
                if since_rv < evicted_rv or since_rv > self._last_rv:
                    raise GoneError(
                        f"too old resource version: {since_rv} (the watch "
                        f"cache window for {kind} starts at {evicted_rv})")
                if ring is not None:
                    replay = [f for f in ring.since(since_rv)
                              if (namespace is None
                                  or k8s.namespace(f.obj) == namespace)
                              and k8s.matches_labels(f.obj, label_selector)]
            self._watches.append(_Watch(kind, relay, namespace,
                                        label_selector, frames=True))
            return replay, self._last_rv

    def snapshot_with_frames(self, kind: str, relay: Callable,
                             ) -> tuple[list[dict], int]:
        """Atomically register a frame relay for ``kind`` and return a
        deepcopied snapshot of its current objects plus the anchor rv —
        the init handshake for a server-side watch cache: the cache is
        exactly consistent from birth (every event after the snapshot
        arrives through the relay, in rv order). Holding every shard
        lock plus the rv lock excludes all writers — no event can be
        stamped while the snapshot is cut — so reads served from the
        cache are never stale relative to the store."""
        with self._all_shards_locked():
            with self._rv_lock:
                refs = [obj for s in self._shards
                        for okey, obj in s.objects.items()
                        if okey.kind == kind]
                self._watches.append(_Watch(kind, relay, None, None,
                                            frames=True))
                anchor = self._last_rv
        # stored objects are immutable: the copies happen outside the
        # locks (the deepcopied-return contract is unchanged)
        return [k8s.deepcopy(o) for o in refs], anchor

    def list_cached(self, kind: str, namespace: str | None = None,
                    label_selector: dict[str, str] | None = None,
                    min_resource_version: int | None = None) -> list[dict]:
        """Interface parity with HttpApiClient.list_cached (the rv=0
        consistent-read-from-cache LIST): this store IS the state of
        record, so the cache-acceptable form serves current state (which
        trivially satisfies any ``min_resource_version`` gate)."""
        return self.list(kind, namespace, label_selector)

    def unwatch(self, callback: Callable[[WatchEvent], None]) -> None:
        """Deregister a watch callback (watch stream teardown — the apiserver
        facade drops its per-connection relay when the HTTP client goes away)."""
        with self._rv_lock:
            # equality, not identity: a bound method (the serve cache's
            # _on_frame relay) is a fresh object per attribute access, and
            # == compares __self__/__func__; for plain functions/closures
            # == degrades to identity, so other callers are unchanged.
            # In-place slice assignment keeps the guarded list registered.
            self._watches[:] = [w for w in self._watches
                                if w.callback != callback]

    # ----------------------------------------------------------- conveniences
    def get_or_none(self, kind: str, namespace: str, name: str) -> dict | None:
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def all_objects(self) -> Iterator[dict]:
        refs: list[dict] = []
        for shard in self._shards:
            with shard.lock:
                refs.extend(shard.objects.values())
        return iter([k8s.deepcopy(o) for o in refs])
