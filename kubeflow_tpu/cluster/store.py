"""In-process Kubernetes API server.

This is the framework's envtest analog *and* a first-class component: the
reference boots a real apiserver+etcd via sigs.k8s.io/controller-runtime/envtest
(suite_test.go:50-110) because its controllers speak only API-server state
(SURVEY §1: "two independent controller processes cooperate on one CRD purely
through API-server state"). We reproduce the semantics the controllers rely on:

- optimistic concurrency via metadata.resourceVersion (ConflictError on stale
  updates — what retry.RetryOnConflict loops on in the reference,
  culling_controller.go:107,125,144,172);
- GenerateName materialization (apiserver suffixing; notebook_controller.go:444-449
  depends on this for >52-char names);
- finalizers + deletionTimestamp two-phase delete (odh notebook_controller.go:207-333);
- ownerReference cascade GC (the reference leans on GC for STS/Service/SA/CM
  cleanup, SURVEY §3.4);
- watch fan-out with ADDED/MODIFIED/DELETED events feeding controller workqueues
  (SetupWithManager watches, notebook_controller.go:778-826).

Thread-safe; a single ``threading.RLock`` guards the state — the apiserver is
the serialization point exactly as in Kubernetes.
"""

from __future__ import annotations

import base64
import bisect
import itertools
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterator

from ..utils import k8s
from ..utils.names import generate_suffix
from .errors import (AlreadyExistsError, ConflictError, InvalidError,
                     NotFoundError)

CLUSTER_SCOPED_KINDS = {
    "Namespace", "ClusterRole", "ClusterRoleBinding", "OAuthClient",
    "CustomResourceDefinition", "PriorityClass", "Node", "APIServer",
    "MutatingWebhookConfiguration", "ValidatingWebhookConfiguration",
}


@dataclass(frozen=True)
class ObjectKey:
    kind: str
    namespace: str
    name: str


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    obj: dict


@dataclass
class _Watch:
    kind: str
    callback: Callable[[WatchEvent], None]
    namespace: str | None = None
    label_selector: dict[str, str] | None = None


_now_iso = k8s.now_iso


def _encode_continue(namespace: str, name: str) -> str:
    """Opaque continue token naming the last key a page served (the real
    apiserver's token is likewise base64 JSON of a positional cursor)."""
    raw = json.dumps([namespace, name]).encode()
    return base64.urlsafe_b64encode(raw).decode().rstrip("=")


def _decode_continue(token: str) -> tuple[str, str]:
    pad = "=" * (-len(token) % 4)
    try:
        ns, nm = json.loads(base64.urlsafe_b64decode(token + pad))
        return (str(ns), str(nm))
    except (ValueError, TypeError):
        raise InvalidError(f"malformed continue token {token!r}") from None


class ClusterStore:
    """The in-process apiserver + etcd. All mutating verbs return a deep copy
    of the stored object (as the real apiserver returns the canonical form)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._objects: dict[ObjectKey, dict] = {}
        self._rv_counter = itertools.count(1)
        self._last_rv = 0  # latest issued rv — reported in LIST metadata
        # one-entry sorted-key snapshot for paginated LISTs: a pager walks
        # the same (kind, namespace) shape page after page, and re-sorting
        # the whole kind under the lock per page would make one chunked
        # LIST O(pages × N log N) of lock-held work. Keyed on _last_rv, so
        # any write invalidates it (deletes don't bump rv — the pop loop
        # below tolerates keys deleted since the snapshot).
        self._page_snapshot: tuple | None = None  # (kind, ns, rv, pairs)
        self._uid_counter = itertools.count(1)
        self._watches: list[_Watch] = []
        # admission hooks: list of (kind, fn(operation, obj, old) -> obj|raise)
        self._admission: list[tuple[str, Callable]] = []
        # CRD structural schemas: kind → {version: openAPIV3Schema}; kept in
        # step with CustomResourceDefinition objects so CRs are validated
        # server-side, as kube-apiserver does for installed CRDs
        self._crd_schemas: dict[str, dict[str, dict]] = {}
        # Mutating/ValidatingWebhookConfiguration objects, indexed so writes
        # call out over real HTTPS AdmissionReview (cluster/remote_admission)
        self._webhook_configs: dict[str, dict[ObjectKey, dict]] = {}

    def _next_rv(self) -> str:
        """Issue the next resourceVersion (caller holds the lock) and
        remember it — LIST metadata reports the latest issued rv, the
        anchor for informer-style ``resourceVersion=0`` list-then-watch."""
        self._last_rv = next(self._rv_counter)
        return str(self._last_rv)

    # ------------------------------------------------------------------ keys
    def _key(self, kind: str, namespace: str, name: str) -> ObjectKey:
        if kind in CLUSTER_SCOPED_KINDS:
            namespace = ""
        return ObjectKey(kind, namespace, name)

    def _key_of(self, obj: dict) -> ObjectKey:
        return self._key(k8s.kind(obj), k8s.namespace(obj), k8s.name(obj))

    # ------------------------------------------------------------- admission
    def register_admission(self, kind: str, fn: Callable) -> None:
        """Register an admission plugin invoked before create/update/patch is
        persisted — the seam the mutating/validating webhooks plug into
        (the reference registers these on the manager's webhook server,
        odh main.go:306-331; kube-apiserver calls them in-flight)."""
        self._admission.append((kind, fn))

    def _admit(self, operation: str, obj: dict, old: dict | None) -> dict:
        for kind, fn in self._admission:
            if kind == k8s.kind(obj):
                obj = fn(operation, obj, old)
        obj = self._run_remote_admission(operation, obj, old)
        # schema validation runs AFTER webhooks, on what will be persisted —
        # the apiserver's phase order (mutating admission → schema →
        # persistence)
        self._validate_against_crd(obj)
        return obj

    def _run_remote_admission(self, operation: str, obj: dict,
                              old: dict | None) -> dict:
        """HTTPS AdmissionReview against registered webhook configurations
        (mutating phase, then validating — the apiserver's order). The
        config index is snapshotted under the lock; the HTTP calls run
        outside it (see create())."""
        from . import remote_admission as ra
        if k8s.kind(obj) in ra.CONFIG_KINDS:
            return obj  # configurations themselves are not gated
        with self._lock:
            mutating = [k8s.deepcopy(c) for c in
                        self._webhook_configs.get(ra.MUTATING_KIND,
                                                  {}).values()]
            validating = [k8s.deepcopy(c) for c in
                          self._webhook_configs.get(ra.VALIDATING_KIND,
                                                    {}).values()]
        if mutating:
            obj = ra.run_webhooks(mutating, operation, obj, old,
                                  mutating=True)
        if validating:
            ra.run_webhooks(validating, operation, obj, old, mutating=False)
        return obj

    def _index_webhook_config(self, key: ObjectKey, obj: dict) -> None:
        self._webhook_configs.setdefault(key.kind, {})[key] = k8s.deepcopy(obj)

    def _unindex_webhook_config(self, key: ObjectKey) -> None:
        self._webhook_configs.get(key.kind, {}).pop(key, None)

    # -------------------------------------------------------- CRD schemas
    def _index_crd(self, crd: dict) -> None:
        kind = k8s.get_in(crd, "spec", "names", "kind")
        if not kind:
            return
        versions = {}
        for v in k8s.get_in(crd, "spec", "versions", default=[]) or []:
            s = k8s.get_in(v, "schema", "openAPIV3Schema")
            if v.get("served") and s:
                versions[v["name"]] = s
        if versions:
            self._crd_schemas[kind] = versions

    def _unindex_crd(self, crd: dict) -> None:
        kind = k8s.get_in(crd, "spec", "names", "kind")
        self._crd_schemas.pop(kind, None)

    def _validate_against_crd(self, obj: dict) -> None:
        with self._lock:  # schema index is written under the lock
            versions = self._crd_schemas.get(k8s.kind(obj))
        if not versions:
            return
        version = (obj.get("apiVersion") or "").rpartition("/")[2]
        schema = versions.get(version)
        if schema is None:
            return  # unserved/unknown version: caught by typed admission
        from ..api.schema import validate_schema
        errors = validate_schema(obj, schema)
        if errors:
            shown = "; ".join(errors[:5])
            if len(errors) > 5:
                shown += f" (+{len(errors) - 5} more)"
            raise InvalidError(
                f"{k8s.kind(obj)} {k8s.namespace(obj)}/{k8s.name(obj)} "
                f"is invalid: {shown}")

    # ----------------------------------------------------------------- verbs
    def create(self, obj: dict) -> dict:
        obj = k8s.deepcopy(obj)
        # admission runs OUTSIDE the store lock (kube-apiserver holds no
        # global lock around webhook calls): remote webhooks are HTTPS
        # round-trips whose handlers read back into this store from their
        # own threads — under the lock that is a deadlock. Races admitted
        # here are caught at persist (AlreadyExists / Conflict).
        obj = self._admit("CREATE", obj, None)
        with self._lock:
            md = k8s.meta(obj)
            if not md.get("name") and md.get("generateName"):
                md["name"] = md["generateName"] + generate_suffix(
                    f'{md["generateName"]}{next(self._uid_counter)}', 5)
            if not md.get("name"):
                raise InvalidError("metadata.name or generateName required")
            key = self._key_of(obj)
            if key in self._objects:
                raise AlreadyExistsError(f"{key.kind} {key.namespace}/{key.name}")
            md["uid"] = f"uid-{next(self._uid_counter)}"
            md["resourceVersion"] = self._next_rv()
            md["generation"] = 1
            md.setdefault("creationTimestamp", _now_iso())
            self._objects[key] = obj
            if key.kind == "CustomResourceDefinition":
                self._index_crd(obj)
            elif key.kind in ("MutatingWebhookConfiguration",
                              "ValidatingWebhookConfiguration"):
                self._index_webhook_config(key, obj)
            stored = k8s.deepcopy(obj)
        self._notify(WatchEvent("ADDED", stored))
        return k8s.deepcopy(stored)

    def get(self, kind: str, namespace: str, name: str) -> dict:
        with self._lock:
            key = self._key(kind, namespace, name)
            obj = self._objects.get(key)
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name}")
            return k8s.deepcopy(obj)

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict[str, str] | None = None) -> list[dict]:
        items, _, _ = self.list_page(kind, namespace, label_selector)
        return items

    def list_page(self, kind: str, namespace: str | None = None,
                  label_selector: dict[str, str] | None = None,
                  limit: int | None = None,
                  continue_token: str | None = None,
                  resource_version: str | None = None,
                  ) -> tuple[list[dict], str | None, str]:
        """LIST with apiserver chunking semantics (``limit``/``continue``,
        apimachinery ListOptions). Returns ``(items, next_continue,
        list_rv)``; ``next_continue`` is None on the final page.

        Keys are served in deterministic ``(namespace, name)`` order and
        the continue token names the last key a page walked, so on a
        quiescent population the pages compose into exactly the
        unpaginated set for every page size (the equivalence the tests
        pin). Objects created/deleted between pages may be missed or seen
        once, as with the real chunked LIST — level-triggered consumers
        tolerate that, and the watch diff repairs it.

        ``resource_version``: ``"0"`` is the informer cache-ack form —
        "any stored state is acceptable, don't require quorum"; this store
        IS the state of record, so it serves current state (the point of
        accepting it is that clients can pipeline list-then-watch without
        a special case). Exact/minimum-rv forms are likewise served from
        current state — there are no historical snapshots here. ``list_rv``
        is the latest issued resourceVersion, the anchor a watch would
        start from."""
        start_after = (_decode_continue(continue_token)
                       if continue_token else None)
        if limit is not None and limit <= 0:
            limit = None  # limit=0 means "no limit", as on the wire
        with self._lock:
            pairs = self._sorted_pairs_locked(kind, namespace,
                                              snapshot=limit is not None)
            start = (bisect.bisect_right(pairs, start_after)
                     if start_after is not None else 0)
            out: list[dict] = []
            last_pair: tuple[str, str] | None = None
            next_token: str | None = None
            for pair in pairs[start:]:
                # a key may have been deleted since the snapshot (deletes
                # don't bump rv): skip — same "objects deleted between
                # pages may be missed" contract as the real chunked LIST
                obj = self._objects.get(ObjectKey(kind, pair[0], pair[1]))
                if obj is None or not k8s.matches_labels(obj,
                                                         label_selector):
                    continue
                if limit is not None and len(out) >= limit:
                    # page full with at least one candidate left: hand out
                    # a cursor at the last key actually served
                    next_token = _encode_continue(*last_pair)
                    break
                out.append(k8s.deepcopy(obj))
                last_pair = pair
            return out, next_token, str(self._last_rv)

    def _sorted_pairs_locked(self, kind: str, namespace: str | None,
                             snapshot: bool) -> list[tuple[str, str]]:
        """Sorted (namespace, name) pairs for a kind (caller holds the
        lock). Paginated calls (``snapshot=True``) reuse the one-entry
        snapshot while no write has bumped ``_last_rv``, so walking a big
        fleet in pages sorts once, not once per page."""
        token = (kind, namespace, self._last_rv)
        if snapshot and self._page_snapshot is not None and \
                self._page_snapshot[:3] == token:
            return self._page_snapshot[3]
        pairs = sorted(
            (key.namespace, key.name) for key in self._objects
            if key.kind == kind
            and (namespace is None or key.namespace == namespace))
        if snapshot:
            self._page_snapshot = (*token, pairs)
        return pairs

    def update(self, obj: dict) -> dict:
        obj = k8s.deepcopy(obj)
        deferred_events: list[WatchEvent] = []
        key = self._key_of(obj)
        # snapshot + early conflict check, then admit OUTSIDE the lock (see
        # create()); the post-admission check below re-validates that the
        # state admitted against is still the state being replaced
        with self._lock:
            old_snapshot = self._objects.get(key)
            if old_snapshot is None:
                raise NotFoundError(f"{key.kind} {key.namespace}/{key.name}")
            old_snapshot = k8s.deepcopy(old_snapshot)
        snapshot_rv = old_snapshot["metadata"]["resourceVersion"]
        new_rv = k8s.get_in(obj, "metadata", "resourceVersion")
        if new_rv is not None and new_rv != snapshot_rv:
            raise ConflictError(
                f"{key.kind} {key.namespace}/{key.name}: stale resourceVersion")
        obj = self._admit("UPDATE", obj, old_snapshot)
        with self._lock:
            old = self._objects.get(key)
            if old is None:
                raise NotFoundError(f"{key.kind} {key.namespace}/{key.name}")
            # re-check ONLY for optimistic writers: a no-RV update keeps the
            # apiserver's unconditional last-write-wins semantics even when a
            # concurrent write landed during the out-of-lock admission window
            if new_rv is not None and \
                    old["metadata"]["resourceVersion"] != snapshot_rv:
                raise ConflictError(
                    f"{key.kind} {key.namespace}/{key.name}: object changed "
                    f"during admission")
            md = k8s.meta(obj)
            md["uid"] = old["metadata"]["uid"]
            md["creationTimestamp"] = old["metadata"]["creationTimestamp"]
            if k8s.get_in(old, "metadata", "deletionTimestamp"):
                md["deletionTimestamp"] = old["metadata"]["deletionTimestamp"]
            md["resourceVersion"] = self._next_rv()
            if obj.get("spec") != old.get("spec"):
                md["generation"] = old["metadata"].get("generation", 1) + 1
            else:
                md["generation"] = old["metadata"].get("generation", 1)
            if (k8s.get_in(obj, "metadata", "deletionTimestamp")
                    and not k8s.get_in(obj, "metadata", "finalizers")):
                # last finalizer stripped → actually remove (two-phase delete)
                deferred_events = self._remove_and_gc(key, replacement=obj)
            else:
                self._objects[key] = obj
                if key.kind == "CustomResourceDefinition":
                    self._index_crd(obj)
                elif key.kind in ("MutatingWebhookConfiguration",
                                  "ValidatingWebhookConfiguration"):
                    self._index_webhook_config(key, obj)
                deferred_events = [WatchEvent("MODIFIED", k8s.deepcopy(obj))]
            stored = k8s.deepcopy(obj)
        for ev in deferred_events:
            self._notify(ev)
        return k8s.deepcopy(stored)

    # bounds the patch re-merge loop: each retry re-runs admission (possibly
    # remote HTTPS round-trips), so a hot object must back off and eventually
    # surface the conflict rather than livelock
    PATCH_MAX_RETRIES = 20

    def patch(self, kind: str, namespace: str, name: str, patch: dict) -> dict:
        """RFC 7386 JSON merge patch (client.MergeFrom semantics). Unlike
        update(), it re-merges against the current version on a concurrent
        write, as the reference relies on for annotation removal
        (odh notebook_controller.go:516-523) — with bounded backoff now that
        each attempt may spend webhook round-trips outside the lock."""
        for attempt in range(self.PATCH_MAX_RETRIES):
            with self._lock:
                key = self._key(kind, namespace, name)
                old = self._objects.get(key)
                if old is None:
                    raise NotFoundError(f"{kind} {namespace}/{name}")
                merged = k8s.json_merge_patch(old, patch)
                k8s.meta(merged)["resourceVersion"] = old["metadata"]["resourceVersion"]
            try:
                return self.update(merged)
            except ConflictError:
                # raced a concurrent writer; re-merge on the new version
                time.sleep(min(0.001 * (2 ** attempt), 0.1))
        raise ConflictError(f"{kind} {namespace}/{name}: patch kept "
                            f"conflicting after {self.PATCH_MAX_RETRIES} "
                            f"attempts")

    def update_status(self, obj: dict) -> dict:
        """Status subresource semantics: only .status is applied."""
        with self._lock:
            key = self._key_of(obj)
            old = self._objects.get(key)
            if old is None:
                raise NotFoundError(f"{key.kind} {key.namespace}/{key.name}")
            new_rv = k8s.get_in(obj, "metadata", "resourceVersion")
            if new_rv is not None and new_rv != old["metadata"]["resourceVersion"]:
                raise ConflictError(f"{key.kind} {key.namespace}/{key.name}")
            stored = k8s.deepcopy(old)
            stored["status"] = k8s.deepcopy(obj.get("status", {}))
            stored["metadata"]["resourceVersion"] = self._next_rv()
            self._objects[key] = stored
            out = k8s.deepcopy(stored)
        self._notify(WatchEvent("MODIFIED", out))
        return k8s.deepcopy(out)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        """Two-phase delete: finalizers present → set deletionTimestamp and
        wait for controllers to strip them; else remove + cascade to owned
        objects (background GC)."""
        with self._lock:
            snapshot = self._objects.get(self._key(kind, namespace, name))
            if snapshot is None:
                raise NotFoundError(f"{kind} {namespace}/{name}")
            snapshot = k8s.deepcopy(snapshot)
        # DELETE-gating webhooks (operations: ["DELETE"]) fire like the real
        # apiserver's; outside the lock (see create())
        self._run_remote_admission("DELETE", snapshot, snapshot)
        events: list[WatchEvent] = []
        with self._lock:
            key = self._key(kind, namespace, name)
            obj = self._objects.get(key)
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name}")
            if k8s.get_in(obj, "metadata", "finalizers"):
                if not k8s.get_in(obj, "metadata", "deletionTimestamp"):
                    obj["metadata"]["deletionTimestamp"] = _now_iso()
                    obj["metadata"]["resourceVersion"] = self._next_rv()
                    events.append(WatchEvent("MODIFIED", k8s.deepcopy(obj)))
            else:
                events.extend(self._remove_and_gc(key))
        for ev in events:
            self._notify(ev)

    # ------------------------------------------------------- delete plumbing
    def _remove_and_gc(self, key: ObjectKey,
                       replacement: dict | None = None) -> list[WatchEvent]:
        """Remove object and cascade-delete dependents via ownerReferences,
        honoring dependents' own finalizers. Caller holds the lock."""
        obj = replacement if replacement is not None else self._objects.get(key)
        events: list[WatchEvent] = []
        if key in self._objects:
            del self._objects[key]
        if obj is None:
            return events
        if key.kind == "CustomResourceDefinition":
            self._unindex_crd(obj)
        elif key.kind in ("MutatingWebhookConfiguration",
                          "ValidatingWebhookConfiguration"):
            self._unindex_webhook_config(key)
        events.append(WatchEvent("DELETED", k8s.deepcopy(obj)))
        owner_uid = k8s.uid(obj)
        if owner_uid:
            dependents = [dk for dk, dobj in self._objects.items()
                          if k8s.is_owned_by(dobj, owner_uid)]
            for dk in dependents:
                dobj = self._objects.get(dk)
                if dobj is None:
                    continue
                if k8s.get_in(dobj, "metadata", "finalizers"):
                    if not k8s.get_in(dobj, "metadata", "deletionTimestamp"):
                        dobj["metadata"]["deletionTimestamp"] = _now_iso()
                        dobj["metadata"]["resourceVersion"] = self._next_rv()
                        events.append(WatchEvent("MODIFIED", k8s.deepcopy(dobj)))
                else:
                    events.extend(self._remove_and_gc(dk))
        return events

    # ----------------------------------------------------------------- watch
    def watch(self, kind: str, callback: Callable[[WatchEvent], None],
              namespace: str | None = None,
              label_selector: dict[str, str] | None = None) -> None:
        with self._lock:
            self._watches.append(_Watch(kind, callback, namespace, label_selector))

    def unwatch(self, callback: Callable[[WatchEvent], None]) -> None:
        """Deregister a watch callback (watch stream teardown — the apiserver
        facade drops its per-connection relay when the HTTP client goes away)."""
        with self._lock:
            self._watches = [w for w in self._watches if w.callback is not callback]

    def _notify(self, event: WatchEvent) -> None:
        kind = k8s.kind(event.obj)
        ns = k8s.namespace(event.obj)
        # snapshot under lock, dispatch outside to avoid deadlocks with
        # callbacks that call back into the store
        with self._lock:
            targets = [w for w in self._watches
                       if w.kind == kind
                       and (w.namespace is None or w.namespace == ns)
                       and k8s.matches_labels(event.obj, w.label_selector)]
        for w in targets:
            w.callback(WatchEvent(event.type, k8s.deepcopy(event.obj)))

    # ----------------------------------------------------------- conveniences
    def get_or_none(self, kind: str, namespace: str, name: str) -> dict | None:
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def all_objects(self) -> Iterator[dict]:
        with self._lock:
            return iter([k8s.deepcopy(o) for o in self._objects.values()])
