"""Kind → REST mapping (the analog of controller-runtime's RESTMapper).

The reference's client knows how to turn a typed object into an apiserver
URL via the discovery-backed RESTMapper inside client-go; our API objects are
plain dicts keyed by ``kind``, so the mapping lives in one static table
covering every kind the controllers touch. An unknown kind raises — a
fabricated group/version would just 404 confusingly on a real apiserver;
extend the table (or pass a RestMapping) instead.

Path shapes (the real wire format):

- core v1, namespaced:    /api/v1/namespaces/{ns}/{plural}[/{name}]
- core v1, cluster:       /api/v1/{plural}[/{name}]
- group, namespaced:      /apis/{group}/{version}/namespaces/{ns}/{plural}[/{name}]
- group, cluster:         /apis/{group}/{version}/{plural}[/{name}]
- all-namespace list:     the namespaced shape minus the namespaces segment
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class RestMapping:
    kind: str
    api_version: str  # "v1" or "group/version"
    plural: str
    namespaced: bool = True

    @property
    def group_version(self) -> tuple[str, str]:
        if "/" in self.api_version:
            group, version = self.api_version.split("/", 1)
            return group, version
        return "", self.api_version

    def path(self, namespace: str | None = None, name: str | None = None,
             subresource: str | None = None) -> str:
        group, version = self.group_version
        parts = ["/api", version] if not group else ["/apis", group, version]
        if self.namespaced and namespace:
            parts += ["namespaces", namespace]
        parts.append(self.plural)
        if name:
            parts.append(name)
            if subresource:
                parts.append(subresource)
        return "/".join(parts)


_MAPPINGS = [
    # core/v1
    RestMapping("Pod", "v1", "pods"),
    RestMapping("Service", "v1", "services"),
    RestMapping("ConfigMap", "v1", "configmaps"),
    RestMapping("Secret", "v1", "secrets"),
    RestMapping("ServiceAccount", "v1", "serviceaccounts"),
    RestMapping("Event", "v1", "events"),
    RestMapping("PersistentVolumeClaim", "v1", "persistentvolumeclaims"),
    RestMapping("Namespace", "v1", "namespaces", namespaced=False),
    RestMapping("Node", "v1", "nodes", namespaced=False),
    # apps/v1
    RestMapping("StatefulSet", "apps/v1", "statefulsets"),
    RestMapping("Deployment", "apps/v1", "deployments"),
    # our CRDs
    RestMapping("Notebook", "kubeflow.org/v1", "notebooks"),
    RestMapping("SlicePool", "tpu.kubeflow.org/v1", "slicepools",
                namespaced=False),
    RestMapping("TPUQuota", "tpu.kubeflow.org/v1", "tpuquotas",
                namespaced=False),
    # networking
    RestMapping("NetworkPolicy", "networking.k8s.io/v1", "networkpolicies"),
    # rbac
    RestMapping("Role", "rbac.authorization.k8s.io/v1", "roles"),
    RestMapping("RoleBinding", "rbac.authorization.k8s.io/v1", "rolebindings"),
    RestMapping("ClusterRole", "rbac.authorization.k8s.io/v1",
                "clusterroles", namespaced=False),
    RestMapping("ClusterRoleBinding", "rbac.authorization.k8s.io/v1",
                "clusterrolebindings", namespaced=False),
    # gateway API
    RestMapping("HTTPRoute", "gateway.networking.k8s.io/v1", "httproutes"),
    RestMapping("Gateway", "gateway.networking.k8s.io/v1", "gateways"),
    RestMapping("ReferenceGrant", "gateway.networking.k8s.io/v1beta1",
                "referencegrants"),
    # coordination
    RestMapping("Lease", "coordination.k8s.io/v1", "leases"),
    # apiextensions
    RestMapping("CustomResourceDefinition", "apiextensions.k8s.io/v1",
                "customresourcedefinitions", namespaced=False),
    # admissionregistration
    RestMapping("MutatingWebhookConfiguration",
                "admissionregistration.k8s.io/v1",
                "mutatingwebhookconfigurations", namespaced=False),
    RestMapping("ValidatingWebhookConfiguration",
                "admissionregistration.k8s.io/v1",
                "validatingwebhookconfigurations", namespaced=False),
    # scheduling
    RestMapping("PriorityClass", "scheduling.k8s.io/v1", "priorityclasses",
                namespaced=False),
    # OpenShift groups the extension controller touches
    RestMapping("APIServer", "config.openshift.io/v1", "apiservers",
                namespaced=False),
    RestMapping("Proxy", "config.openshift.io/v1", "proxies",
                namespaced=False),
    RestMapping("OAuthClient", "oauth.openshift.io/v1", "oauthclients",
                namespaced=False),
    RestMapping("ImageStream", "image.openshift.io/v1", "imagestreams"),
    RestMapping("Route", "route.openshift.io/v1", "routes"),
    # DSPA + Istio
    RestMapping("DataSciencePipelinesApplication",
                "datasciencepipelinesapplications.opendatahub.io/v1alpha1",
                "datasciencepipelinesapplications"),
    RestMapping("VirtualService", "networking.istio.io/v1beta1",
                "virtualservices"),
]

_BY_KIND = {m.kind: m for m in _MAPPINGS}
_BY_ROUTE: dict[tuple[str, str, str], RestMapping] = {}
for _m in _MAPPINGS:
    _g, _v = _m.group_version
    _BY_ROUTE[(_g, _v, _m.plural)] = _m


def register(mapping: RestMapping) -> None:
    """Extend the table at runtime (user-defined CRDs)."""
    _BY_KIND[mapping.kind] = mapping
    group, version = mapping.group_version
    _BY_ROUTE[(group, version, mapping.plural)] = mapping


def mapping_for(kind: str) -> RestMapping:
    mapping = _BY_KIND.get(kind)
    if mapping is None:
        raise KeyError(
            f"no REST mapping for kind {kind!r}; register one with "
            f"restmapper.register(RestMapping(...)) or add it to the table")
    return mapping


def mapping_for_route(group: str, version: str, plural: str) -> RestMapping | None:
    m = _BY_ROUTE.get((group, version, plural))
    if m is not None:
        return m
    # tolerate version drift (e.g. a client speaking v1beta1 for a kind we
    # serve at v1) the way the real apiserver serves multiple versions
    for (g, _v, p), cand in _BY_ROUTE.items():
        if g == group and p == plural:
            return cand
    return None
