"""HTTP(S) Kubernetes API client — the real-cluster transport.

Implements the same client protocol the controllers already consume from
``ClusterStore`` (get/get_or_none/list/create/update/update_status/patch/
delete/watch) over the Kubernetes REST wire protocol, so the reconcilers run
unmodified against a real apiserver — the role client-go plays for the
reference's managers (controllers speak HTTPS to kube-apiserver,
notebook-controller/main.go:95-148; odh main.go:236-275).

Auth, mirroring client-go's loading order:

- ``HttpApiClient.from_kubeconfig(path)`` — kubeconfig contexts: bearer
  token, client certificates (inline ``*-data`` or file paths), cluster CA;
- ``HttpApiClient.in_cluster()`` — the ServiceAccount mount
  (/var/run/secrets/kubernetes.io/serviceaccount) + KUBERNETES_SERVICE_HOST,
  exactly what the deploy manifests give the manager pod;
- plain constructor for tests / token-only setups.

Transport: requests ride per-thread persistent HTTP/1.1 connections
(keep-alive pool) instead of a fresh TCP connect per request — the server
half of every request's round-trips, and the per-connection handler-thread
spawn on the facade, disappear from the hot path. A reused connection the
server closed idle is retried ONCE on a fresh one, only when the failure
happened at SEND time (the server never read the request, so the retry is
safe for every verb); response-phase failures keep their PR-2 ambiguity
semantics and are owned by the RetryPolicy layer.

Watches are reconnecting daemon threads reading the newline-delimited JSON
stream (``?watch=true``). The loop tracks the resourceVersion of the last
event it DELIVERED (bookmark frames anchor idle streams) and reconnects
with ``?resourceVersion=N``: the apiserver replays the retained window
after N — no LIST, no gap, O(delta) — and answers ``410 Gone`` when the
window was evicted, which drops the cursor and falls back to the original
LIST+diff resync: changed/new objects re-deliver as MODIFIED/ADDED and
objects that vanished synthesize DELETED — so informer caches can neither
go stale nor keep ghosts across apiserver restarts. ``watch_resumes_total``
counts which path each reconnect took.

In-process admission registration is NOT available here: against a real
apiserver, admission runs via webhook configurations served by the manager's
AdmissionServer (config/webhook), exactly as in the reference.
"""

from __future__ import annotations

import base64
import http.client
import itertools
import json
import logging
import os
import random
import socket
import ssl
import struct
import tempfile
import threading
import time
import urllib.error
from dataclasses import dataclass
from urllib.parse import quote, urlencode, urlsplit

from ..utils import k8s, sanitizer, tracing
from . import codec, restmapper
from .errors import (AlreadyExistsError, ApiError, ConflictError,
                     ForbiddenError, GoneError, InvalidError, NotFoundError,
                     ServiceUnavailableError, TooManyRequestsError)
from .store import WatchEvent

log = logging.getLogger("kubeflow_tpu.http_client")

_TRACER = tracing.get_tracer("kubeflow_tpu.http_client")

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def _resource_from_path(path: str) -> str:
    """Resource plural out of an API path for span attributes —
    ``/apis/g/v1/namespaces/ns/notebooks/name`` → ``notebooks``;
    best-effort (attribute metadata, never load-bearing)."""
    parts = path.split("?", 1)[0].strip("/").split("/")
    try:
        i = parts.index("namespaces")
        return parts[i + 2] if len(parts) > i + 2 else parts[-1]
    except ValueError:
        return parts[3] if parts[:1] == ["apis"] and len(parts) > 3 \
            else parts[-1]

_ERROR_BY_REASON = {
    "NotFound": NotFoundError,
    "AlreadyExists": AlreadyExistsError,
    "Conflict": ConflictError,
    "Invalid": InvalidError,
    "Forbidden": ForbiddenError,
    "TooManyRequests": TooManyRequestsError,
    "ServiceUnavailable": ServiceUnavailableError,
    "Expired": GoneError,
}
_ERROR_BY_CODE = {404: NotFoundError, 409: ConflictError, 410: GoneError,
                  422: InvalidError, 403: ForbiddenError,
                  429: TooManyRequestsError, 503: ServiceUnavailableError}

#: failures that mean "the bytes didn't arrive", not "the server said no":
#: connection refused/reset (URLError/OSError) and a response that
#: truncated mid-wire (IncompleteRead/BadStatusLine are HTTPExceptions,
#: NOT OSErrors — a reset-mid-body previously escaped every handler here)
TRANSPORT_ERRORS = (urllib.error.URLError, OSError, http.client.HTTPException)


class MalformedListError(http.client.HTTPException):
    """A LIST response parsed as JSON but carries no ``items`` array — a
    truncated/foreign body (LB error page, apiserver killed mid-write)
    that must surface as a retryable transport failure. Reading it as an
    empty list would be catastrophic during a watch resync: the RV-diff
    would synthesize DELETED for every live object."""


class MalformedBinaryError(http.client.HTTPException):
    """A binary-negotiated response body that failed to decode — the
    codec's CodecError lifted into the transport-error taxonomy
    (⊂ TRANSPORT_ERRORS), so a truncated or foreign binary body rides the
    same bounded retry + breaker accounting as a JSONDecodeError on a
    truncated JSON body. Never a silent partial decode."""


@dataclass(frozen=True)
class RetryPolicy:
    """client-go-style bounded retries with decorrelated-jitter backoff.

    What retries (the policy table, also in ARCHITECTURE.md):

    - ``429`` — every verb: the server rejected the request before
      processing (priority-and-fairness), so retry is always safe;
      ``Retry-After`` overrides the computed backoff when sent.
    - ``503`` — idempotent verbs only (GET/LIST/DELETE).
    - transport errors (refused/reset/truncated) — idempotent verbs, plus
      *named* creates: a reset POST may or may not have applied, and the
      retry disambiguates via 409 AlreadyExists + a live read. generateName
      creates never retry on transport errors (a blind retry could
      materialize two objects).
    - PUT/PATCH — 429 only: resourceVersion preconditions + the
      reconcilers' conflict-retry loops own that ambiguity.
    """

    max_attempts: int = 4
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0

# Watch streams ask the server to close gracefully after this long
# (?timeoutSeconds=, honored by real apiservers); the socket read timeout
# sits just above it so a dead stream is still detected. Our facade sends
# 10s bookmarks, a real apiserver sends nothing on an idle watch — either
# way a reconnect costs one list that delivers nothing when RVs are
# unchanged, so the cadence is cheap.
WATCH_SERVER_TIMEOUT_S = 290
WATCH_READ_TIMEOUT_S = WATCH_SERVER_TIMEOUT_S + 10.0
WATCH_RECONNECT_DELAY_S = 1.0
# consecutive watch reconnect failures back off exponentially from
# WATCH_RECONNECT_DELAY_S up to this cap (an unreachable apiserver must
# not be hammered at 1 Hz per watched kind); a stream that lived this
# long before dropping resets the backoff
WATCH_BACKOFF_MAX_S = 30.0
WATCH_BACKOFF_RESET_AFTER_S = 5.0


def _read_exact(resp, n: int) -> bytes:
    """Read exactly ``n`` bytes from a streaming response, short only at
    EOF (http.client's read(n) may return fewer on a chunk boundary)."""
    out = b""
    while len(out) < n:  # bounded: returns short the moment read() EOFs
        part = resp.read(n - len(out))
        if not part:
            return out
        out += part
    return out


def _require_items(parsed: dict) -> None:
    """LIST-body validator for _json: no ``items`` array → transport
    failure (see MalformedListError)."""
    if not isinstance(parsed, dict) or \
            not isinstance(parsed.get("items"), list):
        raise MalformedListError("LIST body has no items array")


def _serialize_selector(selector: dict) -> str:
    """k8s labelSelector grammar subset: ``key=value`` equality terms plus
    bare ``key`` existence terms (value ``None``)."""
    return ",".join(key if val is None else f"{key}={val}"
                    for key, val in selector.items())


def _error_from_response(code: int, body: bytes,
                         headers=None) -> ApiError:
    reason, message = "", ""
    try:
        status = json.loads(body)
        reason = status.get("reason", "")
        message = status.get("message", "")
    except (ValueError, AttributeError):
        message = body.decode(errors="replace")[:200]
    cls = _ERROR_BY_REASON.get(reason) or _ERROR_BY_CODE.get(code) or ApiError
    err = cls(message or f"HTTP {code}")
    err.code = code  # preserve the wire status (e.g. 401) on generic errors
    if headers is not None:
        err.retry_after = _parse_retry_after(headers.get("Retry-After"))
    return err


def _parse_retry_after(raw: str | None) -> float | None:
    """Delay-seconds form only (integer per RFC 7231; our facade also sends
    sub-second floats). The HTTP-date form is ignored — client-go does the
    same for apiserver flow-control."""
    if not raw:
        return None
    try:
        return max(float(raw), 0.0)
    except ValueError:
        return None


def _data_or_file(data_b64: str | None, path: str | None) -> str | None:
    """Resolve kubeconfig's inline-base64-or-file-path pattern to a path."""
    if data_b64:
        tmp = tempfile.NamedTemporaryFile("wb", delete=False,
                                          prefix="kubeflow-tpu-kc-")
        tmp.write(base64.b64decode(data_b64))
        tmp.close()
        return tmp.name
    return path


class HttpApiClient:
    """Client protocol implementation over HTTP(S)."""

    supports_inprocess_admission = False

    def __init__(self, base_url, token: str | None = None,
                 ca_cert: str | None = None, client_cert: str | None = None,
                 client_key: str | None = None, verify: bool = True,
                 timeout: float = 30.0, metrics=None,
                 retry_policy: RetryPolicy | None = None,
                 list_page_size: int | None = None,
                 user_agent: str = "kubeflow-tpu-manager",
                 rng: random.Random | None = None,
                 wire_format: str = "json") -> None:
        # ``base_url`` accepts one URL, a comma-separated list, or a
        # list/tuple — the replicated-frontend form: every request can be
        # served by any frontend (one shared store behind them), so NEW
        # connections rotate endpoints and a connect failure transparently
        # fails over to the next one (mid-soak frontend kill: in-flight
        # requests on the dead frontend surface through the normal retry
        # machinery; every reconnect lands on a live one)
        if isinstance(base_url, (list, tuple)):
            urls = [u.rstrip("/") for u in base_url if u]
        else:
            urls = [u.strip().rstrip("/")
                    for u in base_url.split(",") if u.strip()]
        if not urls:
            raise ValueError("base_url names no endpoints")
        self.base_url = urls[0]
        self.endpoints = tuple(urls)
        # wire negotiation: "binary" sends/accepts the compact codec media
        # type (error Status bodies stay JSON — decode is driven by the
        # RESPONSE Content-Type, so a mixed fleet or a binary-unaware
        # server degrades to JSON transparently); "json" is the default
        # and the debugging path
        if wire_format not in ("json", "binary"):
            raise ValueError(f"unknown wire_format {wire_format!r}")
        self.wire_format = wire_format
        self._binary = wire_format == "binary"
        self._accept = (codec.BINARY_CONTENT_TYPE + ", application/json"
                        if self._binary else "application/json")
        self.token = token
        self.timeout = timeout
        self.retry_policy = retry_policy or RetryPolicy()
        # flow identity for the apiserver's priority & fairness layer
        # (cluster/apf.py classifies on the User-Agent header): manager
        # replicas keep the kubeflow-tpu prefix; tenant tooling should
        # set its own so a LIST storm lands in its own flow's queues
        self.user_agent = user_agent
        # LIST chunking (?limit=N&continue=…): bounds the memory and tail
        # latency of a fleet-sized LIST — the backfills and post-outage
        # resyncs page through instead of one giant body. None = unpaged.
        self.list_page_size = list_page_size
        # decorrelated jitter source; injectable so fault-injection tests
        # can seed the backoff schedule deterministically
        self._retry_rng = rng or random.Random()
        self._requests_metric = None
        self._retries_metric = None
        self._duration_metric = None
        self._connections_metric = None  # rest_client_connections_opened_total
        self._resumes_metric = None      # watch_resumes_total
        # keep-alive pool: one persistent connection per (thread, client) —
        # http.client connections are not thread-safe, and a thread's
        # requests are serial, so thread affinity IS the pool discipline
        self._addrs = []
        for url in self.endpoints:
            split = urlsplit(url)
            self._addrs.append(
                (split.scheme, split.hostname or "127.0.0.1",
                 split.port or (443 if split.scheme == "https" else 80),
                 split.path.rstrip("/")))
        self._addr = self._addrs[0]
        # round-robin cursor for new connections (itertools.count is
        # GIL-atomic; modulo at the use site)
        self._endpoint_counter = itertools.count()
        self._tl = threading.local()
        self._conns_lock = sanitizer.tracked_lock(
            "http.conns", order=sanitizer.ORDER_WATCH, no_blocking=True)
        # every pooled conn, so close() can reap
        self._conns: set = sanitizer.guarded_by(
            set(), self._conns_lock, "http.conns.pool")
        # optional apiserver health tracker (the manager's circuit
        # breaker): told about every transport-level success/failure —
        # an HTTP error response counts as SUCCESS (the server answered)
        self._health_tracker = None
        if metrics is not None:
            self.attach_metrics(metrics)
        self._ssl: ssl.SSLContext | None = None
        if self.base_url.startswith("https"):
            ctx = ssl.create_default_context(cafile=ca_cert)
            if not verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            if client_cert:
                ctx.load_cert_chain(client_cert, client_key)
            self._ssl = ctx
        self._stopped = threading.Event()
        # optional watch stream-health listener pair (on_gap, on_recover):
        # the read cache's degraded-mode hooks (CachingClient.mark_watch_gap)
        # — while any stream for a kind is down, cached reads of it go live
        self._watch_gap_listeners: tuple | None = None
        self._watch_threads: list[threading.Thread] = []
        # live watch responses, so close() can unblock readline() NOW
        # instead of waiting out the server's bookmark interval
        self._live_streams: set = set()
        self._streams_lock = sanitizer.tracked_lock(
            "http.streams", order=sanitizer.ORDER_WATCH, no_blocking=True)

    # ------------------------------------------------------------ factories
    @classmethod
    def from_kubeconfig(cls, path: str | None = None,
                        context: str | None = None) -> "HttpApiClient":
        import yaml
        path = path or os.environ.get("KUBECONFIG") or \
            os.path.expanduser("~/.kube/config")
        with open(path) as fh:
            cfg = yaml.safe_load(fh)
        ctx_name = context or cfg.get("current-context")
        ctx = next(c["context"] for c in cfg.get("contexts", [])
                   if c["name"] == ctx_name)
        cluster = next(c["cluster"] for c in cfg.get("clusters", [])
                       if c["name"] == ctx["cluster"])
        user = next((u["user"] for u in cfg.get("users", [])
                     if u["name"] == ctx.get("user")), {})
        return cls(
            cluster["server"],
            token=user.get("token"),
            ca_cert=_data_or_file(cluster.get("certificate-authority-data"),
                                  cluster.get("certificate-authority")),
            client_cert=_data_or_file(user.get("client-certificate-data"),
                                      user.get("client-certificate")),
            client_key=_data_or_file(user.get("client-key-data"),
                                     user.get("client-key")),
            verify=not cluster.get("insecure-skip-tls-verify", False),
        )

    @classmethod
    def in_cluster(cls) -> "HttpApiClient":
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(f"{SA_DIR}/token") as fh:
            token = fh.read().strip()
        ca = f"{SA_DIR}/ca.crt"
        return cls(f"https://{host}:{port}", token=token,
                   ca_cert=ca if os.path.exists(ca) else None)

    # ------------------------------------------------------------ transport
    def _new_conn(self, timeout: float, stream: bool = False):
        """Open a connection to the next endpoint in rotation, failing
        over across the remaining endpoints on a connect failure (a
        killed frontend disappears from new connections immediately; only
        when EVERY endpoint refuses does the failure surface)."""
        last_err: OSError | None = None
        for _ in range(len(self._addrs)):
            pick = next(self._endpoint_counter) % len(self._addrs)
            scheme, host, port, prefix = self._addrs[pick]
            if scheme == "https":
                conn = http.client.HTTPSConnection(host, port,
                                                   timeout=timeout,
                                                   context=self._ssl)
            else:
                conn = http.client.HTTPConnection(host, port,
                                                  timeout=timeout)
            try:
                conn.connect()
            except OSError as err:
                last_err = err
                continue
            # a persistent connection carries many small request/response
            # pairs: Nagle + delayed ACK turns each into a ~40 ms stall
            # (http.client writes headers and body in separate send()s)
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn._kt_prefix = prefix  # per-endpoint path prefix
            if self._connections_metric is not None:
                # pooled vs stream: one watch stream = one connection by
                # design (reconnect chaos churns them legitimately), so the
                # keep-alive reuse bound is computed over pooled conns only
                self._connections_metric.inc(
                    {"type": "stream" if stream else "pooled"})
            return conn
        raise last_err if last_err is not None else OSError("no endpoints")

    def _checkout(self, timeout: float, pooled: bool):
        """This thread's persistent connection (or a dedicated one for
        streams). Returns ``(conn, reused)`` — ``reused`` gates the
        stale-keep-alive retry in _request."""
        if not pooled:
            return self._new_conn(timeout, stream=True), False
        slot = self._tl
        conn = getattr(slot, "conn", None)
        if conn is not None:
            resp = getattr(slot, "resp", None)
            if resp is not None and not getattr(resp, "_kt_drained", False):
                # the previous response never finished (truncated body,
                # abandoned or PARTIAL read): the conn is mid-message —
                # recycle it. isclosed() alone cannot tell: a response
                # closed before EOF (read() raised mid-body, with-block
                # closed it) reports closed while unread bytes still sit
                # on the socket, and the next request would parse them as
                # its status line. Only a read that actually reached EOF
                # (_mark_drained) proves the conn is clean.
                self._discard(conn, pooled=True)
                conn = None
        reused = conn is not None
        if conn is None:
            conn = self._new_conn(timeout)
            slot.conn = conn
            with self._conns_lock:
                self._conns.add(conn)
        slot.resp = None
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
        else:
            conn.timeout = timeout  # applies at connect
        return conn, reused

    @staticmethod
    def _mark_drained(resp) -> None:
        """Record that ``resp`` was read to EOF — the proof _checkout
        needs that the pooled connection carries no leftover body bytes
        and is safe to reuse."""
        resp._kt_drained = True

    def _discard(self, conn, pooled: bool) -> None:
        if pooled:
            slot = self._tl
            if getattr(slot, "conn", None) is conn:
                slot.conn = None
                slot.resp = None
            with self._conns_lock:
                self._conns.discard(conn)
        try:
            conn.close()
        except OSError:
            pass

    def _request(self, method: str, path: str, body: dict | None = None,
                 content_type: str = "application/json",
                 timeout: float | None = None, pooled: bool = True):
        """One wire request over the keep-alive pool. ``pooled=False``
        (watch streams) opens a dedicated connection, attached to the
        response as ``_kt_conn`` so the stream can close it; everything
        else reuses this thread's persistent connection — the response
        must be fully read before the thread's next request (every caller
        does), or the next checkout recycles the connection."""
        data = None
        if body is not None:
            if self._binary:
                data = codec.encode(body)
                content_type = (codec.BINARY_PATCH_CONTENT_TYPE
                                if "merge-patch" in content_type
                                else codec.BINARY_CONTENT_TYPE)
            else:
                data = json.dumps(body).encode()
        headers = {"Accept": self._accept,
                   "User-Agent": self.user_agent}
        if data is not None:
            headers["Content-Type"] = content_type
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        # W3C trace-context propagation: every request carries the active
        # span's identity so the server's spans (APF wait, handler) and the
        # audit trail join the client's trace; None when tracing is off
        ctx = tracing.current_context()
        if ctx is not None:
            headers["traceparent"] = tracing.format_traceparent(ctx)
        timeout = timeout or self.timeout
        for attempt in (0, 1):
            conn, reused = None, False
            try:
                conn, reused = self._checkout(timeout, pooled)
                # path prefix is per-endpoint (a pooled conn remembers
                # which frontend it reached)
                conn.request(method,
                             getattr(conn, "_kt_prefix", self._addr[3])
                             + path,
                             body=data, headers=headers)
            except (http.client.HTTPException, OSError) as err:
                # SEND-phase failure (connect included): the server never
                # read this request. On a REUSED keep-alive connection the
                # overwhelming cause is the server having closed it idle —
                # retry ONCE on a fresh connection, transparently and for
                # EVERY verb (no bytes were processed, so no ambiguity). A
                # fresh connection failing is a real outage: surface it.
                if conn is not None:
                    self._discard(conn, pooled)
                if reused and attempt == 0:
                    continue
                self._count_request(method, "<error>")
                self._health_fail()
                err._kt_health_recorded = True  # _json must not double-count
                raise
            try:
                resp = conn.getresponse()
            except (http.client.HTTPException, OSError) as err:
                # RESPONSE-phase failure: the request MAY have been
                # processed (the PR-2 ambiguous shape) — owned by the
                # RetryPolicy layer, with ONE exception: a REUSED
                # connection closing with zero response bytes
                # (RemoteDisconnected) on a GET is the idle-close race
                # losing to our send — a GET retry is always safe, so
                # recover transparently (the Go transport's rule).
                # Mutations surface even then: without the policy layer's
                # ambiguous_retry marker, a silently retried create could
                # turn its own first write into a hard AlreadyExists.
                self._discard(conn, pooled)
                if reused and attempt == 0 and method == "GET" and \
                        isinstance(err, http.client.RemoteDisconnected):
                    continue
                self._count_request(method, "<error>")
                self._health_fail()
                err._kt_health_recorded = True
                raise
            break
        if pooled:
            self._tl.resp = resp  # reuse gate for the next checkout
        else:
            resp._kt_conn = conn  # the stream's teardown closes it
        if ctx is not None:
            # the innermost span here is _json's wire span (a noop sink on
            # untraced paths like watch streams)
            tracing.current_span().set_attribute("http.status", resp.status)
        if resp.status >= 400:
            payload = resp.read()  # frees the conn for reuse
            self._mark_drained(resp)
            if not pooled:
                # a dedicated stream connection whose request errored
                # (e.g. watch resume → 410 Gone) never reaches the
                # stream's teardown — close it here, not at GC time
                self._discard(conn, pooled=False)
            self._count_request(method, resp.status)
            self._health_ok()  # an error RESPONSE still means "reachable"
            raise _error_from_response(resp.status, payload,
                                       resp.headers) from None
        self._count_request(method, resp.status)
        self._health_ok()
        return resp

    def _count_request(self, method: str, code) -> None:
        if self._requests_metric is not None:
            self._requests_metric.inc({"method": method, "code": str(code)})

    def _count_retry(self, method: str, reason: str) -> None:
        if self._retries_metric is not None:
            self._retries_metric.inc({"verb": method, "reason": reason})

    def _observe_duration(self, method: str, started: float) -> None:
        if self._duration_metric is not None:
            self._duration_metric.observe(time.monotonic() - started,
                                          {"verb": method},
                                          exemplar=tracing.current_exemplar())

    def _health_ok(self) -> None:
        tracker = self._health_tracker
        if tracker is not None:
            tracker.record_success()

    def _health_fail(self) -> None:
        tracker = self._health_tracker
        if tracker is not None:
            tracker.record_failure()

    def set_watch_gap_listener(self, on_gap, on_recover) -> None:
        """Attach per-kind stream-health callbacks: ``on_gap(kind)`` fires
        when a watch stream for the kind drops (events may be missed until
        reconnect), ``on_recover(kind)`` once the reconnected stream's
        RV-diff resync has been delivered (the consumer's cache is
        converged again). The read cache serves the gap window live."""
        self._watch_gap_listeners = (on_gap, on_recover)

    def _notify_watch_gap(self, kind: str, gapped: bool) -> None:
        listeners = self._watch_gap_listeners
        if listeners is None:
            return
        try:
            (listeners[0] if gapped else listeners[1])(kind)
        except Exception:  # noqa: BLE001 — consumer bug must not kill a watch
            log.exception("watch gap listener failed for %s", kind)

    def set_health_tracker(self, tracker) -> None:
        """Attach an apiserver health tracker (record_success/
        record_failure) — the manager's circuit breaker. Watch reconnects
        report through the same seam, so a full outage trips the breaker
        even while the worker pool is idle."""
        self._health_tracker = tracker

    def ping(self, timeout: float = 2.0) -> bool:
        """Transport-liveness probe (GET /readyz): True when the apiserver
        answered at all — ANY http status counts, only a connection-level
        failure is down. The breaker's half-open probe; never retried."""
        try:
            with self._request("GET", "/readyz", timeout=timeout) as resp:
                resp.read()  # a reset manifests at body-read, not connect
                self._mark_drained(resp)
            return True
        except ApiError:
            return True
        except TRANSPORT_ERRORS:
            return False

    def attach_metrics(self, registry) -> None:
        """Bind a metrics registry — the rest_client_* family (client-go
        exposes these through the controller-runtime registry; the
        reference's managers ship them on the same endpoint as the
        notebook series). setup_controllers calls this late, since the
        client is constructed before the registry exists."""
        self._requests_metric = registry.counter(
            "rest_client_requests_total",
            "Number of apiserver HTTP requests, by verb and status code.")
        self._retries_metric = registry.counter(
            "rest_client_retries_total",
            "Number of request retries, by verb and reason "
            "(an HTTP status or 'transport').")
        self._duration_metric = registry.histogram(
            "rest_client_request_duration_seconds",
            "Apiserver request latency per attempt, by verb.")
        self._connections_metric = registry.counter(
            "rest_client_connections_opened_total",
            "TCP connections opened to the apiserver. With the keep-alive "
            "pool this grows with threads and outages, not with requests — "
            "the reuse ratio the loadtest smoke bounds.")
        self._resumes_metric = registry.counter(
            "watch_resumes_total",
            "Watch stream reconnects by kind and mode: resume = replayed "
            "from the server watch cache by resourceVersion (no LIST), "
            "relist = full LIST+diff resync fallback (410 Gone or no "
            "resume cursor).")

    def _count_resume(self, kind: str, mode: str) -> None:
        if self._resumes_metric is not None:
            self._resumes_metric.inc({"kind": kind, "mode": mode})

    def _api_retry_wait(self, err: ApiError, method: str,
                        fallback_delay: float) -> float | None:
        """Seconds to wait before retrying an HTTP error, or None when the
        error is not retryable for this verb (see RetryPolicy)."""
        if err.code == 429:
            return err.retry_after if err.retry_after is not None \
                else fallback_delay
        if err.code == 503 and method in ("GET", "DELETE"):
            return err.retry_after if err.retry_after is not None \
                else fallback_delay
        return None

    def _json(self, method: str, path: str, body: dict | None = None,
              content_type: str = "application/json",
              retry_transport: bool | None = None,
              validate=None) -> dict:
        """One logical request with the RetryPolicy applied — see
        ``_json_impl``. When tracing records, each logical request gets one
        wire span (verb/resource/code/retries, retry attempts as events);
        the untraced path calls ``_json_impl`` directly, bypassing span
        setup entirely."""
        if not tracing.is_recording():
            return self._json_impl(method, path, body, content_type,
                                   retry_transport, validate)
        with _TRACER.start_span(
                f"rest.{method.lower()}",
                {"http.method": method, "http.path": path.split("?", 1)[0],
                 "k8s.resource": _resource_from_path(path)}) as span:
            try:
                out = self._json_impl(method, path, body, content_type,
                                      retry_transport, validate)
                span.set_status(tracing.STATUS_OK)
                return out
            except ApiError as err:
                span.set_attribute("http.status", err.code)
                raise

    def _json_impl(self, method: str, path: str, body: dict | None = None,
                   content_type: str = "application/json",
                   retry_transport: bool | None = None,
                   validate=None) -> dict:
        """One logical request with the RetryPolicy applied. Transport
        retries default to the idempotent verbs; create() opts named POSTs
        in explicitly. Errors surfacing on a retry after an ambiguous
        (transport) failure carry ``ambiguous_retry`` so callers can
        disambiguate (AlreadyExists on create, NotFound on delete).
        ``validate(parsed)`` may raise a TRANSPORT_ERRORS member to flag a
        200 body that is semantically truncated (a LIST without ``items``)
        — it rides the same retry/health path as a reset mid-body."""
        policy = self.retry_policy
        if retry_transport is None:
            retry_transport = method in ("GET", "DELETE")
        ambiguous = False
        delay = policy.backoff_base_s
        attempt = 0
        while True:  # bounded: raises once attempt reaches policy.max_attempts
            attempt += 1
            started = time.monotonic()
            try:
                with self._request(method, path, body, content_type) as resp:
                    resp_ctype = resp.headers.get("Content-Type", "")
                    data = resp.read()
                    self._mark_drained(resp)
                self._observe_duration(method, started)
                # decode by the RESPONSE Content-Type, not the negotiated
                # preference: error Status bodies are always JSON, and a
                # binary-unaware server answering JSON degrades cleanly
                if codec.accepts_binary(resp_ctype):
                    try:
                        parsed = codec.decode(data)
                    except codec.CodecError as exc:
                        # truncated/garbled binary body → retryable
                        # transport failure (PR-2 semantics), same as a
                        # JSONDecodeError on a truncated JSON body
                        raise MalformedBinaryError(str(exc)) from None
                else:
                    parsed = json.loads(data)
                if validate is not None:
                    validate(parsed)
                return parsed
            except ApiError as err:
                self._observe_duration(method, started)
                err.ambiguous_retry = ambiguous
                wait = None
                if attempt < policy.max_attempts:
                    wait = self._api_retry_wait(err, method, delay)
                if wait is None:
                    raise
                if err.code == 503 and method != "GET":
                    # a 503 gives no guarantee processing never started
                    # (an LB can emit it after the apiserver applied the
                    # write) — a DELETE retried through one must treat a
                    # subsequent 404 as its own earlier success
                    ambiguous = True
                reason = str(err.code)
                pending = err
            except (*TRANSPORT_ERRORS, json.JSONDecodeError) as err:
                # JSONDecodeError covers a reset that truncated mid-HEADERS:
                # the client parses what arrived, finds no Content-Length,
                # reads to EOF and hands back an empty/partial body — same
                # wire failure as IncompleteRead, different surface
                self._observe_duration(method, started)
                if not getattr(err, "_kt_health_recorded", False):
                    # a body that truncated AFTER a successful connect
                    # (IncompleteRead/JSONDecodeError) was not seen by
                    # _request
                    self._health_fail()
                if method != "GET":
                    # the request may have been applied server-side
                    ambiguous = True
                if not retry_transport or attempt >= policy.max_attempts:
                    raise
                wait = delay
                reason = "transport"
                pending = err
            # decorrelated jitter (the AWS builders'-library shape): each
            # delay is uniform(base, prev*3) capped — spreads a thundering
            # herd of retriers without a coordinated clock
            delay = min(policy.backoff_cap_s,
                        self._retry_rng.uniform(policy.backoff_base_s,
                                                delay * 3))
            self._count_retry(method, reason)
            span = tracing.current_span()  # noop sink when untraced
            span.add_event("retry", {"attempt": attempt, "reason": reason})
            span.set_attribute("retries", attempt)
            # the cap applies to COMPUTED backoff only — a server-sent
            # Retry-After is pacing we must honor (bounded for sanity)
            if self._stopped.wait(min(wait, 30.0)):
                raise pending  # close() aborts in-flight retry waits

    @staticmethod
    def _path(kind: str, namespace: str | None = None,
              name: str | None = None, subresource: str | None = None,
              query: dict | None = None) -> str:
        mapping = restmapper.mapping_for(kind)
        path = mapping.path(namespace, quote(name) if name else None,
                            subresource)
        if query:
            path += "?" + urlencode(query)
        return path

    # ---------------------------------------------------------------- verbs
    def get(self, kind: str, namespace: str, name: str,
            resource_version: str | None = None) -> dict:
        """``resource_version="0"`` (or a minimum rv) is the rv-gated form
        the apiserver serves lock-free from its watch cache — 'any state
        at least this fresh is acceptable'; omit for a quorum read."""
        query = {"resourceVersion": resource_version} \
            if resource_version is not None else None
        return self._json("GET", self._path(kind, namespace, name,
                                            query=query))

    def get_or_none(self, kind: str, namespace: str, name: str) -> dict | None:
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict[str, str] | None = None) -> list[dict]:
        return self._list(kind, namespace, label_selector)[0]

    def list_cached(self, kind: str, namespace: str | None = None,
                    label_selector: dict[str, str] | None = None,
                    min_resource_version: int | None = None) -> list[dict]:
        """Consistent read from the apiserver's watch cache:
        ``resourceVersion=0`` (or ≥ ``min_resource_version``) LISTs are
        served lock-free from the server-side cache — the form resyncs,
        backfills, and scrapes ride so N managers can re-list
        concurrently without stampeding the store's write-path lock. The
        facade's cache is fed synchronously under the store lock, so
        'cached' here is never stale relative to the store."""
        rv = "0" if min_resource_version is None \
            else str(min_resource_version)
        return self._list(kind, namespace, label_selector,
                          resource_version=rv)[0]

    def _list(self, kind: str, namespace: str | None,
              label_selector: dict[str, str] | None,
              resource_version: str | None = None,
              ) -> tuple[list[dict], int | None]:
        """One logical LIST, paged through ``limit``/``continue`` when
        ``list_page_size`` is set (bounds resync memory + tail latency on
        big fleets). ``resource_version="0"`` is the informer cache-ack
        form the resync path sends. Returns ``(items, list_rv)`` —
        ``list_rv`` is the list metadata's resourceVersion from the FIRST
        page, the reflector's watch-from anchor, or None when the server
        sent none. First page, not last: each page serves live state, so
        a later page's rv covers events (e.g. a deletion of a page-1
        object) whose frames this LIST does not reflect — resuming from
        it would skip them forever. Resuming from the first page's rv
        re-delivers anything that changed between pages instead:
        duplicates are level-safe, skips are not."""
        base_query: dict[str, str] = {}
        if label_selector:
            base_query["labelSelector"] = _serialize_selector(label_selector)
        if resource_version is not None:
            base_query["resourceVersion"] = resource_version
        items: list[dict] = []
        cont: str | None = None
        list_rv: int | None = None
        first_page = True
        while True:  # bounded: returns when continue token absent
            query = dict(base_query)
            if self.list_page_size:
                query["limit"] = str(self.list_page_size)
            if cont:
                query["continue"] = cont
            path = self._path(kind, namespace, query=query or None)
            # a 200 body without an ``items`` array is a WIRE failure
            # (half-written/foreign body — an LB error page), never an
            # empty fleet: _require_items raises MalformedListError
            # (⊂ TRANSPORT_ERRORS) inside _json, so it gets the standard
            # bounded-jitter retry AND counts toward the breaker's
            # consecutive-failure threshold like any truncated response
            body = self._json("GET", path, validate=_require_items)
            items.extend(body["items"])
            meta = body.get("metadata") or {}
            if first_page:
                first_page = False
                try:
                    list_rv = int(meta.get("resourceVersion"))
                except (TypeError, ValueError):
                    list_rv = None
            cont = meta.get("continue")
            if not cont:
                return items, list_rv

    def create(self, obj: dict) -> dict:
        kind = k8s.kind(obj)
        obj.setdefault("apiVersion", restmapper.mapping_for(kind).api_version)
        name = k8s.name(obj)
        try:
            # transport retry only for NAMED creates — a generateName
            # retry could materialize two objects with no way to tell
            return self._json("POST", self._path(kind, k8s.namespace(obj)),
                              obj, retry_transport=bool(name))
        except AlreadyExistsError as err:
            if not err.ambiguous_retry or not name:
                raise
            # an earlier attempt died mid-response (connection reset): the
            # write probably landed and this 409 is our own object. Check
            # against the live resourceVersion: if the object exists,
            # return it as the created state. A racing foreign create is
            # indistinguishable — level-based reconcilers converge on the
            # next loop either way (they re-read and adopt/patch).
            existing = self.get_or_none(kind, k8s.namespace(obj), name)
            if existing is not None:
                log.debug("create %s %s/%s: 409 after ambiguous retry; "
                          "adopting live object rv=%s", kind,
                          k8s.namespace(obj), name,
                          k8s.get_in(existing, "metadata",
                                     "resourceVersion", default="?"))
                return existing
            raise

    def update(self, obj: dict) -> dict:
        kind = k8s.kind(obj)
        obj.setdefault("apiVersion", restmapper.mapping_for(kind).api_version)
        return self._json("PUT", self._path(kind, k8s.namespace(obj),
                                            k8s.name(obj)), obj)

    def update_status(self, obj: dict) -> dict:
        kind = k8s.kind(obj)
        obj.setdefault("apiVersion", restmapper.mapping_for(kind).api_version)
        return self._json("PUT", self._path(kind, k8s.namespace(obj),
                                            k8s.name(obj), "status"), obj)

    def patch(self, kind: str, namespace: str, name: str, patch: dict) -> dict:
        return self._json("PATCH", self._path(kind, namespace, name), patch,
                          content_type="application/merge-patch+json")

    def delete(self, kind: str, namespace: str, name: str) -> None:
        try:
            self._json("DELETE", self._path(kind, namespace, name))
        except NotFoundError as err:
            if err.ambiguous_retry:
                return  # an earlier ambiguous attempt already deleted it
            raise

    def register_admission(self, kind: str, fn) -> None:
        raise RuntimeError(
            "in-process admission is not available over the HTTP client; "
            "serve the webhooks via AdmissionServer + the webhook "
            "configuration manifests (config/webhook), as the reference does")

    # ---------------------------------------------------------------- watch
    def watch(self, kind: str, callback, namespace: str | None = None,
              label_selector: dict[str, str] | None = None) -> None:
        """Blocks until the first stream is connected (up to 5 s) so that,
        as with ClusterStore.watch, no event after watch() returns can be
        missed — CachingClient's watch-then-list backfill depends on this
        ordering to never go stale — AND until the initial LIST+diff resync
        has delivered (informer cache-sync semantics): an object created
        after watch() returns is delivered exactly once, by the live
        stream, never a second time by a still-in-flight initial list.
        If the stream can't connect in time (transient network failure),
        the eventual first connect resyncs creations/updates from that gap
        as ADDED; one narrow hole remains — an object both
        created-and-deleted (or listed by the consumer and deleted)
        entirely within the pre-connect gap leaves no trace for the diff,
        so a consumer that listed during the gap can hold it until its
        next list. Level-based reconcilers tolerate this; it closes the
        moment the object changes again."""
        connected = threading.Event()
        synced = threading.Event()
        thread = threading.Thread(
            target=self._watch_loop,
            args=(kind, callback, namespace, label_selector, connected,
                  synced),
            daemon=True, name=f"kubeflow-tpu-watch-{kind}")
        self._watch_threads.append(thread)
        thread.start()
        deadline = time.monotonic() + 5.0
        if not connected.wait(timeout=5.0):
            log.warning("watch %s not connected after 5s; resync will run "
                        "on first connect", kind)
        elif not synced.wait(timeout=max(deadline - time.monotonic(), 0.1)):
            log.warning("watch %s connected but initial resync still in "
                        "flight after 5s; racing events may deliver twice",
                        kind)

    @staticmethod
    def _obj_key(obj: dict) -> tuple[str, str]:
        return (k8s.namespace(obj), k8s.name(obj))

    @staticmethod
    def _obj_rv(obj: dict) -> str:
        return str(k8s.get_in(obj, "metadata", "resourceVersion", default=""))

    def _watch_loop(self, kind: str, callback, namespace, label_selector,
                    connected: threading.Event,
                    synced: threading.Event | None = None):
        # (namespace, name) → SLIM record of the last object DELIVERED to
        # the callback (rv + the routing fields, see _slim — pinning every
        # full object forever costs O(fleet × object size) per watch
        # thread): the resync diff compares resourceVersions against it,
        # and an outage-time deletion is synthesized as DELETED carrying
        # this skeleton, so owner-mapped and label-filtered watches still
        # route it
        seen: dict[tuple[str, str], dict] = {}
        # shared reconnect state: ``rv`` is the resume cursor (largest
        # resourceVersion DELIVERED on any stream, bookmark-anchored when
        # idle) — None means the next connect must run the LIST+diff
        # resync; ``connected_once`` separates first-connect informer
        # replay from counted relist fallbacks
        state: dict = {"rv": None, "connected_once": False}
        failures = 0
        in_gap = False

        def on_resynced() -> None:
            # stream live again AND converged (RV replay or LIST+diff
            # delivered): end any degraded window, and release a watch()
            # caller still blocked on initial cache sync
            nonlocal in_gap
            if synced is not None:
                synced.set()
            if in_gap:
                in_gap = False
                self._notify_watch_gap(kind, False)

        while not self._stopped.is_set():
            stream_started = time.monotonic()
            failed = True
            try:
                self._watch_stream(kind, callback, namespace, label_selector,
                                   connected, seen, on_resynced, state)
                failed = False  # server closed the stream cleanly
            except GoneError:
                if self._stopped.is_set():
                    return
                # the resume window was evicted server-side (or the rv
                # belongs to another store incarnation): events WERE
                # missed — drop the cursor so the next connect relists,
                # and reconnect promptly (the 410 is an answer, not an
                # outage)
                log.debug("watch %s resume expired (410 Gone); falling "
                          "back to LIST+diff resync", kind)
                state["rv"] = None
                failed = False
            except json.JSONDecodeError as err:
                if self._stopped.is_set():
                    return  # close() aborted the read mid-body: not an error
                # malformed/truncated LIST body during resync (LB error
                # page, apiserver killed mid-write): reconnect — a dead
                # watch thread would mean a permanently stale informer.
                # WARNING, not debug: a persistently malformed server must
                # stay visible, not loop silently
                log.warning("watch %s resync body unparseable (%s); "
                            "reconnecting", kind, err)
            except (*TRANSPORT_ERRORS, ApiError) as err:
                if self._stopped.is_set():
                    return
                # ApiError covers the resync LIST failing with a Status
                # (429/503 burst, a 401 during token rotation) AFTER the
                # retry budget — the daemon watch thread must reconnect
                # with backoff, never die (a dead thread is a permanently
                # stale informer with no error surface). HTTPException
                # covers a body reset mid-resync (IncompleteRead), which
                # is NOT an OSError and previously escaped this loop.
                log.debug("watch %s dropped (%s: %s); reconnecting", kind,
                          type(err).__name__, err)
            # a dropped stream only opens a DEGRADED window when it cannot
            # resume: with a cursor the missed events are retained in the
            # server's watch cache and replay on reconnect — the informer
            # merely lags, exactly as on a busy healthy stream, so cached
            # reads stay authoritative. Without a cursor (first connect
            # still failing, or a 410 just voided it) events may be
            # missed until the LIST+diff resync lands: serve reads live.
            if not self._stopped.is_set() and not in_gap \
                    and state["rv"] is None:
                in_gap = True
                self._notify_watch_gap(kind, True)
            # a stream that served for a while then dropped is the normal
            # reconnect cadence; only back-to-back connect/resync failures
            # escalate the delay (unreachable or persistently erroring
            # apiserver — don't hammer it at 1 Hz per watched kind)
            if failed and \
                    time.monotonic() - stream_started < \
                    WATCH_BACKOFF_RESET_AFTER_S:
                failures += 1
            else:
                failures = 0
            delay = WATCH_RECONNECT_DELAY_S
            if failures > 1:
                delay = min(WATCH_RECONNECT_DELAY_S * 2 ** min(failures, 8),
                            WATCH_BACKOFF_MAX_S)
                delay *= self._retry_rng.uniform(0.5, 1.0)
            self._stopped.wait(delay)

    #: metadata fields a slim ``seen`` record keeps: the resync diff needs
    #: resourceVersion; a synthesized DELETED must still route through
    #: owner mappers (ownerReferences), label mappers/selectors (labels),
    #: and key extraction (name/namespace/uid)
    _SLIM_METADATA_FIELDS = ("name", "namespace", "uid", "resourceVersion",
                             "labels", "ownerReferences")

    @classmethod
    def _slim(cls, obj: dict) -> dict:
        """Skeleton of a delivered object for the ``seen`` map — rv plus
        only what DELETED synthesis routing needs. Pinning full objects
        pinned O(fleet × object size) per watch thread forever."""
        md = obj.get("metadata") or {}
        return {"kind": obj.get("kind"), "apiVersion": obj.get("apiVersion"),
                "metadata": {k: md[k] for k in cls._SLIM_METADATA_FIELDS
                             if k in md}}

    def _deliver(self, callback, event: WatchEvent, seen: dict) -> bool:
        """Invoke the callback, then record delivery (returns whether it
        was recorded — the watch stream advances its resume cursor only
        past delivered events). A raising callback is logged and NOT
        recorded, so the next resync/replay re-delivers the event instead
        of silently losing it."""
        try:
            callback(event)
        except Exception:  # noqa: BLE001 — consumer bug must not kill the watch
            log.exception("watch callback failed for %s %s",
                          k8s.kind(event.obj), event.type)
            return False
        key = self._obj_key(event.obj)
        if event.type == "DELETED":
            seen.pop(key, None)
        else:
            seen[key] = self._slim(event.obj)
        return True

    def _resync(self, kind, callback, namespace, label_selector,
                seen: dict) -> int | None:
        """After a dropped stream: list and diff against what was delivered.
        Changed objects → MODIFIED, unseen → ADDED, vanished → DELETED with
        the last-delivered skeleton as the final state (a deletion during
        the outage would otherwise never surface and leave ghost objects in
        informer caches). Returns the LIST's resourceVersion — the
        reflector's watch-from anchor: the stream is complete through it
        the moment the diff is delivered — or None when any delivery
        failed (anchoring would let resumes skip the failed event forever;
        a cursorless next reconnect relists and re-delivers it)."""
        current: dict[tuple[str, str], dict] = {}
        # rv=0: the informer list-then-watch form — any stored state is
        # acceptable (the RV-diff below reconciles staleness); pages when
        # list_page_size is set, so a post-outage resync of a big fleet
        # never materializes one giant body
        items, list_rv = self._list(kind, namespace, label_selector,
                                    resource_version="0")
        for obj in items:
            current[self._obj_key(obj)] = obj
        complete = True
        for key, obj in current.items():
            if key not in seen:
                complete &= self._deliver(callback, WatchEvent("ADDED", obj),
                                          seen)
            elif self._obj_rv(seen[key]) != self._obj_rv(obj):
                complete &= self._deliver(callback,
                                          WatchEvent("MODIFIED", obj), seen)
        for key in [key for key in seen if key not in current]:
            final_state = seen[key]
            complete &= self._deliver(callback,
                                      WatchEvent("DELETED", final_state),
                                      seen)
        return list_rv if complete else None

    def _watch_stream(self, kind: str, callback, namespace, label_selector,
                      connected: threading.Event, seen: dict,
                      on_resynced=None, state: dict | None = None):
        state = state if state is not None \
            else {"rv": None, "connected_once": False}
        resume_rv = state.get("rv")
        query = {"watch": "true",
                 "timeoutSeconds": str(WATCH_SERVER_TIMEOUT_S)}
        if resume_rv is not None:
            query["resourceVersion"] = str(resume_rv)
        if label_selector:
            query["labelSelector"] = _serialize_selector(label_selector)
        path = self._path(kind, namespace, query=query)

        def advance(rv_raw) -> None:
            try:
                rv = int(rv_raw)
            except (TypeError, ValueError):
                return
            # rv 0 is a VALID anchor (a from-birth stream on an empty
            # store is complete through 0) — only None means "no cursor,
            # must relist"
            if state["rv"] is None or rv > state["rv"]:
                state["rv"] = rv

        # dedicated (non-pooled) connection: the stream holds it for its
        # whole lifetime and it is never reusable afterwards
        resp = self._request("GET", path, timeout=WATCH_READ_TIMEOUT_S,
                             pooled=False)
        try:
            with self._streams_lock:
                self._live_streams.add(resp)
            try:
                connected.set()  # server has registered the watch relay
                if resume_rv is not None:
                    # RV-resumable reconnect: the server is replaying the
                    # retained window after resume_rv on THIS stream — no
                    # LIST, no missable gap, the consumer cache just
                    # catches up through the replayed frames below
                    self._count_resume(kind, "resume")
                    if on_resynced is not None:
                        on_resynced()
                else:
                    # resync AFTER the stream is live (no missable gap): on
                    # the first connect this is informer semantics —
                    # initial list → ADDED for existing objects, as
                    # controller-runtime delivers at boot — and after a 410
                    # (or a drop that never delivered) it is the diff that
                    # surfaces missed changes and deletions. Events racing
                    # the resync may deliver twice (level-based consumers
                    # tolerate that); with unchanged RVs the diff delivers
                    # nothing.
                    if state["connected_once"]:
                        self._count_resume(kind, "relist")
                    list_rv = self._resync(kind, callback, namespace,
                                           label_selector, seen)
                    # anchor the resume cursor at the LIST's rv NOW: a
                    # stream dropped before the first bookmark is read
                    # must still reconnect in resume mode (the reflector's
                    # list-then-watch-from-rv contract)
                    if list_rv is not None:
                        advance(list_rv)
                    if on_resynced is not None:
                        on_resynced()
                state["connected_once"] = True
                # stream framing follows the RESPONSE Content-Type:
                # length-prefixed codec frames when the server honored a
                # binary Accept, NDJSON otherwise (a binary-unaware or
                # older server degrades the stream to JSON transparently)
                binary_stream = codec.accepts_binary(
                    resp.headers.get("Content-Type"))
                while not self._stopped.is_set():
                    try:
                        if binary_stream:
                            head = _read_exact(resp, 4)
                            if len(head) < 4:
                                return  # server closed the stream
                            (total,) = struct.unpack(">I", head)
                            payload = _read_exact(resp, total)
                            if len(payload) < total:
                                return  # truncated frame: reconnect
                        else:
                            line = resp.readline()
                            if not line:
                                return  # server closed the stream
                    except ValueError:
                        # close()'s fallback path closed the file under us
                        # ("I/O operation on closed file") — shutdown race,
                        # scoped here so resync JSON errors stay loud
                        return
                    try:
                        if binary_stream:
                            event_type, obj = codec.parse_event(payload)
                        else:
                            frame = json.loads(line)
                            event_type = frame["type"]
                            obj = frame["object"]
                    except (ValueError, KeyError, TypeError):
                        # truncated/garbled frame (apiserver killed
                        # mid-write; CodecError ⊂ ValueError): reconnect;
                        # the replay/resync re-covers whatever it carried
                        return
                    if event_type == "BOOKMARK":
                        # idle-stream resume anchor: the server guarantees
                        # this stream is complete through the bookmark rv
                        advance(k8s.get_in(obj, "metadata",
                                           "resourceVersion"))
                        continue
                    if self._deliver(callback, WatchEvent(event_type, obj),
                                     seen):
                        advance(k8s.get_in(obj, "metadata",
                                           "resourceVersion"))
                    else:
                        # failed delivery: the stream is NOT complete past
                        # this event, and a later event or bookmark must
                        # not advance the cursor over it — drop the
                        # stream; the reconnect resumes from the last
                        # DELIVERED rv and replays this event (the
                        # re-delivery _deliver's contract promises)
                        return
            finally:
                with self._streams_lock:
                    self._live_streams.discard(resp)
        finally:
            try:
                resp.close()
            except OSError:
                pass
            conn = getattr(resp, "_kt_conn", None)
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass

    def close(self) -> None:
        """Stop watch threads NOW: set the stop flag and shut down the live
        watch sockets. A blocked recv() wakes on socket shutdown (returns
        0 bytes → readline sees EOF); calling resp.close() instead would
        contend on the BufferedReader lock the reading thread holds and
        block until the read timeout."""
        self._stopped.set()
        # reap the keep-alive pool: worker threads' persistent connections
        # are idle at shutdown (their requests are done) or their in-flight
        # retry waits just aborted via _stopped — closing from here is the
        # only way to reach them across threads
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        with self._streams_lock:
            streams = list(self._live_streams)
        for resp in streams:
            try:
                sock = resp.fp.raw._sock  # noqa: SLF001 — http.client layout
                sock.shutdown(socket.SHUT_RDWR)
            except (OSError, ValueError):
                pass  # already closed: nothing left to unblock
            except AttributeError:
                # different response internals: fall back to close() —
                # may block until the read timeout, but never hangs forever
                try:
                    resp.close()
                except OSError:
                    pass
