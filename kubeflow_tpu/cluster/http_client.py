"""HTTP(S) Kubernetes API client — the real-cluster transport.

Implements the same client protocol the controllers already consume from
``ClusterStore`` (get/get_or_none/list/create/update/update_status/patch/
delete/watch) over the Kubernetes REST wire protocol, so the reconcilers run
unmodified against a real apiserver — the role client-go plays for the
reference's managers (controllers speak HTTPS to kube-apiserver,
notebook-controller/main.go:95-148; odh main.go:236-275).

Auth, mirroring client-go's loading order:

- ``HttpApiClient.from_kubeconfig(path)`` — kubeconfig contexts: bearer
  token, client certificates (inline ``*-data`` or file paths), cluster CA;
- ``HttpApiClient.in_cluster()`` — the ServiceAccount mount
  (/var/run/secrets/kubernetes.io/serviceaccount) + KUBERNETES_SERVICE_HOST,
  exactly what the deploy manifests give the manager pod;
- plain constructor for tests / token-only setups.

Watches are reconnecting daemon threads reading the newline-delimited JSON
stream (``?watch=true``). After a drop the client re-lists and diffs against
the per-key resourceVersions it has delivered: changed/new objects re-deliver
as MODIFIED/ADDED and objects that vanished during the outage synthesize
DELETED — so informer caches can neither go stale nor keep ghosts across
apiserver restarts, and a quiet cluster costs one cheap list per reconnect,
not a full re-delivery.

In-process admission registration is NOT available here: against a real
apiserver, admission runs via webhook configurations served by the manager's
AdmissionServer (config/webhook), exactly as in the reference.
"""

from __future__ import annotations

import base64
import http.client
import json
import logging
import os
import random
import socket
import ssl
import tempfile
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass
from urllib.parse import quote, urlencode

from ..utils import k8s
from . import restmapper
from .errors import (AlreadyExistsError, ApiError, ConflictError,
                     ForbiddenError, InvalidError, NotFoundError,
                     ServiceUnavailableError, TooManyRequestsError)
from .store import WatchEvent

log = logging.getLogger("kubeflow_tpu.http_client")

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

_ERROR_BY_REASON = {
    "NotFound": NotFoundError,
    "AlreadyExists": AlreadyExistsError,
    "Conflict": ConflictError,
    "Invalid": InvalidError,
    "Forbidden": ForbiddenError,
    "TooManyRequests": TooManyRequestsError,
    "ServiceUnavailable": ServiceUnavailableError,
}
_ERROR_BY_CODE = {404: NotFoundError, 409: ConflictError, 422: InvalidError,
                  403: ForbiddenError, 429: TooManyRequestsError,
                  503: ServiceUnavailableError}

#: failures that mean "the bytes didn't arrive", not "the server said no":
#: connection refused/reset (URLError/OSError) and a response that
#: truncated mid-wire (IncompleteRead/BadStatusLine are HTTPExceptions,
#: NOT OSErrors — a reset-mid-body previously escaped every handler here)
TRANSPORT_ERRORS = (urllib.error.URLError, OSError, http.client.HTTPException)


class MalformedListError(http.client.HTTPException):
    """A LIST response parsed as JSON but carries no ``items`` array — a
    truncated/foreign body (LB error page, apiserver killed mid-write)
    that must surface as a retryable transport failure. Reading it as an
    empty list would be catastrophic during a watch resync: the RV-diff
    would synthesize DELETED for every live object."""


@dataclass(frozen=True)
class RetryPolicy:
    """client-go-style bounded retries with decorrelated-jitter backoff.

    What retries (the policy table, also in ARCHITECTURE.md):

    - ``429`` — every verb: the server rejected the request before
      processing (priority-and-fairness), so retry is always safe;
      ``Retry-After`` overrides the computed backoff when sent.
    - ``503`` — idempotent verbs only (GET/LIST/DELETE).
    - transport errors (refused/reset/truncated) — idempotent verbs, plus
      *named* creates: a reset POST may or may not have applied, and the
      retry disambiguates via 409 AlreadyExists + a live read. generateName
      creates never retry on transport errors (a blind retry could
      materialize two objects).
    - PUT/PATCH — 429 only: resourceVersion preconditions + the
      reconcilers' conflict-retry loops own that ambiguity.
    """

    max_attempts: int = 4
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0

# Watch streams ask the server to close gracefully after this long
# (?timeoutSeconds=, honored by real apiservers); the socket read timeout
# sits just above it so a dead stream is still detected. Our facade sends
# 10s bookmarks, a real apiserver sends nothing on an idle watch — either
# way a reconnect costs one list that delivers nothing when RVs are
# unchanged, so the cadence is cheap.
WATCH_SERVER_TIMEOUT_S = 290
WATCH_READ_TIMEOUT_S = WATCH_SERVER_TIMEOUT_S + 10.0
WATCH_RECONNECT_DELAY_S = 1.0
# consecutive watch reconnect failures back off exponentially from
# WATCH_RECONNECT_DELAY_S up to this cap (an unreachable apiserver must
# not be hammered at 1 Hz per watched kind); a stream that lived this
# long before dropping resets the backoff
WATCH_BACKOFF_MAX_S = 30.0
WATCH_BACKOFF_RESET_AFTER_S = 5.0


def _require_items(parsed: dict) -> None:
    """LIST-body validator for _json: no ``items`` array → transport
    failure (see MalformedListError)."""
    if not isinstance(parsed, dict) or \
            not isinstance(parsed.get("items"), list):
        raise MalformedListError("LIST body has no items array")


def _serialize_selector(selector: dict) -> str:
    """k8s labelSelector grammar subset: ``key=value`` equality terms plus
    bare ``key`` existence terms (value ``None``)."""
    return ",".join(key if val is None else f"{key}={val}"
                    for key, val in selector.items())


def _error_from_response(code: int, body: bytes,
                         headers=None) -> ApiError:
    reason, message = "", ""
    try:
        status = json.loads(body)
        reason = status.get("reason", "")
        message = status.get("message", "")
    except (ValueError, AttributeError):
        message = body.decode(errors="replace")[:200]
    cls = _ERROR_BY_REASON.get(reason) or _ERROR_BY_CODE.get(code) or ApiError
    err = cls(message or f"HTTP {code}")
    err.code = code  # preserve the wire status (e.g. 401) on generic errors
    if headers is not None:
        err.retry_after = _parse_retry_after(headers.get("Retry-After"))
    return err


def _parse_retry_after(raw: str | None) -> float | None:
    """Delay-seconds form only (integer per RFC 7231; our facade also sends
    sub-second floats). The HTTP-date form is ignored — client-go does the
    same for apiserver flow-control."""
    if not raw:
        return None
    try:
        return max(float(raw), 0.0)
    except ValueError:
        return None


def _data_or_file(data_b64: str | None, path: str | None) -> str | None:
    """Resolve kubeconfig's inline-base64-or-file-path pattern to a path."""
    if data_b64:
        tmp = tempfile.NamedTemporaryFile("wb", delete=False,
                                          prefix="kubeflow-tpu-kc-")
        tmp.write(base64.b64decode(data_b64))
        tmp.close()
        return tmp.name
    return path


class HttpApiClient:
    """Client protocol implementation over HTTP(S)."""

    supports_inprocess_admission = False

    def __init__(self, base_url: str, token: str | None = None,
                 ca_cert: str | None = None, client_cert: str | None = None,
                 client_key: str | None = None, verify: bool = True,
                 timeout: float = 30.0, metrics=None,
                 retry_policy: RetryPolicy | None = None,
                 list_page_size: int | None = None) -> None:
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        self.retry_policy = retry_policy or RetryPolicy()
        # LIST chunking (?limit=N&continue=…): bounds the memory and tail
        # latency of a fleet-sized LIST — the backfills and post-outage
        # resyncs page through instead of one giant body. None = unpaged.
        self.list_page_size = list_page_size
        self._retry_rng = random.Random()  # decorrelated jitter source
        self._requests_metric = None
        self._retries_metric = None
        self._duration_metric = None
        # optional apiserver health tracker (the manager's circuit
        # breaker): told about every transport-level success/failure —
        # an HTTP error response counts as SUCCESS (the server answered)
        self._health_tracker = None
        if metrics is not None:
            self.attach_metrics(metrics)
        self._ssl: ssl.SSLContext | None = None
        if self.base_url.startswith("https"):
            ctx = ssl.create_default_context(cafile=ca_cert)
            if not verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            if client_cert:
                ctx.load_cert_chain(client_cert, client_key)
            self._ssl = ctx
        self._stopped = threading.Event()
        # optional watch stream-health listener pair (on_gap, on_recover):
        # the read cache's degraded-mode hooks (CachingClient.mark_watch_gap)
        # — while any stream for a kind is down, cached reads of it go live
        self._watch_gap_listeners: tuple | None = None
        self._watch_threads: list[threading.Thread] = []
        # live watch responses, so close() can unblock readline() NOW
        # instead of waiting out the server's bookmark interval
        self._live_streams: set = set()
        self._streams_lock = threading.Lock()

    # ------------------------------------------------------------ factories
    @classmethod
    def from_kubeconfig(cls, path: str | None = None,
                        context: str | None = None) -> "HttpApiClient":
        import yaml
        path = path or os.environ.get("KUBECONFIG") or \
            os.path.expanduser("~/.kube/config")
        with open(path) as fh:
            cfg = yaml.safe_load(fh)
        ctx_name = context or cfg.get("current-context")
        ctx = next(c["context"] for c in cfg.get("contexts", [])
                   if c["name"] == ctx_name)
        cluster = next(c["cluster"] for c in cfg.get("clusters", [])
                       if c["name"] == ctx["cluster"])
        user = next((u["user"] for u in cfg.get("users", [])
                     if u["name"] == ctx.get("user")), {})
        return cls(
            cluster["server"],
            token=user.get("token"),
            ca_cert=_data_or_file(cluster.get("certificate-authority-data"),
                                  cluster.get("certificate-authority")),
            client_cert=_data_or_file(user.get("client-certificate-data"),
                                      user.get("client-certificate")),
            client_key=_data_or_file(user.get("client-key-data"),
                                     user.get("client-key")),
            verify=not cluster.get("insecure-skip-tls-verify", False),
        )

    @classmethod
    def in_cluster(cls) -> "HttpApiClient":
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(f"{SA_DIR}/token") as fh:
            token = fh.read().strip()
        ca = f"{SA_DIR}/ca.crt"
        return cls(f"https://{host}:{port}", token=token,
                   ca_cert=ca if os.path.exists(ca) else None)

    # ------------------------------------------------------------ transport
    def _request(self, method: str, path: str, body: dict | None = None,
                 content_type: str = "application/json",
                 timeout: float | None = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self.base_url + path, data=data,
                                     method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            resp = urllib.request.urlopen(
                req, timeout=timeout or self.timeout, context=self._ssl)
            self._count_request(method, resp.status)
            self._health_ok()
            return resp
        except urllib.error.HTTPError as err:
            self._count_request(method, err.code)
            self._health_ok()  # an error RESPONSE still means "reachable"
            raise _error_from_response(err.code, err.read(),
                                       err.headers) from None
        except (urllib.error.URLError, OSError) as err:
            self._count_request(method, "<error>")
            self._health_fail()
            err._kt_health_recorded = True  # _json must not double-count
            raise

    def _count_request(self, method: str, code) -> None:
        if self._requests_metric is not None:
            self._requests_metric.inc({"method": method, "code": str(code)})

    def _count_retry(self, method: str, reason: str) -> None:
        if self._retries_metric is not None:
            self._retries_metric.inc({"verb": method, "reason": reason})

    def _observe_duration(self, method: str, started: float) -> None:
        if self._duration_metric is not None:
            self._duration_metric.observe(time.monotonic() - started,
                                          {"verb": method})

    def _health_ok(self) -> None:
        tracker = self._health_tracker
        if tracker is not None:
            tracker.record_success()

    def _health_fail(self) -> None:
        tracker = self._health_tracker
        if tracker is not None:
            tracker.record_failure()

    def set_watch_gap_listener(self, on_gap, on_recover) -> None:
        """Attach per-kind stream-health callbacks: ``on_gap(kind)`` fires
        when a watch stream for the kind drops (events may be missed until
        reconnect), ``on_recover(kind)`` once the reconnected stream's
        RV-diff resync has been delivered (the consumer's cache is
        converged again). The read cache serves the gap window live."""
        self._watch_gap_listeners = (on_gap, on_recover)

    def _notify_watch_gap(self, kind: str, gapped: bool) -> None:
        listeners = self._watch_gap_listeners
        if listeners is None:
            return
        try:
            (listeners[0] if gapped else listeners[1])(kind)
        except Exception:  # noqa: BLE001 — consumer bug must not kill a watch
            log.exception("watch gap listener failed for %s", kind)

    def set_health_tracker(self, tracker) -> None:
        """Attach an apiserver health tracker (record_success/
        record_failure) — the manager's circuit breaker. Watch reconnects
        report through the same seam, so a full outage trips the breaker
        even while the worker pool is idle."""
        self._health_tracker = tracker

    def ping(self, timeout: float = 2.0) -> bool:
        """Transport-liveness probe (GET /readyz): True when the apiserver
        answered at all — ANY http status counts, only a connection-level
        failure is down. The breaker's half-open probe; never retried."""
        try:
            with self._request("GET", "/readyz", timeout=timeout) as resp:
                resp.read()  # a reset manifests at body-read, not connect
            return True
        except ApiError:
            return True
        except TRANSPORT_ERRORS:
            return False

    def attach_metrics(self, registry) -> None:
        """Bind a metrics registry — the rest_client_* family (client-go
        exposes these through the controller-runtime registry; the
        reference's managers ship them on the same endpoint as the
        notebook series). setup_controllers calls this late, since the
        client is constructed before the registry exists."""
        self._requests_metric = registry.counter(
            "rest_client_requests_total",
            "Number of apiserver HTTP requests, by verb and status code.")
        self._retries_metric = registry.counter(
            "rest_client_retries_total",
            "Number of request retries, by verb and reason "
            "(an HTTP status or 'transport').")
        self._duration_metric = registry.histogram(
            "rest_client_request_duration_seconds",
            "Apiserver request latency per attempt, by verb.")

    def _api_retry_wait(self, err: ApiError, method: str,
                        fallback_delay: float) -> float | None:
        """Seconds to wait before retrying an HTTP error, or None when the
        error is not retryable for this verb (see RetryPolicy)."""
        if err.code == 429:
            return err.retry_after if err.retry_after is not None \
                else fallback_delay
        if err.code == 503 and method in ("GET", "DELETE"):
            return err.retry_after if err.retry_after is not None \
                else fallback_delay
        return None

    def _json(self, method: str, path: str, body: dict | None = None,
              content_type: str = "application/json",
              retry_transport: bool | None = None,
              validate=None) -> dict:
        """One logical request with the RetryPolicy applied. Transport
        retries default to the idempotent verbs; create() opts named POSTs
        in explicitly. Errors surfacing on a retry after an ambiguous
        (transport) failure carry ``ambiguous_retry`` so callers can
        disambiguate (AlreadyExists on create, NotFound on delete).
        ``validate(parsed)`` may raise a TRANSPORT_ERRORS member to flag a
        200 body that is semantically truncated (a LIST without ``items``)
        — it rides the same retry/health path as a reset mid-body."""
        policy = self.retry_policy
        if retry_transport is None:
            retry_transport = method in ("GET", "DELETE")
        ambiguous = False
        delay = policy.backoff_base_s
        attempt = 0
        while True:
            attempt += 1
            started = time.monotonic()
            try:
                with self._request(method, path, body, content_type) as resp:
                    data = resp.read()
                self._observe_duration(method, started)
                parsed = json.loads(data)
                if validate is not None:
                    validate(parsed)
                return parsed
            except ApiError as err:
                self._observe_duration(method, started)
                err.ambiguous_retry = ambiguous
                wait = None
                if attempt < policy.max_attempts:
                    wait = self._api_retry_wait(err, method, delay)
                if wait is None:
                    raise
                if err.code == 503 and method != "GET":
                    # a 503 gives no guarantee processing never started
                    # (an LB can emit it after the apiserver applied the
                    # write) — a DELETE retried through one must treat a
                    # subsequent 404 as its own earlier success
                    ambiguous = True
                reason = str(err.code)
                pending = err
            except (*TRANSPORT_ERRORS, json.JSONDecodeError) as err:
                # JSONDecodeError covers a reset that truncated mid-HEADERS:
                # the client parses what arrived, finds no Content-Length,
                # reads to EOF and hands back an empty/partial body — same
                # wire failure as IncompleteRead, different surface
                self._observe_duration(method, started)
                if not getattr(err, "_kt_health_recorded", False):
                    # a body that truncated AFTER a successful connect
                    # (IncompleteRead/JSONDecodeError) was not seen by
                    # _request
                    self._health_fail()
                if method != "GET":
                    # the request may have been applied server-side
                    ambiguous = True
                if not retry_transport or attempt >= policy.max_attempts:
                    raise
                wait = delay
                reason = "transport"
                pending = err
            # decorrelated jitter (the AWS builders'-library shape): each
            # delay is uniform(base, prev*3) capped — spreads a thundering
            # herd of retriers without a coordinated clock
            delay = min(policy.backoff_cap_s,
                        self._retry_rng.uniform(policy.backoff_base_s,
                                                delay * 3))
            self._count_retry(method, reason)
            # the cap applies to COMPUTED backoff only — a server-sent
            # Retry-After is pacing we must honor (bounded for sanity)
            if self._stopped.wait(min(wait, 30.0)):
                raise pending  # close() aborts in-flight retry waits

    @staticmethod
    def _path(kind: str, namespace: str | None = None,
              name: str | None = None, subresource: str | None = None,
              query: dict | None = None) -> str:
        mapping = restmapper.mapping_for(kind)
        path = mapping.path(namespace, quote(name) if name else None,
                            subresource)
        if query:
            path += "?" + urlencode(query)
        return path

    # ---------------------------------------------------------------- verbs
    def get(self, kind: str, namespace: str, name: str) -> dict:
        return self._json("GET", self._path(kind, namespace, name))

    def get_or_none(self, kind: str, namespace: str, name: str) -> dict | None:
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict[str, str] | None = None) -> list[dict]:
        return self._list(kind, namespace, label_selector)

    def _list(self, kind: str, namespace: str | None,
              label_selector: dict[str, str] | None,
              resource_version: str | None = None) -> list[dict]:
        """One logical LIST, paged through ``limit``/``continue`` when
        ``list_page_size`` is set (bounds resync memory + tail latency on
        big fleets). ``resource_version="0"`` is the informer cache-ack
        form the resync path sends."""
        base_query: dict[str, str] = {}
        if label_selector:
            base_query["labelSelector"] = _serialize_selector(label_selector)
        if resource_version is not None:
            base_query["resourceVersion"] = resource_version
        items: list[dict] = []
        cont: str | None = None
        while True:
            query = dict(base_query)
            if self.list_page_size:
                query["limit"] = str(self.list_page_size)
            if cont:
                query["continue"] = cont
            path = self._path(kind, namespace, query=query or None)
            # a 200 body without an ``items`` array is a WIRE failure
            # (half-written/foreign body — an LB error page), never an
            # empty fleet: _require_items raises MalformedListError
            # (⊂ TRANSPORT_ERRORS) inside _json, so it gets the standard
            # bounded-jitter retry AND counts toward the breaker's
            # consecutive-failure threshold like any truncated response
            body = self._json("GET", path, validate=_require_items)
            items.extend(body["items"])
            cont = (body.get("metadata") or {}).get("continue")
            if not cont:
                return items

    def create(self, obj: dict) -> dict:
        kind = k8s.kind(obj)
        obj.setdefault("apiVersion", restmapper.mapping_for(kind).api_version)
        name = k8s.name(obj)
        try:
            # transport retry only for NAMED creates — a generateName
            # retry could materialize two objects with no way to tell
            return self._json("POST", self._path(kind, k8s.namespace(obj)),
                              obj, retry_transport=bool(name))
        except AlreadyExistsError as err:
            if not err.ambiguous_retry or not name:
                raise
            # an earlier attempt died mid-response (connection reset): the
            # write probably landed and this 409 is our own object. Check
            # against the live resourceVersion: if the object exists,
            # return it as the created state. A racing foreign create is
            # indistinguishable — level-based reconcilers converge on the
            # next loop either way (they re-read and adopt/patch).
            existing = self.get_or_none(kind, k8s.namespace(obj), name)
            if existing is not None:
                log.debug("create %s %s/%s: 409 after ambiguous retry; "
                          "adopting live object rv=%s", kind,
                          k8s.namespace(obj), name,
                          k8s.get_in(existing, "metadata",
                                     "resourceVersion", default="?"))
                return existing
            raise

    def update(self, obj: dict) -> dict:
        kind = k8s.kind(obj)
        obj.setdefault("apiVersion", restmapper.mapping_for(kind).api_version)
        return self._json("PUT", self._path(kind, k8s.namespace(obj),
                                            k8s.name(obj)), obj)

    def update_status(self, obj: dict) -> dict:
        kind = k8s.kind(obj)
        obj.setdefault("apiVersion", restmapper.mapping_for(kind).api_version)
        return self._json("PUT", self._path(kind, k8s.namespace(obj),
                                            k8s.name(obj), "status"), obj)

    def patch(self, kind: str, namespace: str, name: str, patch: dict) -> dict:
        return self._json("PATCH", self._path(kind, namespace, name), patch,
                          content_type="application/merge-patch+json")

    def delete(self, kind: str, namespace: str, name: str) -> None:
        try:
            self._json("DELETE", self._path(kind, namespace, name))
        except NotFoundError as err:
            if err.ambiguous_retry:
                return  # an earlier ambiguous attempt already deleted it
            raise

    def register_admission(self, kind: str, fn) -> None:
        raise RuntimeError(
            "in-process admission is not available over the HTTP client; "
            "serve the webhooks via AdmissionServer + the webhook "
            "configuration manifests (config/webhook), as the reference does")

    # ---------------------------------------------------------------- watch
    def watch(self, kind: str, callback, namespace: str | None = None,
              label_selector: dict[str, str] | None = None) -> None:
        """Blocks until the first stream is connected (up to 5 s) so that,
        as with ClusterStore.watch, no event after watch() returns can be
        missed — CachingClient's watch-then-list backfill depends on this
        ordering to never go stale. If the stream can't connect in time
        (transient network failure), the eventual first connect resyncs
        creations/updates from that gap as ADDED; one narrow hole remains —
        an object both created-and-deleted (or listed by the consumer and
        deleted) entirely within the pre-connect gap leaves no trace for the
        diff, so a consumer that listed during the gap can hold it until its
        next list. Level-based reconcilers tolerate this; it closes the
        moment the object changes again."""
        connected = threading.Event()
        thread = threading.Thread(
            target=self._watch_loop,
            args=(kind, callback, namespace, label_selector, connected),
            daemon=True, name=f"kubeflow-tpu-watch-{kind}")
        self._watch_threads.append(thread)
        thread.start()
        if not connected.wait(timeout=5.0):
            log.warning("watch %s not connected after 5s; resync will run "
                        "on first connect", kind)

    @staticmethod
    def _obj_key(obj: dict) -> tuple[str, str]:
        return (k8s.namespace(obj), k8s.name(obj))

    @staticmethod
    def _obj_rv(obj: dict) -> str:
        return str(k8s.get_in(obj, "metadata", "resourceVersion", default=""))

    def _watch_loop(self, kind: str, callback, namespace, label_selector,
                    connected: threading.Event):
        # (namespace, name) → last object DELIVERED to the callback (the
        # informer's deleted-final-state store): the resync diff compares
        # resourceVersions against it, and an outage-time deletion is
        # synthesized as DELETED carrying this full final object, so
        # owner-mapped and label-filtered watches still route it
        seen: dict[tuple[str, str], dict] = {}
        failures = 0
        in_gap = False

        def on_resynced() -> None:
            # stream live again AND the RV-diff delivered: consumers'
            # caches are converged — end the degraded window
            nonlocal in_gap
            if in_gap:
                in_gap = False
                self._notify_watch_gap(kind, False)

        while not self._stopped.is_set():
            stream_started = time.monotonic()
            failed = True
            try:
                self._watch_stream(kind, callback, namespace, label_selector,
                                   connected, seen, on_resynced)
                failed = False  # server closed the stream cleanly
            except json.JSONDecodeError as err:
                if self._stopped.is_set():
                    return  # close() aborted the read mid-body: not an error
                # malformed/truncated LIST body during resync (LB error
                # page, apiserver killed mid-write): reconnect — a dead
                # watch thread would mean a permanently stale informer.
                # WARNING, not debug: a persistently malformed server must
                # stay visible, not loop silently
                log.warning("watch %s resync body unparseable (%s); "
                            "reconnecting", kind, err)
            except (*TRANSPORT_ERRORS, ApiError) as err:
                if self._stopped.is_set():
                    return
                # ApiError covers the resync LIST failing with a Status
                # (429/503 burst, a 401 during token rotation) AFTER the
                # retry budget — the daemon watch thread must reconnect
                # with backoff, never die (a dead thread is a permanently
                # stale informer with no error surface). HTTPException
                # covers a body reset mid-resync (IncompleteRead), which
                # is NOT an OSError and previously escaped this loop.
                log.debug("watch %s dropped (%s: %s); reconnecting", kind,
                          type(err).__name__, err)
            # a dropped stream (clean rotation or failure) leaves a gap —
            # events until the next resync may be missed; flag it once per
            # outage so index-served reads fall back live for the window
            if not self._stopped.is_set() and not in_gap:
                in_gap = True
                self._notify_watch_gap(kind, True)
            # a stream that served for a while then dropped is the normal
            # reconnect cadence; only back-to-back connect/resync failures
            # escalate the delay (unreachable or persistently erroring
            # apiserver — don't hammer it at 1 Hz per watched kind)
            if failed and \
                    time.monotonic() - stream_started < \
                    WATCH_BACKOFF_RESET_AFTER_S:
                failures += 1
            else:
                failures = 0
            delay = WATCH_RECONNECT_DELAY_S
            if failures > 1:
                delay = min(WATCH_RECONNECT_DELAY_S * 2 ** min(failures, 8),
                            WATCH_BACKOFF_MAX_S)
                delay *= self._retry_rng.uniform(0.5, 1.0)
            self._stopped.wait(delay)

    def _deliver(self, callback, event: WatchEvent, seen: dict) -> None:
        """Invoke the callback, then record delivery. A raising callback is
        logged and NOT recorded, so the next resync re-delivers the event
        instead of silently losing it."""
        try:
            callback(event)
        except Exception:  # noqa: BLE001 — consumer bug must not kill the watch
            log.exception("watch callback failed for %s %s",
                          k8s.kind(event.obj), event.type)
            return
        key = self._obj_key(event.obj)
        if event.type == "DELETED":
            seen.pop(key, None)
        else:
            seen[key] = event.obj

    def _resync(self, kind, callback, namespace, label_selector,
                seen: dict) -> None:
        """After a dropped stream: list and diff against what was delivered.
        Changed objects → MODIFIED, unseen → ADDED, vanished → DELETED with
        the last-delivered object as the final state (a deletion during the
        outage would otherwise never surface and leave ghost objects in
        informer caches)."""
        current: dict[tuple[str, str], dict] = {}
        # rv=0: the informer list-then-watch form — any stored state is
        # acceptable (the RV-diff below reconciles staleness); pages when
        # list_page_size is set, so a post-outage resync of a big fleet
        # never materializes one giant body
        for obj in self._list(kind, namespace, label_selector,
                              resource_version="0"):
            current[self._obj_key(obj)] = obj
        for key, obj in current.items():
            if key not in seen:
                self._deliver(callback, WatchEvent("ADDED", obj), seen)
            elif self._obj_rv(seen[key]) != self._obj_rv(obj):
                self._deliver(callback, WatchEvent("MODIFIED", obj), seen)
        for key in [key for key in seen if key not in current]:
            final_state = seen[key]
            self._deliver(callback, WatchEvent("DELETED", final_state), seen)

    def _watch_stream(self, kind: str, callback, namespace, label_selector,
                      connected: threading.Event, seen: dict,
                      on_resynced=None):
        query = {"watch": "true",
                 "timeoutSeconds": str(WATCH_SERVER_TIMEOUT_S)}
        if label_selector:
            query["labelSelector"] = _serialize_selector(label_selector)
        path = self._path(kind, namespace, query=query)
        with self._request("GET", path, timeout=WATCH_READ_TIMEOUT_S) as resp:
            with self._streams_lock:
                self._live_streams.add(resp)
            try:
                connected.set()  # server has registered the watch relay
                # resync AFTER the stream is live (no missable gap): on the
                # first connect this is informer semantics — initial list →
                # ADDED for existing objects, as controller-runtime delivers
                # at boot — and after an outage it is the diff that surfaces
                # missed changes and deletions. Events racing the resync may
                # deliver twice (level-based consumers tolerate that); with
                # unchanged RVs the diff delivers nothing.
                self._resync(kind, callback, namespace, label_selector, seen)
                if on_resynced is not None:
                    on_resynced()
                while not self._stopped.is_set():
                    try:
                        line = resp.readline()
                    except ValueError:
                        # close()'s fallback path closed the file under us
                        # ("I/O operation on closed file") — shutdown race,
                        # scoped here so resync JSON errors stay loud
                        return
                    if not line:
                        return  # server closed the stream
                    try:
                        frame = json.loads(line)
                        event_type = frame["type"]
                        obj = frame["object"]
                    except (ValueError, KeyError, TypeError):
                        # truncated NDJSON frame (apiserver killed
                        # mid-write): reconnect; the resync re-covers
                        # whatever it carried
                        return
                    if event_type == "BOOKMARK":
                        continue
                    self._deliver(callback, WatchEvent(event_type, obj), seen)
            finally:
                with self._streams_lock:
                    self._live_streams.discard(resp)

    def close(self) -> None:
        """Stop watch threads NOW: set the stop flag and shut down the live
        watch sockets. A blocked recv() wakes on socket shutdown (returns
        0 bytes → readline sees EOF); calling resp.close() instead would
        contend on the BufferedReader lock the reading thread holds and
        block until the read timeout."""
        self._stopped.set()
        with self._streams_lock:
            streams = list(self._live_streams)
        for resp in streams:
            try:
                sock = resp.fp.raw._sock  # noqa: SLF001 — http.client layout
                sock.shutdown(socket.SHUT_RDWR)
            except (OSError, ValueError):
                pass  # already closed: nothing left to unblock
            except AttributeError:
                # different response internals: fall back to close() —
                # may block until the read timeout, but never hangs forever
                try:
                    resp.close()
                except OSError:
                    pass
