"""API error taxonomy mirroring k8s.io/apimachinery/pkg/api/errors.

The reference's controllers branch on apierrs.IsNotFound / IsConflict /
IsAlreadyExists everywhere (e.g. notebook_controller.go:151-204); our
controllers do the same against these exception types."""

from __future__ import annotations


class ApiError(Exception):
    code = 500
    reason = "InternalError"

    def __init__(self, message: str = ""):
        super().__init__(message or self.reason)
        self.message = message or self.reason


class NotFoundError(ApiError):
    code = 404
    reason = "NotFound"


class AlreadyExistsError(ApiError):
    code = 409
    reason = "AlreadyExists"


class ConflictError(ApiError):
    """Optimistic-concurrency failure (stale resourceVersion) — what
    retry.RetryOnConflict retries on in the reference."""
    code = 409
    reason = "Conflict"


class InvalidError(ApiError):
    code = 422
    reason = "Invalid"


class ForbiddenError(ApiError):
    code = 403
    reason = "Forbidden"


def is_not_found(err: Exception) -> bool:
    return isinstance(err, NotFoundError)


def is_conflict(err: Exception) -> bool:
    return isinstance(err, ConflictError)


def is_already_exists(err: Exception) -> bool:
    return isinstance(err, AlreadyExistsError)
