"""API error taxonomy mirroring k8s.io/apimachinery/pkg/api/errors.

The reference's controllers branch on apierrs.IsNotFound / IsConflict /
IsAlreadyExists everywhere (e.g. notebook_controller.go:151-204); our
controllers do the same against these exception types."""

from __future__ import annotations


class ApiError(Exception):
    code = 500
    reason = "InternalError"
    #: server-suggested retry delay in seconds (a 429/503 ``Retry-After``
    #: header); None when the server sent none
    retry_after: float | None = None
    #: set by the transport when this error surfaced on a RETRY after an
    #: ambiguous failure (connection reset mid-request): the earlier
    #: attempt may have been applied, so e.g. AlreadyExists on a retried
    #: create is probably our own first write landing
    ambiguous_retry: bool = False

    def __init__(self, message: str = ""):
        super().__init__(message or self.reason)
        self.message = message or self.reason


class NotFoundError(ApiError):
    code = 404
    reason = "NotFound"


class AlreadyExistsError(ApiError):
    code = 409
    reason = "AlreadyExists"


class ConflictError(ApiError):
    """Optimistic-concurrency failure (stale resourceVersion) — what
    retry.RetryOnConflict retries on in the reference."""
    code = 409
    reason = "Conflict"


class InvalidError(ApiError):
    code = 422
    reason = "Invalid"


class GoneError(ApiError):
    """410 Gone with reason ``Expired`` — the apiserver's answer when a
    watch asks to resume from a resourceVersion the watch cache has
    already evicted (apimachinery NewResourceExpired). The client's only
    correct move is the full LIST+diff resync; resuming anywhere else
    could silently skip evicted events."""
    code = 410
    reason = "Expired"


class ForbiddenError(ApiError):
    code = 403
    reason = "Forbidden"


class TooManyRequestsError(ApiError):
    """Apiserver priority-and-fairness rejection (429). Always safe to
    retry — the server refused the request *before* processing it — and
    carries the server's ``Retry-After`` pacing when sent."""
    code = 429
    reason = "TooManyRequests"


class ServiceUnavailableError(ApiError):
    """503 from the apiserver or an LB in front of it (overload, rolling
    restart). Retried for idempotent verbs only: unlike a 429 it gives no
    guarantee about whether processing started."""
    code = 503
    reason = "ServiceUnavailable"


def update_with_conflict_retry(client, read, mutate, attempts: int = 3):
    """retry.RetryOnConflict analog for the read→mutate→update shape, the
    conflict-retry loop concurrent reconcile workers need in several
    places (finalizer strips, copy-fields drift repair).

    ``read()`` returns the current object or None (nothing to do — give
    up quietly); pass a LIVE read (cache.live_reader) when retrying a
    conflict, because the foreign write that caused the 409 may not have
    reached the watch-fed cache yet and a cached re-read would resend the
    same stale resourceVersion. ``mutate(obj)`` edits in place and
    returns whether an update is needed. ConflictError retries up to
    ``attempts`` times; a final conflict or a vanished object returns
    None (callers relying on error-backoff should re-raise instead —
    this helper is for benign races the next watch event re-converges)."""
    for _attempt in range(attempts):
        obj = read()
        if obj is None or not mutate(obj):
            return None
        try:
            return client.update(obj)
        except ConflictError:
            continue
        except NotFoundError:
            return None
    return None

