"""StatefulSet-controller + kubelet simulator.

envtest "has no scheduler/kubelet, so pods never run" and the reference asserts
on rendered objects only (SURVEY §4.2). We go one step further: this simulator
reconciles StatefulSets into Pods and marks them Ready after a configurable
boot delay, so the full CR → slice-ready loop (including status mirroring,
culling probes and the <90s readiness target, BASELINE.md) is exercisable
in-process. It reproduces the StatefulSet semantics our TPU layer leans on:

- pods named ``<sts>-<ordinal>`` with the ``apps.kubernetes.io/pod-index``
  label (the TPU_WORKER_ID source);
- ``spec.subdomain``/``serviceName`` so worker DNS is representable;
- scale-down reaps the highest ordinals first; replicas=0 reaps everything
  (the slice-atomic cull path);
- pod template changes restart pods (rolling update, OnDelete-ish).
"""

from __future__ import annotations

import time

from ..controllers.manager import Request, Result, owner_mapper
from ..utils import k8s
from . import errors
from .store import ClusterStore


class StatefulSetSimulator:
    name = "sim-statefulset-controller"

    def __init__(self, client: ClusterStore, boot_delay_s: float = 0.0,
                 ready_hook=None):
        """``ready_hook(pod) -> bool`` lets tests/bench gate pod readiness on
        e.g. a simulated TPU runtime verification."""
        self.client = client
        self.boot_delay_s = boot_delay_s
        self.ready_hook = ready_hook
        self._boot_times: dict[tuple[str, str], float] = {}

    def setup(self, mgr) -> None:
        mgr.register(self)
        mgr.watch("StatefulSet", self.name)
        mgr.watch("Pod", self.name, mapper=owner_mapper("StatefulSet"))

    def reconcile(self, req: Request) -> Result | None:
        sts = self.client.get_or_none("StatefulSet", req.namespace, req.name)
        if sts is None or k8s.is_deleting(sts):
            return None
        replicas = k8s.get_in(sts, "spec", "replicas", default=1)
        ns, sts_name = req.namespace, req.name
        selector = k8s.get_in(sts, "spec", "template", "metadata", "labels",
                              default={}) or {}
        desired_template = k8s.get_in(sts, "spec", "template", default={})

        # list by spec.selector.matchLabels — IMMUTABLE in real apps/v1,
        # unlike the template labels, which the notebook reconciler
        # rewrites on label edits (copy_statefulset_fields) — so the
        # per-reconcile cost is O(this STS's pods), not O(pods in ns):
        # the informer-index shape of the real STS controller. At a 500-
        # notebook fan-out the unselected list made the simulator O(N²)
        # and dominated the loadtest wall clock. Ownership stays the
        # source of truth; an empty selector falls back to the full list.
        pod_selector = k8s.get_in(sts, "spec", "selector", "matchLabels",
                                  default=None) or None
        requeue: float | None = None
        existing = {k8s.name(p): p
                    for p in self.client.list("Pod", ns,
                                              label_selector=pod_selector)
                    if k8s.is_owned_by(p, k8s.uid(sts))}

        # reap pods beyond replicas (highest ordinals first — STS semantics)
        for pod_name in sorted(existing, reverse=True):
            ordinal = _ordinal_of(pod_name, sts_name)
            if ordinal is None or ordinal >= replicas:
                try:
                    self.client.delete("Pod", ns, pod_name)
                except errors.NotFoundError:
                    pass
                existing.pop(pod_name, None)

        for i in range(replicas):
            pod_name = f"{sts_name}-{i}"
            pod = existing.get(pod_name)
            if pod is None:
                pod = self._make_pod(sts, pod_name, i, selector, desired_template)
                try:
                    self.client.create(pod)
                except errors.AlreadyExistsError:
                    pass
                self._boot_times[(ns, pod_name)] = time.monotonic()
                requeue = max(self.boot_delay_s, 0.001)
                continue
            # template drift → restart (delete; next pass recreates)
            if pod.get("spec", {}).get("containers") != \
                    k8s.get_in(desired_template, "spec", "containers"):
                try:
                    self.client.delete("Pod", ns, pod_name)
                except errors.NotFoundError:
                    pass
                requeue = 0.001
                continue
            if not _pod_is_ready(pod):
                booted_at = self._boot_times.get((ns, pod_name), 0.0)
                if time.monotonic() - booted_at >= self.boot_delay_s and (
                        self.ready_hook is None or self.ready_hook(pod)):
                    self._mark_ready(pod)
                else:
                    requeue = max(self.boot_delay_s / 4, 0.001)

        ready = sum(1 for p in self.client.list(
                        "Pod", ns, label_selector=pod_selector)
                    if k8s.is_owned_by(p, k8s.uid(sts)) and _pod_is_ready(p))
        if k8s.get_in(sts, "status", "readyReplicas") != ready or \
                k8s.get_in(sts, "status", "replicas") != replicas:
            sts["status"] = {"replicas": replicas, "readyReplicas": ready,
                             "currentReplicas": ready}
            try:
                self.client.update_status(sts)
            except (errors.ConflictError, errors.NotFoundError):
                requeue = 0.001
        return Result(requeue_after=requeue) if requeue else None

    def _make_pod(self, sts: dict, pod_name: str, ordinal: int,
                  selector: dict, template: dict) -> dict:
        pod_labels = dict(selector)
        pod_labels["apps.kubernetes.io/pod-index"] = str(ordinal)
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": pod_name,
                "namespace": k8s.namespace(sts),
                "labels": pod_labels,
                "annotations": dict(k8s.get_in(
                    template, "metadata", "annotations", default={}) or {}),
            },
            "spec": k8s.deepcopy(template.get("spec", {})),
            "status": {"phase": "Pending", "conditions": []},
        }
        pod["spec"]["hostname"] = pod_name
        pod["spec"]["subdomain"] = k8s.get_in(sts, "spec", "serviceName",
                                              default="")
        k8s.set_controller_reference(sts, pod)
        return pod

    def _mark_ready(self, pod: dict) -> None:
        now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        container_statuses = [
            {"name": c.get("name", ""), "ready": True, "restartCount": 0,
             "state": {"running": {"startedAt": now}}}
            for c in k8s.get_in(pod, "spec", "containers", default=[]) or []]
        pod["status"] = {
            "phase": "Running",
            "conditions": [
                {"type": "PodScheduled", "status": "True"},
                {"type": "Initialized", "status": "True"},
                {"type": "ContainersReady", "status": "True"},
                {"type": "Ready", "status": "True",
                 "lastTransitionTime": now},
            ],
            "containerStatuses": container_statuses,
        }
        try:
            self.client.update_status(pod)
        except (errors.ConflictError, errors.NotFoundError):
            pass


def _ordinal_of(pod_name: str, sts_name: str) -> int | None:
    prefix = sts_name + "-"
    if not pod_name.startswith(prefix):
        return None
    suffix = pod_name[len(prefix):]
    return int(suffix) if suffix.isdigit() else None


def _pod_is_ready(pod: dict) -> bool:
    return any(c.get("type") == "Ready" and c.get("status") == "True"
               for c in k8s.get_in(pod, "status", "conditions",
                                   default=[]) or [])
