"""StatefulSet-controller + kubelet + node-lifecycle simulator.

envtest "has no scheduler/kubelet, so pods never run" and the reference asserts
on rendered objects only (SURVEY §4.2). We go one step further: this simulator
reconciles StatefulSets into Pods and marks them Ready after a configurable
boot delay, so the full CR → slice-ready loop (including status mirroring,
culling probes and the <90s readiness target, BASELINE.md) is exercisable
in-process. It reproduces the StatefulSet semantics our TPU layer leans on:

- pods named ``<sts>-<ordinal>`` with the ``apps.kubernetes.io/pod-index``
  label (the TPU_WORKER_ID source);
- ``spec.subdomain``/``serviceName`` so worker DNS is representable;
- scale-down reaps the highest ordinals first; replicas=0 reaps everything
  (the slice-atomic cull path);
- pod template changes restart pods (rolling update, OnDelete-ish).

Node lifecycle (the failure mode that dominates TPU fleets — GKE
preemption/maintenance): every pod is bound to a ``Node`` object
(``spec.nodeName``; one node per worker VM, the multi-host TPU shape).
Injecting node failure (``kill_node``/``set_node_ready``/``taint_node``/
``preempt_node``) drives the node-lifecycle-controller behavior the slice
repair loop depends on:

- a pod on a dead node (NotReady, NoExecute-tainted, or deleted) flips
  Ready=False within one reconcile tick — status mirroring reacts
  (``SliceReady`` drops) even without the repair controller;
- after ``node_grace_s`` the pod is EVICTED (deleted); the recreate binds a
  FRESH node (GKE replaces preempted capacity), preserving the pod name and
  ordinal;
- a preemption-notice taint (``cloud.google.com/impending-node-termination``,
  NoSchedule) leaves running pods Ready but blocks new bindings — the
  cordon shape; the repair controller treats the notice itself as Degraded.
"""

from __future__ import annotations

import heapq
import threading
import time

from ..controllers.manager import Request, Result, owner_mapper
from ..utils import k8s, names, sanitizer
from . import errors
from .store import ClusterStore


class _BootScheduler:
    """Event-driven pod-boot timer wheel: one thread, one heap of
    (due, ns, pod) entries, batched readiness flips at each deadline.

    The polled alternative — every StatefulSet requeueing at
    boot_delay/4 until its pods turn Ready — costs O(pods × polls)
    reconciles, which at a 100k-pod soak is millions of no-op dispatches.
    Here each booting pod costs exactly ONE timer entry and one status
    write; the Ready flip's watch event drives the STS reconcile that
    observes it (tick → event, not tick → poll)."""

    def __init__(self, mark_ready) -> None:
        self._mark_ready = mark_ready  # fn(ns, pod_name) -> None
        self._heap: list[tuple[float, str, str]] = []
        self._cv = sanitizer.tracked_condition(
            "kubelet.timer", order=sanitizer.ORDER_CONTROLLER)
        self._thread: threading.Thread | None = None

    #: an empty wheel parks this long before its thread exits — bounds
    #: idle daemon threads (one per simulator) without lifecycle plumbing;
    #: the next schedule() simply restarts the thread
    IDLE_EXIT_S = 5.0

    def schedule(self, due: float, namespace: str, pod_name: str) -> None:
        with self._cv:
            heapq.heappush(self._heap, (due, namespace, pod_name))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="kubelet-boot-scheduler")
                self._thread.start()
            self._cv.notify()

    def _loop(self) -> None:
        while True:  # pump: boot scheduler cv-wait; idle-exit after IDLE_EXIT_S
            due_batch: list[tuple[str, str]] = []
            with self._cv:
                now = time.monotonic()
                while self._heap and self._heap[0][0] <= now:
                    _, ns, pod = heapq.heappop(self._heap)
                    due_batch.append((ns, pod))
                if not due_batch:
                    if self._heap:
                        self._cv.wait(self._heap[0][0] - now)
                        continue
                    self._cv.wait(self.IDLE_EXIT_S)
                    if not self._heap:
                        # idle past the grace: exit rather than pin this
                        # simulator (and its client) via a parked thread
                        # forever; schedule() restarts on demand
                        self._thread = None
                        return
                    continue
            for ns, pod in due_batch:
                try:
                    self._mark_ready(ns, pod)
                except Exception:  # noqa: BLE001 — a single pod's flip
                    pass           # failing must not stall the wheel


def node_doomed(node: dict | None) -> bool:
    """Pods on this node are lost: node gone, NotReady, or NoExecute-tainted
    (the taint manager's eviction trigger). A NoSchedule-only taint — the
    preemption NOTICE — does not doom running pods."""
    if node is None or not k8s.condition_true(node, "Ready"):
        return True
    return any(t.get("effect") == "NoExecute"
               for t in k8s.get_in(node, "spec", "taints", default=[]) or [])


def node_schedulable(node: dict | None) -> bool:
    """New pods may bind here: Ready, untainted, not cordoned."""
    if node is None or not k8s.condition_true(node, "Ready"):
        return False
    if k8s.get_in(node, "spec", "unschedulable"):
        return False
    return not (k8s.get_in(node, "spec", "taints", default=[]) or [])


# ------------------------------------------------------- injection helpers
def set_node_ready(client, node_name: str, ready: bool,
                   reason: str = "KubeletStopped") -> None:
    node = client.get("Node", "", node_name)
    node["status"] = node.get("status") or {}
    node["status"]["conditions"] = [
        {"type": "Ready", "status": "True" if ready else "False",
         "reason": "KubeletReady" if ready else reason,
         "lastTransitionTime": k8s.now_iso()}]
    client.update_status(node)


def taint_node(client, node_name: str,
               key: str = names.PREEMPTION_TAINT_KEY,
               effect: str = "NoSchedule") -> None:
    node = client.get("Node", "", node_name)
    taints = k8s.get_in(node, "spec", "taints", default=[]) or []
    if not any(t.get("key") == key for t in taints):
        taints.append({"key": key, "effect": effect,
                       "timeAdded": k8s.now_iso()})
        node.setdefault("spec", {})["taints"] = taints
        client.update(node)


def preempt_node(client, node_name: str) -> None:
    """GCE/GKE preemption notice: the node keeps serving but termination is
    imminent (ACPI G2 / maintenance event)."""
    taint_node(client, node_name, names.PREEMPTION_TAINT_KEY, "NoSchedule")


def kill_node(client, node_name: str) -> None:
    """The termination itself: kubelet stops posting status (NotReady) and
    the taint manager marks it unreachable/NoExecute."""
    taint_node(client, node_name, names.NODE_UNREACHABLE_TAINT_KEY,
               "NoExecute")
    set_node_ready(client, node_name, False, reason="NodeStatusUnknown")


class StatefulSetSimulator:
    name = "sim-statefulset-controller"

    def __init__(self, client: ClusterStore, boot_delay_s: float = 0.0,
                 ready_hook=None, manage_nodes: bool = True,
                 node_grace_s: float = 0.25,
                 event_driven_boot: bool = False,
                 wall_clock=time.time):
        """``ready_hook(pod) -> bool`` lets tests/bench gate pod readiness on
        e.g. a simulated TPU runtime verification. ``manage_nodes`` binds
        every pod to a simulated Node and runs the node-lifecycle behavior
        described in the module docstring; ``node_grace_s`` is the
        NotReady→eviction window (the pod-eviction-timeout analog,
        wall-clock seconds). ``event_driven_boot`` replaces the
        boot_delay/4 polling requeues with a timer-wheel readiness flip
        (_BootScheduler) — one scheduled event per pod instead of
        O(polls), the 100k-pod soak shape; a ``ready_hook`` keeps the
        polled path (its answer can change between polls)."""
        self.client = client
        self.boot_delay_s = boot_delay_s
        self.ready_hook = ready_hook
        self.manage_nodes = manage_nodes
        self.node_grace_s = node_grace_s
        self.event_driven_boot = event_driven_boot and ready_hook is None
        # injected wall clock for status timestamps (startedAt): logic
        # timing stays monotonic; only the rendered RFC3339 stamps differ
        self.wall_clock = wall_clock
        self._boot_scheduler = _BootScheduler(self._boot_pod_ready) \
            if self.event_driven_boot else None
        self._boot_times: dict[tuple[str, str], float] = {}
        # (ns, pod) → node generation; bumped when the bound node dies so
        # the recreate lands on fresh capacity
        self._node_gen: dict[tuple[str, str], int] = {}
        # (ns, pod) → monotonic time its node was first seen doomed
        self._node_down_since: dict[tuple[str, str], float] = {}

    def setup(self, mgr) -> None:
        mgr.register(self)
        mgr.watch("StatefulSet", self.name)
        mgr.watch("Pod", self.name, mapper=owner_mapper("StatefulSet"))
        if self.manage_nodes:
            mgr.watch("Node", self.name, mapper=self._node_to_sts)

    def _node_to_sts(self, node: dict) -> list[Request]:
        """Node event → the StatefulSets with pods bound to it
        (cache.pods_on_node: by-field ``spec.nodeName`` index when the
        client carries one, O(pods on THIS node))."""
        from .cache import pods_on_node
        out, seen = [], set()
        for pod in pods_on_node(self.client, k8s.name(node)):
            for ref in k8s.get_in(pod, "metadata", "ownerReferences",
                                  default=[]) or []:
                if ref.get("kind") == "StatefulSet":
                    key = (k8s.namespace(pod), ref.get("name"))
                    if key not in seen:
                        seen.add(key)
                        out.append(Request(*key))
        return out

    def reconcile(self, req: Request) -> Result | None:
        sts = self.client.get_or_none("StatefulSet", req.namespace, req.name)
        if sts is None or k8s.is_deleting(sts):
            return None
        replicas = k8s.get_in(sts, "spec", "replicas", default=1)
        ns, sts_name = req.namespace, req.name
        selector = k8s.get_in(sts, "spec", "template", "metadata", "labels",
                              default={}) or {}
        desired_template = k8s.get_in(sts, "spec", "template", default={})

        # list by spec.selector.matchLabels — IMMUTABLE in real apps/v1,
        # unlike the template labels, which the notebook reconciler
        # rewrites on label edits (copy_statefulset_fields) — so the
        # per-reconcile cost is O(this STS's pods), not O(pods in ns):
        # the informer-index shape of the real STS controller. At a 500-
        # notebook fan-out the unselected list made the simulator O(N²)
        # and dominated the loadtest wall clock. Ownership stays the
        # source of truth; an empty selector falls back to the full list.
        pod_selector = k8s.get_in(sts, "spec", "selector", "matchLabels",
                                  default=None) or None
        requeue: float | None = None
        existing = {k8s.name(p): p
                    for p in self.client.list("Pod", ns,
                                              label_selector=pod_selector)
                    if k8s.is_owned_by(p, k8s.uid(sts))}

        # reap pods beyond replicas (highest ordinals first — STS semantics)
        for pod_name in sorted(existing, reverse=True):
            ordinal = _ordinal_of(pod_name, sts_name)
            if ordinal is None or ordinal >= replicas:
                try:
                    self.client.delete("Pod", ns, pod_name)
                except errors.NotFoundError:
                    pass
                existing.pop(pod_name, None)

        for i in range(replicas):
            pod_name = f"{sts_name}-{i}"
            pod = existing.get(pod_name)
            if pod is None:
                pod = self._make_pod(sts, pod_name, i, selector, desired_template)
                try:
                    self.client.create(pod)
                except errors.AlreadyExistsError:
                    pass
                now = time.monotonic()
                self._boot_times[(ns, pod_name)] = now
                if self._boot_scheduler is not None:
                    # event-driven: ONE timer entry flips this pod Ready
                    # at its boot deadline; the requeue below is only a
                    # lost-event safety net, not the readiness poll
                    self._boot_scheduler.schedule(now + self.boot_delay_s,
                                                  ns, pod_name)
                    requeue = max(self.boot_delay_s * 2, 0.25)
                else:
                    requeue = max(self.boot_delay_s, 0.001)
                continue
            # template drift → restart (delete; next pass recreates)
            if pod.get("spec", {}).get("containers") != \
                    k8s.get_in(desired_template, "spec", "containers"):
                try:
                    self.client.delete("Pod", ns, pod_name)
                except errors.NotFoundError:
                    pass
                requeue = 0.001
                continue
            if self.manage_nodes:
                node_requeue = self._apply_node_health(ns, pod)
                if node_requeue is not None:
                    requeue = min(requeue, node_requeue) \
                        if requeue else node_requeue
                    continue  # doomed node: never (re)mark this pod Ready
            if not _pod_is_ready(pod):
                booted_at = self._boot_times.get((ns, pod_name), 0.0)
                if time.monotonic() - booted_at >= self.boot_delay_s and (
                        self.ready_hook is None or self.ready_hook(pod)):
                    self._mark_ready(pod)
                elif self._boot_scheduler is not None:
                    # scheduler owns the flip; safety-net requeue only
                    requeue = max(self.boot_delay_s * 2, 0.25)
                else:
                    requeue = max(self.boot_delay_s / 4, 0.001)

        ready = sum(1 for p in self.client.list(
                        "Pod", ns, label_selector=pod_selector)
                    if k8s.is_owned_by(p, k8s.uid(sts)) and _pod_is_ready(p))
        if k8s.get_in(sts, "status", "readyReplicas") != ready or \
                k8s.get_in(sts, "status", "replicas") != replicas:
            sts["status"] = {"replicas": replicas, "readyReplicas": ready,
                             "currentReplicas": ready}
            try:
                self.client.update_status(sts)
            except (errors.ConflictError, errors.NotFoundError):
                requeue = 0.001
        return Result(requeue_after=requeue) if requeue else None

    # ------------------------------------------------------ node lifecycle
    def _apply_node_health(self, ns: str, pod: dict) -> float | None:
        """Node-lifecycle-controller behavior for one pod. Returns a
        requeue delay while the pod is riding out its node's death (the
        caller must then skip ready-marking), or None when the node is
        fine."""
        pod_name = k8s.name(pod)
        node_name = k8s.get_in(pod, "spec", "nodeName")
        if not node_name:
            return None  # pre-node-era pod (external creation): no binding
        key = (ns, pod_name)
        node = self.client.get_or_none("Node", "", node_name)
        if not node_doomed(node):
            self._node_down_since.pop(key, None)
            return None
        first = self._node_down_since.setdefault(key, time.monotonic())
        if _pod_is_ready(pod):
            # within one reconcile tick of the node dying
            self._mark_not_ready(pod, "NodeNotReady")
        if time.monotonic() - first >= self.node_grace_s:
            # eviction: the pod object goes away; the recreate pass binds
            # the SAME pod name (ordinal/hostname preserved) to new capacity
            try:
                self.client.delete("Pod", ns, pod_name)
            except errors.NotFoundError:
                pass
            self._node_down_since.pop(key, None)
            return 0.001
        return max(self.node_grace_s / 4, 0.001)

    def _bind_node(self, ns: str, pod_name: str) -> str:
        """Current-generation node for this worker, skipping dead/cordoned
        ones (GKE replaces preempted capacity with fresh nodes; the pod
        name — and with it TPU_WORKER_ID and the stable hostname — never
        changes)."""
        key = (ns, pod_name)
        gen = self._node_gen.get(key, 0)
        while True:  # bounded: gen increments until a fresh node name creates
            node_name = f"sim-node-{ns}-{pod_name}-{gen}"
            node = self.client.get_or_none("Node", "", node_name)
            if node is None:
                try:
                    self.client.create({
                        "apiVersion": "v1",
                        "kind": "Node",
                        "metadata": {
                            "name": node_name,
                            "labels": {names.SIM_NODE_LABEL: "true"},
                        },
                        "spec": {},
                        "status": {"conditions": [
                            {"type": "Ready", "status": "True",
                             "reason": "KubeletReady"}]},
                    })
                except errors.AlreadyExistsError:
                    continue  # raced another worker; re-read next loop
                self._node_gen[key] = gen
                return node_name
            if node_schedulable(node):
                self._node_gen[key] = gen
                return node_name
            gen += 1

    def _make_pod(self, sts: dict, pod_name: str, ordinal: int,
                  selector: dict, template: dict) -> dict:
        pod_labels = dict(selector)
        pod_labels[names.POD_INDEX_LABEL] = str(ordinal)
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": pod_name,
                "namespace": k8s.namespace(sts),
                "labels": pod_labels,
                "annotations": dict(k8s.get_in(
                    template, "metadata", "annotations", default={}) or {}),
            },
            "spec": k8s.deepcopy(template.get("spec", {})),
            "status": {"phase": "Pending", "conditions": []},
        }
        pod["spec"]["hostname"] = pod_name
        pod["spec"]["subdomain"] = k8s.get_in(sts, "spec", "serviceName",
                                              default="")
        if self.manage_nodes:
            pod["spec"]["nodeName"] = self._bind_node(k8s.namespace(sts),
                                                      pod_name)
        k8s.set_controller_reference(sts, pod)
        return pod

    def _boot_pod_ready(self, ns: str, pod_name: str) -> None:
        """Timer-wheel readiness flip (event-driven boot): re-read the pod
        at its boot deadline and mark it Ready unless it vanished, already
        turned Ready, sits on a doomed node (the node path owns those —
        the STS reconcile keeps its safety-net requeue either way), or was
        RECREATED since this timer was scheduled — a restart re-stamps
        ``_boot_times`` and schedules a fresh timer, and the predecessor's
        stale timer must not flip the replacement Ready mid-boot."""
        pod = self.client.get_or_none("Pod", ns, pod_name)
        if pod is None or _pod_is_ready(pod):
            return
        booted_at = self._boot_times.get((ns, pod_name), 0.0)
        if time.monotonic() < booted_at + self.boot_delay_s:
            return  # a newer incarnation's timer owns this flip
        if self.manage_nodes:
            node_name = k8s.get_in(pod, "spec", "nodeName")
            if node_name and node_doomed(
                    self.client.get_or_none("Node", "", node_name)):
                return
        self._mark_ready(pod)

    def _mark_ready(self, pod: dict) -> None:
        now = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                            time.gmtime(self.wall_clock()))
        container_statuses = [
            {"name": c.get("name", ""), "ready": True, "restartCount": 0,
             "state": {"running": {"startedAt": now}}}
            for c in k8s.get_in(pod, "spec", "containers", default=[]) or []]
        pod["status"] = {
            "phase": "Running",
            "conditions": [
                {"type": "PodScheduled", "status": "True"},
                {"type": "Initialized", "status": "True"},
                {"type": "ContainersReady", "status": "True"},
                {"type": "Ready", "status": "True",
                 "lastTransitionTime": now},
            ],
            "containerStatuses": container_statuses,
        }
        try:
            self.client.update_status(pod)
        except (errors.ConflictError, errors.NotFoundError):
            pass

    def _mark_not_ready(self, pod: dict, reason: str) -> None:
        now = k8s.now_iso()
        pod = k8s.deepcopy(pod)
        conditions = [c for c in k8s.get_in(pod, "status", "conditions",
                                            default=[]) or []
                      if c.get("type") not in ("Ready", "ContainersReady")]
        conditions += [
            {"type": "ContainersReady", "status": "False", "reason": reason},
            {"type": "Ready", "status": "False", "reason": reason,
             "lastTransitionTime": now},
        ]
        pod.setdefault("status", {})["conditions"] = conditions
        try:
            self.client.update_status(pod)
        except (errors.ConflictError, errors.NotFoundError):
            pass


def _ordinal_of(pod_name: str, sts_name: str) -> int | None:
    prefix = sts_name + "-"
    if not pod_name.startswith(prefix):
        return None
    suffix = pod_name[len(prefix):]
    return int(suffix) if suffix.isdigit() else None


def _pod_is_ready(pod: dict) -> bool:
    return k8s.condition_true(pod, "Ready")
