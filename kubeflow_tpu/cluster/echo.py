"""Self-write echo suppression for reconciler watch streams.

Every write a reconciler makes comes back to it as a watch event (the
apiserver fans mutations out to all watchers, including the author). For a
level-triggered controller that event carries zero information — the
reconcile that made the write already acted on the freshest state — but it
costs a full re-reconcile. With a single dispatch thread the echoes mostly
vanish into queue coalescing (a deep backlog merges them into the next run
anyway); with MaxConcurrentReconciles > 1 the queue stays shallow and every
echo becomes its own reconcile. Measured on the 500-notebook wire fan-out:
~2x the reconciles and requests per notebook at workers=4 vs workers=1,
almost entirely self-echo re-runs.

``EchoTrackingClient`` wraps a reconciler's client, records the
resourceVersion of every object its writes produce, and exposes an
``is_echo(event)`` predicate for the manager watches: an event whose
object carries exactly a recorded (kind, ns, name) → rv is the author's
own write coming back and is dropped. The same-rv match makes this safe:

- a foreign write (other controller, user, another replica) bumps rv past
  the recorded value → never suppressed;
- our write racing a foreign one: whichever landed later has a different
  rv → the foreign state is always delivered;
- DELETED events are never suppressed (deletes need no rv reasoning);
- a missed recording (in-process stores deliver watch callbacks inline,
  BEFORE the write call returns) fails open: the echo is delivered and
  merely costs the old re-reconcile.

This is the same idea as controller-runtime's predicate layer
(GenerationChangedPredicate and friends drop self-inflicted status-echo
reconciles); rv-matching generalizes it to annotation/label writes, which
this control plane uses as its cooperation protocol.

One contract change for authors: a reconciler must NOT rely on its own
write's echo to re-trigger itself (e.g. "update then return; the watch
re-enqueues"). Pattern replacement: return ``Result(requeue_after=0)`` for
an explicit immediate requeue (extension.py's finalizer-add does this).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from ..utils import k8s, sanitizer
from ..utils.metrics import phase_record


class EchoTrackingClient:
    """Transparent client wrapper: writes record their resulting
    resourceVersion; everything else passes through. Thread-safe — with a
    worker pool, several reconciles of different keys write concurrently."""

    #: rvs remembered per object — one reconcile can write the same object
    #: more than once (create + immediate fixup), and each echo arrives
    #: separately
    RVS_PER_KEY = 4
    #: objects tracked before the oldest recording is evicted
    CAPACITY = 8192
    #: kinds no ``not_echo`` predicate ever consults — recording them
    #: (high-churn Event writes from the recorder especially) would only
    #: evict live Notebook/STS/Service records from the bounded table
    NEVER_TRACK = frozenset(("Event",))

    def __init__(self, client):
        self._client = client
        self._lock = sanitizer.tracked_lock(
            "echo.table", order=sanitizer.ORDER_LEAF)
        # (kind, namespace, name) → list of recent rv strings (newest last)
        self._written: OrderedDict[tuple[str, str, str], list[str]] = \
            OrderedDict()

    # ------------------------------------------------------------ recording
    def _record(self, obj):
        if isinstance(obj, dict) and obj.get("kind") not in self.NEVER_TRACK:
            rv = k8s.get_in(obj, "metadata", "resourceVersion")
            if rv is not None:
                key = (k8s.kind(obj), k8s.namespace(obj), k8s.name(obj))
                with self._lock:
                    rvs = self._written.setdefault(key, [])
                    rvs.append(str(rv))
                    del rvs[:-self.RVS_PER_KEY]
                    self._written.move_to_end(key)
                    while len(self._written) > self.CAPACITY:
                        self._written.popitem(last=False)
        return obj

    def is_echo(self, event) -> bool:
        """True iff ``event`` is the delivery of one of OUR writes."""
        if event.type == "DELETED":
            return False
        obj = event.obj
        rv = k8s.get_in(obj, "metadata", "resourceVersion")
        if rv is None:
            return False
        key = (k8s.kind(obj), k8s.namespace(obj), k8s.name(obj))
        with self._lock:
            return str(rv) in self._written.get(key, ())

    def not_echo(self, event) -> bool:
        """Watch-predicate form: pass everything that is not our echo."""
        return not self.is_echo(event)

    # --------------------------------------------------------------- writes
    # Every verb is also attributed to the reconcile phase collector
    # (utils.metrics.phase_record): this wrapper is the one layer EVERY
    # reconciler's client chain passes through exactly once, so the
    # reconcile_read_seconds / reconcile_write_seconds decomposition is
    # measured here — cached reads cost microseconds, wire reads cost a
    # round trip, and the histograms prove which one the hot path takes.
    def create(self, obj):
        t0 = time.monotonic()
        try:
            return self._record(self._client.create(obj))
        finally:
            phase_record("write", time.monotonic() - t0)

    def update(self, obj):
        t0 = time.monotonic()
        try:
            return self._record(self._client.update(obj))
        finally:
            phase_record("write", time.monotonic() - t0)

    def update_status(self, obj):
        t0 = time.monotonic()
        try:
            return self._record(self._client.update_status(obj))
        finally:
            phase_record("write", time.monotonic() - t0)

    def patch(self, kind, namespace, name, patch):
        t0 = time.monotonic()
        try:
            return self._record(self._client.patch(kind, namespace, name,
                                                   patch))
        finally:
            phase_record("write", time.monotonic() - t0)

    def delete(self, kind, namespace, name):
        t0 = time.monotonic()
        try:
            return self._client.delete(kind, namespace, name)
        finally:
            phase_record("write", time.monotonic() - t0)

    # ---------------------------------------------------------------- reads
    def get(self, kind, namespace, name):
        t0 = time.monotonic()
        try:
            return self._client.get(kind, namespace, name)
        finally:
            phase_record("read", time.monotonic() - t0)

    def get_or_none(self, kind, namespace, name):
        t0 = time.monotonic()
        try:
            return self._client.get_or_none(kind, namespace, name)
        finally:
            phase_record("read", time.monotonic() - t0)

    def list(self, kind, namespace=None, label_selector=None):
        t0 = time.monotonic()
        try:
            return self._client.list(kind, namespace, label_selector)
        finally:
            phase_record("read", time.monotonic() - t0)

    def get_owned(self, kind, owner):
        from .cache import owned_objects
        t0 = time.monotonic()
        try:
            return owned_objects(self._client, kind, owner)
        finally:
            phase_record("read", time.monotonic() - t0)

    # -------------------------------------------------------- passthrough
    def __getattr__(self, name):
        return getattr(self._client, name)
