"""Webhook-configuration-driven remote admission.

kube-apiserver's admission phase POSTs ``admission.k8s.io/v1``
AdmissionReview over HTTPS to the webhooks registered by
Mutating/ValidatingWebhookConfiguration objects and applies the returned
JSONPatch (the reference's webhooks are registered exactly this way,
config/webhook + odh main.go:306-331). ClusterStore reproduces that here:
configuration objects created in the store are indexed, and writes of
matching kinds call out to the configured HTTPS endpoints — so the
manager's real AdmissionServer (webhook/server.py) is exercised over the
genuine wire protocol, not just via in-process plugin registration.

Supported clientConfig: ``url`` (+ optional ``caBundle``). Service-based
clientConfig needs cluster DNS, which standalone deployments don't have —
those configs are skipped with a log (on a real cluster the real apiserver
resolves them; this module is the facade's analog).

failurePolicy semantics match the reference's hard-gate behavior
(SURVEY §5: failurePolicy=Fail makes admission a hard gate): an unreachable
webhook denies the write under Fail (default) and is skipped under Ignore.
"""

from __future__ import annotations

import base64
import copy
import json
import logging
import ssl
import urllib.error
import urllib.request

from ..utils import k8s
from . import restmapper
from .errors import ApiError, InvalidError

log = logging.getLogger("kubeflow_tpu.remote_admission")

MUTATING_KIND = "MutatingWebhookConfiguration"
VALIDATING_KIND = "ValidatingWebhookConfiguration"
CONFIG_KINDS = (MUTATING_KIND, VALIDATING_KIND)

DEFAULT_TIMEOUT_S = 10.0


class AdmissionWebhookError(ApiError):
    code = 500
    reason = "InternalError"


def _unescape(token: str) -> str:
    return token.replace("~1", "/").replace("~0", "~")


def apply_json_patch(obj: dict, ops: list[dict]) -> dict:
    """RFC 6902 add/remove/replace (the ops AdmissionServer emits). A
    ``remove`` whose intermediate path is absent is a no-op instead of
    grafting empty maps into the object (can happen when webhook patches
    race each other)."""
    result = copy.deepcopy(obj)
    for op in ops:
        tokens = [_unescape(t) for t in op["path"].split("/")[1:]]
        verb = op["op"]
        parent = result
        missing = False
        for token in tokens[:-1]:
            if isinstance(parent, list):
                parent = parent[int(token)]
            elif token in parent:
                parent = parent[token]
            elif verb == "remove":
                missing = True
                break
            else:
                parent = parent.setdefault(token, {})
        if missing:
            continue
        leaf = tokens[-1] if tokens else ""
        if isinstance(parent, list):
            index = len(parent) if leaf == "-" else int(leaf)
            if verb == "add":
                parent.insert(index, op["value"])
            elif verb == "remove":
                del parent[index]
            else:
                parent[index] = op["value"]
        else:
            if verb == "remove":
                parent.pop(leaf, None)
            else:
                parent[leaf] = op["value"]
    return result


def _rule_matches(rule: dict, kind: str, operation: str,
                  api_version: str = "") -> bool:
    try:
        mapping = restmapper.mapping_for(kind)
    except KeyError:
        return False
    group, _version = mapping.group_version
    groups = rule.get("apiGroups", ["*"])
    if "*" not in groups and group not in groups:
        return False
    resources = rule.get("resources", ["*"])
    if "*" not in resources and mapping.plural not in resources:
        return False
    versions = rule.get("apiVersions", ["*"])
    version = api_version.rpartition("/")[2] if api_version else ""
    if "*" not in versions and version and version not in versions:
        return False
    operations = rule.get("operations", ["*"])
    return "*" in operations or operation in operations


_ssl_cache: dict[str, ssl.SSLContext] = {}


def _ssl_context(ca_bundle_b64: str | None) -> ssl.SSLContext | None:
    """Per-caBundle cached context built from cadata — no temp files, no
    per-call context construction (admission runs on every store write)."""
    if not ca_bundle_b64:
        return None
    ctx = _ssl_cache.get(ca_bundle_b64)
    if ctx is None:
        pem = base64.b64decode(ca_bundle_b64).decode()
        ctx = ssl.create_default_context(cadata=pem)
        _ssl_cache[ca_bundle_b64] = ctx
    return ctx


def _call(url: str, review: dict, ca_bundle_b64: str | None,
          timeout: float) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(review).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout,
                                context=_ssl_context(ca_bundle_b64)) as resp:
        return json.loads(resp.read())


def run_webhooks(configs: list[dict], operation: str, obj: dict,
                 old: dict | None, *, mutating: bool,
                 timeout: float = DEFAULT_TIMEOUT_S) -> dict:
    """Run every matching webhook of the given phase; returns the (possibly
    mutated) object, raises ApiError on denial/hard failure."""
    kind = k8s.kind(obj)
    api_version = obj.get("apiVersion", "")
    for config in configs:
        for webhook in config.get("webhooks", []) or []:
            if not any(_rule_matches(rule, kind, operation, api_version)
                       for rule in webhook.get("rules", []) or []):
                continue
            client_config = webhook.get("clientConfig", {}) or {}
            url = client_config.get("url")
            fail_open = webhook.get("failurePolicy", "Fail") == "Ignore"
            if not url:
                log.info("webhook %s has service-based clientConfig; the "
                         "standalone facade has no cluster DNS — skipping "
                         "(a real apiserver resolves it)",
                         webhook.get("name"))
                continue
            review = {
                "apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview",
                "request": {
                    "uid": f"{k8s.namespace(obj)}.{k8s.name(obj)}.{operation}",
                    "operation": operation,
                    "object": obj,
                    "oldObject": old,
                },
            }
            try:
                answer = _call(url, review, client_config.get("caBundle"),
                               timeout)
            except (urllib.error.URLError, OSError, ValueError) as exc:
                if fail_open:
                    log.warning("webhook %s unreachable (%s); failurePolicy="
                                "Ignore — admitting", webhook.get("name"), exc)
                    continue
                raise AdmissionWebhookError(
                    f"calling webhook {webhook.get('name')}: {exc}") from exc
            response = (answer or {}).get("response", {}) or {}
            if not response.get("allowed", False):
                status = response.get("status", {}) or {}
                err = InvalidError(status.get(
                    "message", f"denied by webhook {webhook.get('name')}"))
                err.code = status.get("code", 400)
                raise err
            if mutating and response.get("patch"):
                ops = json.loads(base64.b64decode(response["patch"]))
                obj = apply_json_patch(obj, ops)
    return obj
