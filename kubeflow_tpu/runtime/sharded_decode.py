"""Tensor-parallel serving: run decode on a sharded mesh.

A model whose weights exceed one chip's HBM serves by sharding over the
``tp`` axis of a mesh: attention heads and MLP hidden split across chips
(parallel/sharding.py DEFAULT_RULES — ``heads``/``kv_heads``/``mlp``/
``vocab`` → tp), the KV cache inherits the head sharding from the
sharded projections, and XLA inserts the one all-reduce per layer that
tensor parallelism costs (after ``wo`` and ``w_down``). Nothing in
models/decode.py changes: GSPMD propagates the input shardings through
the same jitted ``generate``/``decode_step``/``decode_window`` —
placement is data, not code.

Decode-time note on fsdp: DEFAULT_RULES shard ``embed`` over fsdp,
which is right for training (per-step all-gather amortized over a big
batch) but adds a latency-path gather per token when serving. A serving
mesh should set ``fsdp=1`` (all axes exist, unused ones at size 1 —
parallel/mesh.py MeshConfig.auto(n, tp=n)) so weights shard over tp
only; ``decode_rules()`` exists for meshes that must keep a real fsdp
axis, mapping ``embed`` to None instead.

The reference (a notebook provisioning controller) has no serving path;
this is the TPU workload layer's scale-out serving story (SURVEY §2d:
ICI-collective work happens inside the provisioned containers).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

from ..models.transformer import TransformerConfig, param_logical_specs
from ..parallel.sharding import PartitionRules, param_shardings

DEFAULT_RULES = PartitionRules().rules


def decode_rules() -> PartitionRules:
    """DEFAULT_RULES with ``embed`` replicated: on a mesh that keeps a
    real fsdp axis, fsdp-sharded weights would cost an all-gather on the
    per-token latency path — serving wants them resident."""
    rules = tuple((k, None) if k == "embed" else (k, v)
                  for k, v in DEFAULT_RULES)
    return PartitionRules(rules=rules)


def shard_decode_params(params: dict, mesh: Mesh,
                        config: TransformerConfig,
                        rules: PartitionRules | None = None) -> dict:
    """Place a params pytree onto ``mesh`` with the serving layout.

    Works for the dense and MoE families (specs chosen by config type).
    The returned tree feeds the ordinary ``generate``/``decode_step``/
    ``speculative_generate``/serving engines unchanged — every jitted
    decode function picks the mesh up from its inputs.
    """
    from ..models.moe import MoEConfig, moe_param_logical_specs
    if isinstance(config, MoEConfig):
        specs = moe_param_logical_specs(config)
    else:
        specs = param_logical_specs(config)
    shardings = param_shardings(mesh, specs,
                                rules or decode_rules())
    return jax.device_put(params, shardings)
