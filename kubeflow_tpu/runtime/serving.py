"""Batched generation service: the serving half of the notebook workload.

A provisioned notebook that serves its model needs request batching to keep
the chip busy — single-prompt generate calls leave the MXU mostly idle.
Two engines, one submit/Future API:

- ``BatchedGenerator`` — shape-bucketed: a background scheduler coalesces
  concurrent same-shape requests and runs each batch to completion
  (templated/phased load);
- ``ContinuousBatchedGenerator`` — requests join and leave a RUNNING
  batch at token boundaries, with chunked prefill admission, exact
  prefix caching, cooperative cancellation, and (with a draft model)
  per-tick speculative blocks.

Both optionally speculate (models/speculative.py): same outputs — exact
greedy parity, exact sampled distributions — with the target's weights
read once per accepted block instead of once per token.

TPU-first batching policy:
- requests batch only when their (prompt_len, max_new_tokens) shapes match —
  no padding/masking corrections needed, and XLA's compile cache makes
  repeated shapes free (notebook serving is dominated by templated,
  fixed-shape prompts);
- the batch dimension is padded up to power-of-two buckets (dummy rows,
  outputs discarded), so a shape key compiles at most log2(max_batch)+1
  executables rather than one per distinct batch size;
- per-request temperatures ride one batch as a traced (batch,) vector
  (models/decode.py generate), so greedy and sampled requests coexist in a
  batch without recompiling;
- the scheduler waits at most ``max_wait_s`` for the batch to fill — a
  latency/throughput knob, not a correctness one.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from concurrent.futures import CancelledError, Future
from dataclasses import dataclass, field
from functools import partial

from ..utils import sanitizer

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.decode import generate


@dataclass
class GenerateRequest:
    prompt: np.ndarray                # (prompt_len,) int32 token ids
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0                    # 0 disables the k-cut
    top_p: float = 1.0                # 1.0 disables the nucleus cut
    future: Future = field(default_factory=Future)
    # streaming: called with each generated token id (int), on the engine
    # thread, BEFORE the future resolves — must be cheap and non-blocking
    # (hand the id to a queue; never do IO here)
    on_token: object | None = None
    # cooperative cancellation (client disconnect): the engine frees the
    # slot at the next token boundary and fails the future with
    # CancelledError — set via the engine's cancel(), not directly
    cancelled: threading.Event = field(default_factory=threading.Event)

    @property
    def shape_key(self) -> tuple:
        return (len(self.prompt), self.max_new_tokens)


class BatchedGenerator:
    """Coalesce concurrent generate requests into shape-matched batches.

    ``submit`` returns a Future resolving to the (max_new_tokens,) int32
    generated ids; ``generate_sync`` blocks for the result.

    With ``draft_params``/``draft_config`` set, un-warped batches (no
    top-k/top-p) run speculative decoding — same outputs, target weights
    read once per accepted block; ``spec_batches``/``spec_accepted``/
    ``spec_drafted`` expose the acceptance dynamics.
    """

    def __init__(self, params, config, *, max_batch: int = 8,
                 max_wait_s: float = 0.01, seed: int = 0,
                 quantize: bool = False, draft_params=None,
                 draft_config=None, spec_k: int = 4,
                 spec_exact_only: bool = True):
        if quantize:
            # int8 weight-only serving: decode is HBM-bound, so halving
            # weight bytes is 1.25-1.4x tokens/s on v5e and a 4x smaller
            # weight footprint (models/quant.py); ~3% logits error,
            # sampling-grade
            from ..models.quant import quantize_params
            params = quantize_params(params)
        self.params = params
        self.config = config
        # speculative serving: batches whose requests use no top-k/top-p
        # warp run draft-propose/verify-once (models/speculative.py) —
        # same outputs (exact greedy parity / exact sampling distribution),
        # target weights read once per accepted block. Warped or
        # near-max_seq_len batches fall back to plain generate.
        if (draft_params is None) != (draft_config is None):
            raise ValueError("draft_params and draft_config must be "
                             "provided together")
        if draft_params is not None and spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        self.draft = (draft_params, draft_config) \
            if draft_params is not None else None
        self.spec_k = spec_k
        # generate() auto-dispatches to the Pallas flash-decode kernel on
        # TPU at max_seq_len >= 2048, while the speculative verify window
        # is the einsum path — two kernels whose last-bit rounding can
        # flip a near-tie greedy argmax. spec_exact_only (default) falls
        # back to plain generate in that regime so the byte-identical
        # contract holds everywhere it is promised; opting out trades
        # last-bit greedy divergence for speculation on long caches
        # (sampled requests' distributional guarantee is unaffected).
        self.spec_exact_only = spec_exact_only
        self.spec_batches = 0
        self.spec_accepted = 0
        self.spec_drafted = 0
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self._queue: queue.Queue = queue.Queue()
        # shape-mismatched requests parked in arrival order: the next cycle
        # starts from this deque's head, so minority shapes cannot starve
        # behind a sustained stream of newer majority-shape arrivals
        self._pending: collections.deque = collections.deque()
        self._key = jax.random.key(seed)
        self._closed = False
        self._lifecycle = sanitizer.tracked_lock(  # submit/close atomicity
            "serving.lifecycle", order=sanitizer.ORDER_CONTROLLER)
        self.batch_sizes: collections.deque = collections.deque(maxlen=1024)
        self.batches_total = 0
        self.requests_total = 0
        self._thread = threading.Thread(target=self._scheduler, daemon=True,
                                        name="kubeflow-tpu-serving")
        self._thread.start()

    # ----------------------------------------------------------------- API
    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0) -> Future:
        req = GenerateRequest(np.asarray(prompt, np.int32), max_new_tokens,
                              temperature, top_k, top_p)
        with self._lifecycle:
            if self._closed:
                raise RuntimeError("generator is closed")
            self._queue.put(req)
        return req.future

    def generate_sync(self, prompt, max_new_tokens: int,
                      temperature: float = 0.0, *, top_k: int = 0,
                      top_p: float = 1.0, timeout: float = 120.0):
        # keyword-only knobs: a legacy positional `timeout` argument must
        # fail loudly, not silently become top_k
        return self.submit(prompt, max_new_tokens, temperature, top_k,
                           top_p).result(timeout=timeout)

    def close(self) -> None:
        with self._lifecycle:
            if self._closed:
                return
            self._closed = True
            self._queue.put(None)  # sentinel AFTER the last possible submit
        self._thread.join(timeout=10)

    def __enter__(self) -> "BatchedGenerator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ scheduler
    def _take_batch(self) -> list[GenerateRequest] | None:
        """Oldest request first (parked pending before the live queue), then
        gather shape-matched peers until max_batch or a monotonic
        ``max_wait_s`` deadline. Mismatches park in arrival order. Returns
        None on the close sentinel."""
        if self._pending:
            first = self._pending.popleft()
        else:
            first = self._queue.get()
            if first is None:
                return None
        batch = [first]
        # same-shape requests already parked join immediately (FIFO scan)
        for req in list(self._pending):
            if len(batch) >= self.max_batch:
                break
            if req.shape_key == first.shape_key:
                self._pending.remove(req)
                batch.append(req)
        deadline = time.monotonic() + self.max_wait_s
        while len(batch) < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                req = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if req is None:
                self._queue.put(None)  # re-arm the sentinel for next cycle
                break
            if req.shape_key == first.shape_key:
                batch.append(req)
            else:
                self._pending.append(req)
        return batch

    def _scheduler(self) -> None:
        while True:  # pump: scheduler; sentinel batch=None breaks via return
            batch = self._take_batch()
            if batch is None:
                # drain: fail any stragglers so callers don't hang. close()
                # enqueues the sentinel under the lifecycle lock AFTER the
                # last possible submit, so everything is visible here.
                stragglers = list(self._pending)
                self._pending.clear()
                while True:  # bounded: drains queue until Empty
                    try:
                        req = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if req is not None:
                        stragglers.append(req)
                for req in stragglers:
                    req.future.set_exception(RuntimeError("generator closed"))
                return
            try:
                self._run_batch(batch)
            except BaseException as exc:  # noqa: BLE001 — deliver per-request
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(exc)

    @staticmethod
    def _bucket_size(n: int) -> int:
        """Smallest power of two >= n: pads the batch dimension to a few
        bucket sizes so XLA compiles one executable per (shape_key, bucket)
        instead of one per distinct batch size 1..max_batch — without this,
        variable load causes multi-second compile stalls on every new size."""
        size = 1
        while size < n:
            size *= 2
        return size

    def _run_batch(self, batch: list[GenerateRequest]) -> None:
        self.batch_sizes.append(len(batch))
        self.batches_total += 1
        self.requests_total += len(batch)
        rows = [r.prompt for r in batch]
        temps_list = [r.temperature for r in batch]
        top_ks = [r.top_k for r in batch]
        top_ps = [r.top_p for r in batch]
        # never exceed the operator's cap: max_batch bounds device memory
        pad = min(self._bucket_size(len(batch)), self.max_batch) - len(batch)
        if pad:
            rows.extend([rows[0]] * pad)       # dummy rows, outputs discarded
            temps_list.extend([0.0] * pad)
            top_ks.extend([0] * pad)
            top_ps.extend([1.0] * pad)
        prompts = jnp.asarray(np.stack(rows))
        temps = jnp.asarray(temps_list, jnp.float32)
        self._key, sub = jax.random.split(self._key)
        max_new = batch[0].max_new_tokens
        from ..models.decode import uses_flash_decode
        use_spec = (
            self.draft is not None
            and all(k <= 0 for k in top_ks)        # spec has no k/p warps
            and all(p >= 1.0 for p in top_ps)
            and not (self.spec_exact_only and uses_flash_decode(self.config))
            and prompts.shape[1] + max_new + self.spec_k
            <= min(self.config.max_seq_len, self.draft[1].max_seq_len))
        if use_spec:
            from ..models.speculative import speculative_generate
            out, stats = speculative_generate(
                self.params, self.draft[0], prompts, self.config,
                self.draft[1], max_new, k=self.spec_k, temperature=temps,
                key=sub)
            self.spec_batches += 1
            # per-row stats: count only the real rows, not the
            # power-of-two padding dummies
            n_real = len(batch)
            self.spec_accepted += int(stats.accepted[:n_real].sum())
            self.spec_drafted += int(stats.drafted[:n_real].sum())
        else:
            out = generate(self.params, prompts, self.config, max_new,
                           temperature=temps, key=sub,
                           top_k=jnp.asarray(top_ks, jnp.int32),
                           top_p=jnp.asarray(top_ps, jnp.float32))
        out = np.asarray(out)
        for i, req in enumerate(batch):
            req.future.set_result(out[i])


# ===================================================== continuous batching
@dataclass
class _Slot:
    """Host-side bookkeeping for one engine row."""
    req: GenerateRequest | None = None
    target: int = 0          # tokens to emit for the current request
    prefilling: bool = False  # admission in progress; row not active yet


@dataclass
class _Admission:
    """Chunked-prefill progress for one slot: the prompt consumed
    ``chunk`` tokens per engine iteration into a private single-row cache,
    spliced into the engine state when complete. In speculative mode the
    DRAFT model prefills the same prompt into its own row cache with an
    independent cursor (prefix-cache hits can advance the two at
    different rates)."""
    req: GenerateRequest
    padded: np.ndarray       # (1, n_chunks * chunk) pad-extended prompt
    real_len: int
    row_cache: dict
    consumed: int = 0
    last_logits: object = None   # (1, V) at the last REAL position so far
    d_row_cache: dict | None = None
    d_consumed: int = 0


class ContinuousBatchedGenerator:
    """Continuous-batching serving engine: requests join and leave a
    RUNNING batch at token boundaries instead of waiting for a bucket to
    drain (``BatchedGenerator`` runs each batch to completion —
    fine for bench loops, wrong for a serving stack whose arrivals are
    Poisson, not phased).

    Engine design (TPU-first):
    - a fixed pool of ``n_slots`` rows shares ONE KV cache and ONE
      compiled decode step; per-row positions drive the cache writes and
      causal masks (models/decode.decode_step with vector ``pos``), so
      rows at different depths coexist in a step;
    - admission is CHUNKED: the prompt streams through a private
      single-row cache ``prefill_chunk`` tokens per engine iteration
      (models/decode.decode_window), interleaved with decode ticks, then
      splices into the engine state in one aliased update. In-flight
      decodes stall at most one chunk's forward per tick instead of the
      whole prompt's, and XLA compiles one executable per chunk size +
      one splice — not one per distinct prompt length;
    - full prompt chunks are PREFIX-CACHED (templated notebook prompts
      share long system/context prefixes): each fully-real chunk's K/V
      rows are stored under the hash of the ENTIRE prefix through that
      chunk, and a new admission skips every leading chunk whose prefix
      hash hits — LRU-bounded by ``prefix_cache_chunks`` entries, exact
      by construction (a hash covers all tokens that influenced the
      rows). The final (possibly partial) chunk always computes fresh so
      the splice has real last-token logits;
    - generated ids accumulate in a device-side (slots, cap) buffer;
      the host reads a row back only at completion. The per-sync host
      traffic is ONE packed (n_steps, 4, slots) int32 readback (n_out /
      done / sampled ids / emit mask fused in _steps_jit) — a single
      round-trip per ``steps_per_sync`` tokens. With the default
      ``steps_per_sync=1`` every token boundary reaches the host (lowest
      streaming/admission latency); raising it runs that many decode
      steps per dispatch via ``lax.scan``, the first-order throughput
      lever when scheduler↔device latency is ~ms (the axon tunnel) —
      the loop drops back to single-step whenever a request is admitting
      or queued, bounding the admission cost of batching to at most one
      in-flight scan (a submit landing mid-dispatch waits ≤ S steps);
    - free slots run the step as masked dummy rows (static shapes; the
      idle-row compute is the price of never recompiling).

    ``submit`` returns a Future resolving to the (max_new_tokens,) ids.
    Passing ``on_token`` streams each sampled id to the caller at the token
    boundary it was generated on — the ids already ride the per-step packed
    readback, so streaming adds no extra device traffic.
    """

    supports_streaming = True

    def __init__(self, params, config, *, n_slots: int = 8,
                 max_new_cap: int | None = None, seed: int = 0,
                 quantize: bool = False, kv_quant: bool = False,
                 eos_id: int | None = None, pad_id: int = 0,
                 prefill_chunk: int = 256, prefix_cache_chunks: int = 64,
                 steps_per_sync: int = 1,
                 draft_params=None, draft_config=None, spec_k: int = 4,
                 spec_exact_only: bool = True):
        if quantize:
            from ..models.quant import quantize_params
            params = quantize_params(params)
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, "
                             f"got {prefill_chunk}")
        if prefix_cache_chunks < 0:
            raise ValueError(f"prefix_cache_chunks must be >= 0, "
                             f"got {prefix_cache_chunks}")
        if steps_per_sync < 1:
            raise ValueError(f"steps_per_sync must be >= 1, "
                             f"got {steps_per_sync}")
        if steps_per_sync > 1 and draft_params is not None:
            # the speculative tick is already a multi-token block per
            # host sync; stacking the two schedulers would multiply
            # admission latency for no modeled gain
            raise ValueError("steps_per_sync > 1 is not supported "
                             "together with a draft model")
        self.steps_per_sync = steps_per_sync
        # continuous speculation: every tick runs a k-token draft block +
        # ONE verify window for all rows (models/speculative.py
        # propose_and_verify), rows advancing 1..k+1 tokens at their own
        # acceptance rate while admission/collection stay per-token-
        # boundary. Same outputs as the plain engine (greedy exact,
        # sampled exactly target-distributed); top-k/top-p warps are
        # rejected at submit in this mode.
        if (draft_params is None) != (draft_config is None):
            raise ValueError("draft_params and draft_config must be "
                             "provided together")
        if draft_params is not None:
            from ..models.decode import uses_flash_decode
            if spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
            if spec_exact_only and uses_flash_decode(config):
                raise ValueError(
                    "speculative verification runs the einsum window "
                    "while this config's plain decode would use the "
                    "flash kernel; last-bit kernel divergence can flip "
                    "a greedy near-tie — pass spec_exact_only=False to "
                    "accept that, or use the non-speculative engine")
        self.draft = (draft_params, draft_config) \
            if draft_params is not None else None
        self.spec_k = spec_k
        self.params = params
        self.config = config
        self.n_slots = n_slots
        self.cap = max_new_cap or config.max_seq_len
        self.eos_id = eos_id
        self.pad_id = pad_id
        self.kv_quant = kv_quant
        self.prefill_chunk = prefill_chunk
        # prefix cache: full-prefix hash → that chunk's (L, 1, C, ...) K/V
        # rows on device; OrderedDict insertion order is the LRU order
        self.prefix_cache_chunks = prefix_cache_chunks
        self._prefix_cache: collections.OrderedDict = \
            collections.OrderedDict()
        self._queue: queue.Queue = queue.Queue()
        self._slots = [_Slot() for _ in range(n_slots)]
        self._admitting: dict[int, _Admission] = {}
        self._key = jax.random.key(seed)
        self._closed = False
        self._lifecycle = sanitizer.tracked_lock(
            "serving.lifecycle", order=sanitizer.ORDER_CONTROLLER)
        # metrics: the serving-test observable — how many requests were
        # admitted while other rows were mid-generation
        # requests_total counts SUBMISSIONS (like BatchedGenerator's) —
        # it is also the serving-activity signal the culler's prober
        # reads from /healthz (controllers/culling.py)
        self.requests_total = 0
        self.admitted_total = 0
        self.admitted_while_running = 0
        self.steps_total = 0
        self.prefill_chunks_total = 0
        self.prefix_cache_hits_total = 0   # chunks SKIPPED via the cache
        self.cancelled_total = 0
        self.spec_ticks = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        self._state = self._fresh_state()
        self._dstate = None
        if self.draft is not None:
            from ..models.decode import init_kv_cache
            self._dstate = {"cache": init_kv_cache(self.draft[1], n_slots)}
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="kubeflow-tpu-cbatch")
        self._thread.start()

    def _fresh_state(self) -> dict:
        """A zeroed engine state — built at construction and again after a
        donated splice fails at execution (donation invalidated the old
        buffers, so the only honest recovery is failing the batch and
        re-arming from scratch)."""
        from ..models.decode import init_kv_cache
        n_slots, config = self.n_slots, self.config
        return {
            "cache": init_kv_cache(config, n_slots,
                                   kv_quant=self.kv_quant),
            "logits": jnp.zeros((n_slots, config.vocab_size), jnp.float32),
            "pos": jnp.zeros((n_slots,), jnp.int32),
            "active": jnp.zeros((n_slots,), bool),
            "done": jnp.zeros((n_slots,), bool),
            "out": jnp.zeros((n_slots, self.cap), jnp.int32),
            "n_out": jnp.zeros((n_slots,), jnp.int32),
            "temp": jnp.zeros((n_slots,), jnp.float32),
            "top_k": jnp.zeros((n_slots,), jnp.int32),
            "top_p": jnp.ones((n_slots,), jnp.float32),
            # speculative mode only: the newest emitted-not-yet-consumed
            # token per row, its position, and the row's token target
            "last": jnp.zeros((n_slots,), jnp.int32),
            "lpos": jnp.zeros((n_slots,), jnp.int32),
            "target": jnp.zeros((n_slots,), jnp.int32),
        }

    # ----------------------------------------------------------------- API
    def submit(self, prompt, max_new_tokens: int, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0, *,
               on_token=None) -> Future:
        if max_new_tokens > self.cap:
            raise ValueError(f"max_new_tokens {max_new_tokens} exceeds "
                             f"engine cap {self.cap}")
        req = GenerateRequest(np.asarray(prompt, np.int32), max_new_tokens,
                              temperature, top_k, top_p,
                              on_token=on_token)
        if len(req.prompt) == 0:
            raise ValueError("prompt must be non-empty")
        if len(req.prompt) + max_new_tokens > self.config.max_seq_len:
            raise ValueError("prompt + max_new_tokens exceeds max_seq_len")
        if self.draft is not None:
            if top_k > 0 or top_p < 1.0:
                raise ValueError("the speculative engine has no "
                                 "top-k/top-p warps (both distributions "
                                 "would need the warp before the ratio "
                                 "test); use the plain engine")
            # the verify window may overhang the frontier by up to k
            # rejected rows before they are overwritten
            limit = min(self.config.max_seq_len,
                        self.draft[1].max_seq_len)
            if len(req.prompt) + max_new_tokens + self.spec_k > limit:
                raise ValueError(
                    f"prompt + max_new_tokens + spec_k exceeds "
                    f"max_seq_len {limit}")
        req.future._kubeflow_tpu_request = req   # cancel() handle
        with self._lifecycle:
            if self._closed:
                raise RuntimeError("generator is closed")
            self.requests_total += 1
            self._queue.put(req)
        return req.future

    def cancel(self, future: Future) -> bool:
        """Request cooperative cancellation of a submitted generation (a
        disconnected streaming client, an abandoned request): the engine
        frees the slot at the next token boundary — queued or admitting
        requests never run — and the future fails with CancelledError.
        Returns False for futures this engine did not issue or that have
        already resolved."""
        req = getattr(future, "_kubeflow_tpu_request", None)
        if req is None or future.done():
            return False
        req.cancelled.set()
        return True

    def generate_sync(self, prompt, max_new_tokens: int,
                      temperature: float = 0.0, *, top_k: int = 0,
                      top_p: float = 1.0, timeout: float = 120.0):
        return self.submit(prompt, max_new_tokens, temperature, top_k,
                           top_p).result(timeout=timeout)

    def close(self) -> None:
        with self._lifecycle:
            if self._closed:
                return
            self._closed = True
            self._queue.put(None)
        self._thread.join(timeout=30)

    def __enter__(self) -> "ContinuousBatchedGenerator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------- jitted kernels
    @staticmethod
    @partial(jax.jit, static_argnames=("config",), donate_argnums=(1,))
    def _chunk_jit(params, row_cache, chunk, start, last_idx, config):
        """Consume one prompt chunk into a private (L, 1, S, ...) row
        cache (models/decode.decode_window with B=1). ``last_idx`` is the
        in-chunk index of the last REAL token (traced: C-1 for full
        chunks, the prompt tail's offset in the final one — padding
        beyond it writes masked-off garbage the decode frontier later
        overwrites) — its logits carry forward so the final chunk hands
        the splice the prompt's next-token distribution without a
        separate pass. One compile per chunk length, shared by every
        prompt."""
        from ..models.decode import decode_window
        logits, row_cache = decode_window(params, row_cache, chunk,
                                          start, config)
        picked = jnp.take_along_axis(
            logits, last_idx[None, None, None], axis=1)[:, 0]  # (1, V)
        return row_cache, picked

    @staticmethod
    @partial(jax.jit, static_argnames=("chunk",))
    def _extract_chunk_jit(row_cache, start, chunk):
        """Copy rows [start, start+chunk) out of a (L, 1, S, ...) row
        cache — the device-resident value stored in the prefix cache."""
        out = {}
        for name, buf in row_cache.items():
            starts = (jnp.int32(0), jnp.int32(0),
                      jnp.asarray(start, jnp.int32)) + \
                (jnp.int32(0),) * (buf.ndim - 3)
            sizes = (buf.shape[0], 1, chunk) + buf.shape[3:]
            out[name] = lax.dynamic_slice(buf, starts, sizes)
        return out

    @staticmethod
    @partial(jax.jit, donate_argnums=(0,))
    def _insert_chunk_jit(row_cache, delta, start):
        """Write a cached chunk's rows into a fresh row cache at
        ``start`` (donated: the admission's cache updates in place)."""
        out = {}
        for name, buf in row_cache.items():
            starts = (jnp.int32(0), jnp.int32(0),
                      jnp.asarray(start, jnp.int32)) + \
                (jnp.int32(0),) * (buf.ndim - 3)
            out[name] = lax.dynamic_update_slice(buf, delta[name], starts)
        return out

    @staticmethod
    @partial(jax.jit, donate_argnums=(0, 1))
    def _splice_jit(state, row_cache, last_logits, slot, real_len,
                    temp, top_k, top_p, target):
        """Install a completed admission: splice the row cache into
        ``slot``'s row of the engine cache and arm the row. One compile
        total — chunking already erased the prompt-length shape. The old
        engine state and the consumed row cache are donated (the caller
        overwrites/discards both), so XLA aliases the update in place
        instead of copying the whole (L, n_slots, S, ...) cache per
        admission."""
        slot32 = jnp.asarray(slot, jnp.int32)
        cache = dict(state["cache"])
        for name, buf in row_cache.items():
            # (L, 1, S, ...) row → engine (L, n_slots, S, ...) at [:, slot]
            cache[name] = lax.dynamic_update_slice(
                state["cache"][name], buf,
                (jnp.int32(0), slot32) + (jnp.int32(0),) * (buf.ndim - 2))
        return {
            **state,
            "cache": cache,
            "logits": state["logits"].at[slot32].set(last_logits[0]),
            "pos": state["pos"].at[slot32].set(
                jnp.asarray(real_len, jnp.int32)),
            "active": state["active"].at[slot32].set(True),
            "done": state["done"].at[slot32].set(False),
            "n_out": state["n_out"].at[slot32].set(0),
            "out": state["out"].at[slot32].set(0),
            "temp": state["temp"].at[slot32].set(temp),
            "top_k": state["top_k"].at[slot32].set(top_k),
            "top_p": state["top_p"].at[slot32].set(top_p),
            # per-row token budget: the multi-step tick freezes a row on
            # device the step it fills its budget (host collection still
            # happens at the sync boundary)
            "target": state["target"].at[slot32].set(
                jnp.asarray(target, jnp.int32)),
        }

    @staticmethod
    @partial(jax.jit, static_argnames=("eos_id", "pad_id"),
             donate_argnums=(0, 1, 2, 3))
    def _spec_splice_jit(state, dstate, row_cache, d_row_cache,
                        last_logits, slot, real_len, target, temp, key,
                        eos_id, pad_id):
        """Speculative-mode install: splice BOTH models' row caches and
        arm the row with its first token sampled from the prompt's
        next-token logits (the spec loop consumes `last` rather than
        carrying logits — models/speculative.py's `first` seeding)."""
        slot32 = jnp.asarray(slot, jnp.int32)

        def splice(buf_state, rows):
            cache = dict(buf_state["cache"])
            for name, buf in rows.items():
                cache[name] = lax.dynamic_update_slice(
                    buf_state["cache"][name], buf,
                    (jnp.int32(0), slot32) + (jnp.int32(0),) *
                    (buf.ndim - 2))
            return {**buf_state, "cache": cache}

        from ..models.speculative import _scaled_probs
        dstate = splice(dstate, d_row_cache)
        temp32 = jnp.float32(temp)
        greedy = jnp.argmax(last_logits[0]).astype(jnp.int32)
        probs = _scaled_probs(last_logits[0], temp32)
        drawn = jax.random.categorical(
            key, jnp.log(probs + 1e-30)).astype(jnp.int32)
        first = jnp.where(temp32 > 0.0, drawn, greedy)
        done0 = jnp.asarray(False) if eos_id is None else first == eos_id
        state = splice(state, row_cache)
        return {
            **state,
            "pos": state["pos"].at[slot32].set(
                jnp.asarray(real_len, jnp.int32)),
            "active": state["active"].at[slot32].set(True),
            "done": state["done"].at[slot32].set(done0),
            "n_out": state["n_out"].at[slot32].set(1),
            "out": state["out"].at[slot32].set(0).at[slot32, 0].set(first),
            "temp": state["temp"].at[slot32].set(temp32),
            "target": state["target"].at[slot32].set(
                jnp.asarray(target, jnp.int32)),
            "last": state["last"].at[slot32].set(first),
            "lpos": state["lpos"].at[slot32].set(
                jnp.asarray(real_len, jnp.int32)),
        }, dstate, first

    @staticmethod
    @partial(jax.jit, static_argnames=("config", "draft_config", "k",
                                       "eos_id", "pad_id"),
             donate_argnums=(2, 3))
    def _spec_tick_jit(params, draft_params, state, dstate, key, config,
                       draft_config, k, eos_id, pad_id):
        """One speculative engine tick: ONE draft block + ONE verify
        window for every row (models/speculative.propose_and_verify),
        each alive row emitting 1..k+1 tokens at its own acceptance rate.
        The packed host buffer is (slots, k+5) int32 —
        [n_out, done, emit_len, n_acc, emit_0..emit_k] per row — one
        readback per tick like the plain engine's."""
        from ..models.speculative import propose_and_verify
        n_slots = state["last"].shape[0]
        alive = state["active"] & ~state["done"] & \
            (state["n_out"] < state["target"])
        t_cache, d_cache, drafts, n_acc, tail = propose_and_verify(
            params, draft_params, state["cache"], dstate["cache"],
            state["last"], state["lpos"], state["temp"], key,
            config, draft_config, k)

        j = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
        emit = jnp.where(j < n_acc[:, None],
                         jnp.pad(drafts, ((0, 0), (0, 1))), tail[:, None])
        # clamp to the row's remaining budget: a block may complete the
        # request mid-window; tokens past the target are never emitted
        emit_len = jnp.where(
            alive, jnp.minimum(n_acc + 1,
                               state["target"] - state["n_out"]), 0)
        if eos_id is not None:
            is_eos = (emit == eos_id) & (j < emit_len[:, None])
            any_eos = jnp.any(is_eos, axis=1)
            # the block ENDS at its first EOS: nothing after it is
            # written or streamed (the SSE contract says token events
            # stop at EOS; the collect path pads the result tail)
            first_eos = jnp.argmax(is_eos, axis=1).astype(jnp.int32)
            emit_len = jnp.where(any_eos,
                                 jnp.minimum(emit_len, first_eos + 1),
                                 emit_len)
            done = state["done"] | any_eos
        else:
            done = state["done"]
        idx = jnp.where(j < emit_len[:, None],
                        state["n_out"][:, None] + j,
                        jnp.int32(state["out"].shape[1] + 1))
        out = state["out"].at[jnp.arange(n_slots)[:, None], idx].set(
            emit, mode="drop")
        n_out = state["n_out"] + emit_len
        moved = emit_len > 0
        last = jnp.where(moved,
                         jnp.take_along_axis(
                             emit, jnp.maximum(emit_len - 1, 0)[:, None],
                             axis=1)[:, 0],
                         state["last"])
        lpos = state["lpos"] + emit_len
        flags = jnp.concatenate([
            n_out[:, None], done.astype(jnp.int32)[:, None],
            emit_len[:, None],
            jnp.where(alive, n_acc, 0)[:, None], emit], axis=1)
        new_state = {**state, "cache": t_cache, "done": done, "out": out,
                     "n_out": n_out, "last": last, "lpos": lpos}
        return new_state, {**dstate, "cache": d_cache}, flags

    @staticmethod
    @partial(jax.jit,
             static_argnames=("config", "eos_id", "pad_id", "n_steps"))
    def _steps_jit(params, state, key, config, eos_id, pad_id,
                   n_steps=1):
        """``n_steps`` engine ticks in ONE dispatch + ONE readback.

        Per step, a row EMITS iff it is armed, not EOS-done, and under
        its token budget — a row finishing mid-scan freezes on device
        (pad token, carried logits, frozen pos) until the host collects
        it at the sync boundary. With ``n_steps=1`` this is exactly the
        classic tick (collection frees finished rows at the same sync,
        so every occupied row emits). With ``n_steps>1`` the host pays
        one round-trip per n_steps tokens — the first-order lever when
        the scheduler↔device latency is ~ms (the axon tunnel) or the
        host loop is slow relative to a decode step.

        The packed flags buffer is (n_steps, 4, slots) int32 —
        [n_out, done, token, emitted] per step — one readback total;
        ``emitted`` tells the streaming path which tokens are real
        without any per-row host state."""
        from ..models.decode import decode_step, sample_token

        def body(state, key):
            emit = state["active"] & ~state["done"] & \
                (state["n_out"] < state["target"])
            token = sample_token(state["logits"], key, state["temp"],
                                 state["top_k"], state["top_p"])
            token = jnp.where(emit, token, jnp.int32(pad_id))
            rows = jnp.arange(token.shape[0])
            out = state["out"].at[rows, state["n_out"]].set(
                jnp.where(emit, token,
                          state["out"][rows, state["n_out"]]))
            n_out = state["n_out"] + emit.astype(jnp.int32)
            done = state["done"]
            if eos_id is not None:
                done = done | (emit & (token == eos_id))
            logits, cache = decode_step(params, state["cache"], token,
                                        state["pos"], config)
            # frozen/inactive rows keep their carried logits; their cache
            # writes land at their frozen pos but are never read (the row
            # is re-spliced before its slot serves again)
            logits = jnp.where(emit[:, None], logits, state["logits"])
            pos = state["pos"] + emit.astype(jnp.int32)
            flags = jnp.stack([n_out, done.astype(jnp.int32), token,
                               emit.astype(jnp.int32)])
            return ({**state, "cache": cache, "logits": logits,
                     "pos": pos, "done": done, "out": out,
                     "n_out": n_out}, flags)

        state, flags = lax.scan(body, state,
                                jax.random.split(key, n_steps))
        return state, flags

    # -------------------------------------------------------------- engine
    def _free_slots(self) -> list[int]:
        return [i for i, s in enumerate(self._slots) if s.req is None]

    def _any_active(self) -> bool:
        return any(s.req is not None and not s.prefilling
                   for s in self._slots)

    def _prefix_key(self, prompt: np.ndarray, upto: int,
                    model: str = "t") -> tuple:
        # keyed per model: the speculative draft's chunk rows live in the
        # same LRU under a "d" tag (its K/V differ from the target's)
        import hashlib
        return (model, upto, hashlib.sha1(prompt[:upto].tobytes()).digest())

    def _cacheable_chunks(self, real_len: int) -> int:
        """How many leading chunks of a prompt are prefix-cacheable:
        fully-real AND not the final chunk (the final chunk always
        computes fresh so the splice has genuine last-token logits)."""
        C = self.prefill_chunk
        n_chunks = max(1, -(-real_len // C))
        return min(real_len // C, n_chunks - 1)

    def _begin_admission(self, req: GenerateRequest, slot: int) -> None:
        """Stage a chunked admission: private row cache + pad-extended
        prompt; leading chunks whose full-prefix hash is cached splice in
        directly; _advance_admissions consumes the rest chunk-at-a-time."""
        from ..models.decode import init_kv_cache
        C = self.prefill_chunk
        real_len = len(req.prompt)
        n_chunks = max(1, -(-real_len // C))
        padded = np.full((1, n_chunks * C), self.pad_id, np.int32)
        padded[0, :real_len] = req.prompt
        adm = _Admission(
            req=req, padded=padded, real_len=real_len,
            row_cache=init_kv_cache(self.config, 1, kv_quant=self.kv_quant))
        if self.draft is not None:
            adm.d_row_cache = init_kv_cache(self.draft[1], 1)

        def take_hits(row_cache, model: str) -> tuple:
            consumed = 0
            if self.prefix_cache_chunks:
                for c in range(self._cacheable_chunks(real_len)):
                    key = self._prefix_key(req.prompt, (c + 1) * C, model)
                    delta = self._prefix_cache.get(key)
                    if delta is None:
                        break
                    self._prefix_cache.move_to_end(key)  # LRU refresh
                    row_cache = self._insert_chunk_jit(
                        row_cache, delta, jnp.int32(c * C))
                    consumed += C
                    self.prefix_cache_hits_total += 1
            return row_cache, consumed
        adm.row_cache, adm.consumed = take_hits(adm.row_cache, "t")
        if adm.d_row_cache is not None:
            adm.d_row_cache, adm.d_consumed = take_hits(adm.d_row_cache,
                                                        "d")
        self._admitting[slot] = adm
        self._slots[slot] = _Slot(req=req, target=req.max_new_tokens,
                                  prefilling=True)

    def _advance_admissions(self) -> None:
        """One prompt chunk per admitting slot, then (when a prompt
        completes) the splice that arms its row. Interleaved with decode
        ticks by the loop, so an in-flight decode stalls at most one
        chunk's forward per tick instead of a whole long prompt's."""
        C = self.prefill_chunk
        for slot, adm in list(self._admitting.items()):
            req = adm.req
            if req.cancelled.is_set():
                del self._admitting[slot]
                self._slots[slot] = _Slot()
                if not req.future.done():
                    req.future.set_exception(CancelledError())
                self.cancelled_total += 1
                continue
            width = adm.padded.shape[1]

            def consume(model_params, row_cache, config, start, model):
                """One chunk through one model, prefix-cached."""
                chunk = jnp.asarray(adm.padded[:, start:start + C])
                last_idx = jnp.asarray(
                    min(adm.real_len - 1 - start, C - 1), jnp.int32)
                row_cache, logits = self._chunk_jit(
                    model_params, row_cache, chunk, jnp.int32(start),
                    last_idx, config)
                self.prefill_chunks_total += 1
                if self.prefix_cache_chunks and \
                        start // C < self._cacheable_chunks(adm.real_len):
                    try:
                        key = self._prefix_key(req.prompt, start + C,
                                               model)
                        self._prefix_cache[key] = self._extract_chunk_jit(
                            row_cache, jnp.int32(start), chunk=C)
                        self._prefix_cache.move_to_end(key)
                        while len(self._prefix_cache) > \
                                self.prefix_cache_chunks:
                            self._prefix_cache.popitem(last=False)
                    except Exception:  # noqa: BLE001 — caching is an
                        # optimization: an extract failure (e.g. HBM
                        # pressure allocating the entry) must not fail a
                        # request whose prefill already succeeded
                        pass
                return row_cache, logits

            try:
                if adm.consumed < width:
                    adm.row_cache, adm.last_logits = consume(
                        self.params, adm.row_cache, self.config,
                        adm.consumed, "t")
                    adm.consumed += C
                if adm.d_row_cache is not None and adm.d_consumed < width:
                    adm.d_row_cache, _ = consume(
                        self.draft[0], adm.d_row_cache, self.draft[1],
                        adm.d_consumed, "d")
                    adm.d_consumed += C
                if adm.consumed < width or (
                        adm.d_row_cache is not None
                        and adm.d_consumed < width):
                    continue
            except BaseException as exc:  # noqa: BLE001 — fail THIS
                # request; other admissions and the running batch continue
                # (the chunk donated only the admission's private cache)
                del self._admitting[slot]
                self._slots[slot] = _Slot()
                if not req.future.done():
                    req.future.set_exception(exc)
                continue
            try:
                if self.draft is None:
                    self._state = self._splice_jit(
                        self._state, adm.row_cache, adm.last_logits,
                        slot, adm.real_len, jnp.float32(req.temperature),
                        jnp.int32(req.top_k), jnp.float32(req.top_p),
                        jnp.int32(req.max_new_tokens))
                else:
                    self._key, sub = jax.random.split(self._key)
                    self._state, self._dstate, first = \
                        self._spec_splice_jit(
                            self._state, self._dstate, adm.row_cache,
                            adm.d_row_cache, adm.last_logits, slot,
                            adm.real_len, req.max_new_tokens,
                            jnp.float32(req.temperature), sub,
                            self.eos_id, self.pad_id)
                    # the first token is an EMITTED token: stream it
                    if req.on_token is not None:
                        try:
                            req.on_token(int(first))
                        except Exception:  # noqa: BLE001
                            req.on_token = None
            except BaseException as exc:  # noqa: BLE001 — the splice
                # DONATES the engine state. A trace/compile-time failure
                # happens before donation (buffers intact → contain to
                # this request); an execution-time failure invalidated
                # them, so the only honest recovery is failing every
                # in-flight request and re-arming from a fresh state.
                state_intact = not any(
                    getattr(leaf, "is_deleted", lambda: False)()
                    for leaf in jax.tree.leaves((self._state,
                                                 self._dstate)))
                del self._admitting[slot]
                self._slots[slot] = _Slot()
                if not req.future.done():
                    req.future.set_exception(exc)
                if not state_intact:
                    self._fail_all_and_rearm(exc)
                    return
                continue
            del self._admitting[slot]
            self._slots[slot].prefilling = False
            self.admitted_total += 1
            if sum(s.req is not None and not s.prefilling
                   for s in self._slots) > 1:
                self.admitted_while_running += 1

    def _fail_all_and_rearm(self, exc: BaseException) -> None:
        """Donation invalidated the engine buffers: fail every in-flight
        request honestly and rebuild both models' states from zero (the
        engine keeps serving)."""
        for i, s in enumerate(self._slots):
            if s.req is not None and not s.req.future.done():
                s.req.future.set_exception(exc)
            self._slots[i] = _Slot()
        self._admitting.clear()
        self._state = self._fresh_state()
        if self.draft is not None:
            from ..models.decode import init_kv_cache
            self._dstate = {"cache": init_kv_cache(self.draft[1],
                                                   self.n_slots)}

    def _emit_tokens(self, ids: np.ndarray,
                     emitted: np.ndarray) -> None:
        """Deliver one step's sampled ids (already on host via the packed
        flags readback) to streaming requests. A raising callback loses
        its own stream, never the engine loop. ``emitted`` is the
        device's per-row emit mask for this step — under multi-step
        scheduling a row frozen mid-scan (EOS/budget) samples only pad
        filler afterwards, which must not reach the stream."""
        for i, slot in enumerate(self._slots):
            if emitted[i] and slot.req is not None \
                    and not slot.prefilling \
                    and slot.req.on_token is not None:
                try:
                    slot.req.on_token(int(ids[i]))
                except Exception:  # noqa: BLE001
                    slot.req.on_token = None

    def _emit_spec_tokens(self, host: np.ndarray) -> None:
        """Spec-tick streaming: each row emitted 0..k+1 tokens this tick
        — deliver the burst in order (the flags layout carries the emit
        block inline, so no extra readback)."""
        k1 = self.spec_k + 1
        for i, slot in enumerate(self._slots):
            if slot.req is None or slot.prefilling \
                    or slot.req.on_token is None:
                continue
            for t in host[i, 4:4 + min(int(host[i, 2]), k1)]:
                try:
                    slot.req.on_token(int(t))
                except Exception:  # noqa: BLE001
                    slot.req.on_token = None
                    break

    def _collect_finished(self, n_out: np.ndarray,
                          done: np.ndarray) -> None:
        deactivate = []
        for i, slot in enumerate(self._slots):
            if slot.req is None or slot.prefilling:
                continue
            if slot.req.cancelled.is_set():
                if not slot.req.future.done():
                    slot.req.future.set_exception(CancelledError())
                self._slots[i] = _Slot()
                deactivate.append(i)
                self.cancelled_total += 1
                continue
            if n_out[i] >= slot.target or done[i]:
                ids = np.asarray(self._state["out"][i, :slot.target])
                if n_out[i] < slot.target:  # EOS'd early: pad the tail
                    ids = ids.copy()
                    ids[int(n_out[i]):] = self.pad_id
                slot.req.future.set_result(ids.astype(np.int32))
                self._slots[i] = _Slot()
                deactivate.append(i)
        if deactivate:
            active = self._state["active"].at[
                jnp.asarray(deactivate, jnp.int32)].set(False)
            self._state = {**self._state, "active": active}

    def _loop(self) -> None:
        draining = False
        while True:  # pump: decode loop; exits when draining and slots idle
            # stage as many arrivals as there are free slots; block for
            # work only when fully idle (nothing decoding, nothing
            # admitting)
            block = (not draining and not self._any_active()
                     and not self._admitting)
            while not draining:
                free = self._free_slots()
                if not free:
                    break
                try:
                    req = self._queue.get(block=block, timeout=None)
                except queue.Empty:
                    break
                block = False
                if req is None:
                    # close(): finish what's running and what's already
                    # admitting (those requests were accepted), admit
                    # nothing new
                    draining = True
                    break
                if req.cancelled.is_set():  # cancelled while queued
                    if not req.future.done():
                        req.future.set_exception(CancelledError())
                    self.cancelled_total += 1
                    continue
                try:
                    self._begin_admission(req, free[0])
                except BaseException as exc:  # noqa: BLE001
                    if not req.future.done():
                        req.future.set_exception(exc)
            # one prompt chunk per admitting slot per iteration,
            # interleaved with the decode tick below
            self._advance_admissions()
            if not self._any_active():
                if draining and not self._admitting:
                    self._shutdown()
                    return
                continue
            try:
                self._key, sub = jax.random.split(self._key)
                if self.draft is None:
                    # multi-step scheduling: amortize the host round-trip
                    # over n_steps tokens — but drop to single-step while
                    # anything is admitting or queued, so batching never
                    # costs admission latency
                    steps = self.steps_per_sync
                    if steps > 1 and (self._admitting
                                      or not self._queue.empty()):
                        steps = 1
                    self._state, flags = self._steps_jit(
                        self.params, self._state, sub, self.config,
                        self.eos_id, self.pad_id, n_steps=steps)
                    self.steps_total += steps
                    # ONE host sync for all `steps` ticks: the packed
                    # (steps, 4, slots) buffer
                    host = np.asarray(flags)
                    # stream BEFORE collection so every token is delivered
                    # before the request's future resolves
                    for s in range(host.shape[0]):
                        self._emit_tokens(host[s, 2], host[s, 3] != 0)
                    self._collect_finished(host[-1, 0], host[-1, 1] != 0)
                else:
                    self._state, self._dstate, flags = self._spec_tick_jit(
                        self.params, self.draft[0], self._state,
                        self._dstate, sub, self.config, self.draft[1],
                        self.spec_k, self.eos_id, self.pad_id)
                    self.steps_total += 1
                    self.spec_ticks += 1
                    # ONE host sync: (slots, k+5) —
                    # [n_out, done, emit_len, n_acc, emit_0..emit_k]
                    host = np.asarray(flags)
                    moved = host[:, 2] > 0
                    self.spec_drafted += int(moved.sum()) * self.spec_k
                    self.spec_accepted += int(host[moved, 3].sum())
                    self._emit_spec_tokens(host)
                    self._collect_finished(host[:, 0], host[:, 1] != 0)
            except BaseException as exc:  # noqa: BLE001 — fail the batch.
                # The spec tick donates the states: rebuild when the
                # buffers were actually invalidated.
                intact = not any(
                    getattr(leaf, "is_deleted", lambda: False)()
                    for leaf in jax.tree.leaves((self._state,
                                                 self._dstate)))
                if not intact:
                    self._fail_all_and_rearm(exc)
                else:
                    for i, slot in enumerate(self._slots):
                        if slot.req is not None and \
                                not slot.req.future.done():
                            slot.req.future.set_exception(exc)
                        self._slots[i] = _Slot()
                    self._admitting.clear()
                    self._state = {**self._state,
                                   "active": jnp.zeros((self.n_slots,),
                                                       bool)}

    def _shutdown(self) -> None:
        stragglers = [s.req for s in self._slots if s.req is not None]
        while True:  # bounded: drains queue until Empty
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                stragglers.append(req)
        for req in stragglers:
            if not req.future.done():
                req.future.set_exception(RuntimeError("generator closed"))
