"""Batched generation service: the serving half of the notebook workload.

A provisioned notebook that serves its model needs request batching to keep
the chip busy — single-prompt generate calls leave the MXU mostly idle. The
``BatchedGenerator`` runs a background scheduler thread that coalesces
concurrent requests into batches and answers each caller through a Future.

TPU-first batching policy:
- requests batch only when their (prompt_len, max_new_tokens) shapes match —
  no padding/masking corrections needed, and XLA's compile cache makes
  repeated shapes free (notebook serving is dominated by templated,
  fixed-shape prompts);
- the batch dimension is padded up to power-of-two buckets (dummy rows,
  outputs discarded), so a shape key compiles at most log2(max_batch)+1
  executables rather than one per distinct batch size;
- per-request temperatures ride one batch as a traced (batch,) vector
  (models/decode.py generate), so greedy and sampled requests coexist in a
  batch without recompiling;
- the scheduler waits at most ``max_wait_s`` for the batch to fill — a
  latency/throughput knob, not a correctness one.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..models.decode import generate


@dataclass
class GenerateRequest:
    prompt: np.ndarray                # (prompt_len,) int32 token ids
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0                    # 0 disables the k-cut
    top_p: float = 1.0                # 1.0 disables the nucleus cut
    future: Future = field(default_factory=Future)

    @property
    def shape_key(self) -> tuple:
        return (len(self.prompt), self.max_new_tokens)


class BatchedGenerator:
    """Coalesce concurrent generate requests into shape-matched batches.

    ``submit`` returns a Future resolving to the (max_new_tokens,) int32
    generated ids; ``generate_sync`` blocks for the result.
    """

    def __init__(self, params, config, *, max_batch: int = 8,
                 max_wait_s: float = 0.01, seed: int = 0,
                 quantize: bool = False):
        if quantize:
            # int8 weight-only serving: decode is HBM-bound, so halving
            # weight bytes is 1.25-1.4x tokens/s on v5e and a 4x smaller
            # weight footprint (models/quant.py); ~3% logits error,
            # sampling-grade
            from ..models.quant import quantize_params
            params = quantize_params(params)
        self.params = params
        self.config = config
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self._queue: queue.Queue = queue.Queue()
        # shape-mismatched requests parked in arrival order: the next cycle
        # starts from this deque's head, so minority shapes cannot starve
        # behind a sustained stream of newer majority-shape arrivals
        self._pending: collections.deque = collections.deque()
        self._key = jax.random.key(seed)
        self._closed = False
        self._lifecycle = threading.Lock()  # submit/close atomicity
        self.batch_sizes: collections.deque = collections.deque(maxlen=1024)
        self.batches_total = 0
        self.requests_total = 0
        self._thread = threading.Thread(target=self._scheduler, daemon=True,
                                        name="kubeflow-tpu-serving")
        self._thread.start()

    # ----------------------------------------------------------------- API
    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0) -> Future:
        req = GenerateRequest(np.asarray(prompt, np.int32), max_new_tokens,
                              temperature, top_k, top_p)
        with self._lifecycle:
            if self._closed:
                raise RuntimeError("generator is closed")
            self._queue.put(req)
        return req.future

    def generate_sync(self, prompt, max_new_tokens: int,
                      temperature: float = 0.0, *, top_k: int = 0,
                      top_p: float = 1.0, timeout: float = 120.0):
        # keyword-only knobs: a legacy positional `timeout` argument must
        # fail loudly, not silently become top_k
        return self.submit(prompt, max_new_tokens, temperature, top_k,
                           top_p).result(timeout=timeout)

    def close(self) -> None:
        with self._lifecycle:
            if self._closed:
                return
            self._closed = True
            self._queue.put(None)  # sentinel AFTER the last possible submit
        self._thread.join(timeout=10)

    def __enter__(self) -> "BatchedGenerator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ scheduler
    def _take_batch(self) -> list[GenerateRequest] | None:
        """Oldest request first (parked pending before the live queue), then
        gather shape-matched peers until max_batch or a monotonic
        ``max_wait_s`` deadline. Mismatches park in arrival order. Returns
        None on the close sentinel."""
        if self._pending:
            first = self._pending.popleft()
        else:
            first = self._queue.get()
            if first is None:
                return None
        batch = [first]
        # same-shape requests already parked join immediately (FIFO scan)
        for req in list(self._pending):
            if len(batch) >= self.max_batch:
                break
            if req.shape_key == first.shape_key:
                self._pending.remove(req)
                batch.append(req)
        deadline = time.monotonic() + self.max_wait_s
        while len(batch) < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                req = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if req is None:
                self._queue.put(None)  # re-arm the sentinel for next cycle
                break
            if req.shape_key == first.shape_key:
                batch.append(req)
            else:
                self._pending.append(req)
        return batch

    def _scheduler(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                # drain: fail any stragglers so callers don't hang. close()
                # enqueues the sentinel under the lifecycle lock AFTER the
                # last possible submit, so everything is visible here.
                stragglers = list(self._pending)
                self._pending.clear()
                while True:
                    try:
                        req = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if req is not None:
                        stragglers.append(req)
                for req in stragglers:
                    req.future.set_exception(RuntimeError("generator closed"))
                return
            try:
                self._run_batch(batch)
            except BaseException as exc:  # noqa: BLE001 — deliver per-request
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(exc)

    @staticmethod
    def _bucket_size(n: int) -> int:
        """Smallest power of two >= n: pads the batch dimension to a few
        bucket sizes so XLA compiles one executable per (shape_key, bucket)
        instead of one per distinct batch size 1..max_batch — without this,
        variable load causes multi-second compile stalls on every new size."""
        size = 1
        while size < n:
            size *= 2
        return size

    def _run_batch(self, batch: list[GenerateRequest]) -> None:
        self.batch_sizes.append(len(batch))
        self.batches_total += 1
        self.requests_total += len(batch)
        rows = [r.prompt for r in batch]
        temps_list = [r.temperature for r in batch]
        top_ks = [r.top_k for r in batch]
        top_ps = [r.top_p for r in batch]
        # never exceed the operator's cap: max_batch bounds device memory
        pad = min(self._bucket_size(len(batch)), self.max_batch) - len(batch)
        if pad:
            rows.extend([rows[0]] * pad)       # dummy rows, outputs discarded
            temps_list.extend([0.0] * pad)
            top_ks.extend([0] * pad)
            top_ps.extend([1.0] * pad)
        prompts = jnp.asarray(np.stack(rows))
        temps = jnp.asarray(temps_list, jnp.float32)
        self._key, sub = jax.random.split(self._key)
        out = generate(self.params, prompts, self.config,
                       batch[0].max_new_tokens, temperature=temps, key=sub,
                       top_k=jnp.asarray(top_ks, jnp.int32),
                       top_p=jnp.asarray(top_ps, jnp.float32))
        out = np.asarray(out)
        for i, req in enumerate(batch):
            req.future.set_result(out[i])
