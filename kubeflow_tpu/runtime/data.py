"""Host-side input pipeline: batch sources + sharded device prefetch.

The reference delegates all data concerns to the user pod (its CRD passes the
PodSpec through untouched, SURVEY §5 "checkpoint/resume" — PVCs carry user
data). The TPU workload layer needs more: training starves unless the next
batch is already on device when the step ends. This module is the host half
of that contract:

- a ``BatchSource`` is any iterable of numpy/host arrays (token/target dicts
  or tuples) — synthetic LM batches are provided for benchmarks;
- ``prefetch_to_device`` wraps a source with a background thread that stages
  the next ``buffer_size`` batches onto the devices via ``jax.device_put``
  with the mesh's batch NamedSharding. Each host transfers only the shards
  its devices own (device_put with a NamedSharding is multi-host aware), and
  the H2D copy of batch N+1 overlaps the device compute of batch N —
  double buffering, the standard TPU input recipe.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from ..parallel.sharding import batch_sharding


def synthetic_lm_batches(batch_size: int, seq_len: int, vocab_size: int,
                         *, n_batches: int | None = None,
                         seed: int = 0) -> Iterator[tuple]:
    """Deterministic synthetic (tokens, targets) stream for benchmarks and
    tests — targets are tokens shifted left (next-token prediction)."""
    rng = np.random.default_rng(seed)
    i = 0
    while n_batches is None or i < n_batches:
        tokens = rng.integers(0, vocab_size, (batch_size, seq_len),
                              dtype=np.int32)
        targets = np.roll(tokens, -1, axis=1)
        targets[:, -1] = -1  # padding target for the shifted-off position
        yield tokens, targets
        i += 1


class _Stop:
    pass


_STOP = _Stop()


def prefetch_to_device(source: Iterable, mesh: Mesh,
                       sharding: NamedSharding | None = None,
                       buffer_size: int = 2) -> Iterator:
    """Iterate ``source`` with batches staged onto ``mesh``'s devices ahead
    of consumption.

    Each yielded element is the source element with every array leaf
    device_put with ``sharding`` (default: the batch sharding over
    (dp, fsdp) × sp). A background thread keeps ``buffer_size`` batches in
    flight; transfers are async (device_put returns immediately), so the
    device DMA of the next batch overlaps the current step's compute.
    Exceptions in the source propagate to the consumer; the thread exits
    when the source ends, the consumer stops iterating, or an error occurs.
    """
    sharding = sharding or batch_sharding(mesh)
    buf: queue.Queue = queue.Queue(maxsize=buffer_size)
    done = threading.Event()

    def put(item) -> bool:
        """Blocking put that gives up when the consumer is gone."""
        while not done.is_set():
            try:
                buf.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer() -> None:
        try:
            for batch in source:
                staged = jax.tree.map(
                    lambda x: jax.device_put(x, sharding), batch)
                if not put(staged):
                    return
        except BaseException as exc:  # noqa: BLE001 — hand to the consumer
            put(exc)
            return
        put(_STOP)

    thread = threading.Thread(target=producer, daemon=True,
                              name="kubeflow-tpu-prefetch")
    thread.start()

    class _PrefetchIterator:
        def __iter__(self):
            return self

        def __next__(self):
            item = buf.get()
            if isinstance(item, _Stop):
                done.set()
                raise StopIteration
            if isinstance(item, BaseException):
                done.set()
                raise item
            return item

        def close(self) -> None:
            done.set()
            # unblock a producer waiting on a full queue
            while True:
                try:
                    buf.get_nowait()
                except queue.Empty:
                    break
            thread.join(timeout=5)

        def __enter__(self):
            return self

        def __exit__(self, *exc) -> None:
            self.close()

    return _PrefetchIterator()
