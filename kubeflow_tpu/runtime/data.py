"""Host-side input pipeline: batch sources + sharded device prefetch.

The reference delegates all data concerns to the user pod (its CRD passes the
PodSpec through untouched, SURVEY §5 "checkpoint/resume" — PVCs carry user
data). The TPU workload layer needs more: training starves unless the next
batch is already on device when the step ends. This module is the host half
of that contract:

- a ``BatchSource`` is any iterable of numpy/host arrays (token/target dicts
  or tuples) — synthetic LM batches are provided for benchmarks;
- ``prefetch_to_device`` wraps a source with a background thread that stages
  the next ``buffer_size`` batches onto the devices via ``jax.device_put``
  with the mesh's batch NamedSharding. Each host transfers only the shards
  its devices own (device_put with a NamedSharding is multi-host aware), and
  the H2D copy of batch N+1 overlaps the device compute of batch N —
  double buffering, the standard TPU input recipe.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from ..parallel.sharding import batch_sharding


def synthetic_lm_batches(batch_size: int, seq_len: int, vocab_size: int,
                         *, n_batches: int | None = None,
                         seed: int = 0) -> Iterator[tuple]:
    """Deterministic synthetic (tokens, targets) stream for benchmarks and
    tests — targets are tokens shifted left (next-token prediction)."""
    rng = np.random.default_rng(seed)
    i = 0
    while n_batches is None or i < n_batches:
        tokens = rng.integers(0, vocab_size, (batch_size, seq_len),
                              dtype=np.int32)
        targets = np.roll(tokens, -1, axis=1)
        targets[:, -1] = -1  # padding target for the shifted-off position
        yield tokens, targets
        i += 1


def write_token_file(path, tokens: np.ndarray) -> None:
    """Write a flat int32 token stream in the loader's on-disk format (raw
    little-endian int32, no header — the memmap-friendly layout every
    packed-corpus pipeline bottoms out in)."""
    np.asarray(tokens, dtype="<i4").ravel().tofile(path)


def token_file_batches(path, batch_size: int, seq_len: int, *,
                       n_epochs: int | None = 1, seed: int | None = 0,
                       doc_sep: int | None = None) -> Iterator[tuple]:
    """Packed-sequence batches from a raw int32 token file via ``np.memmap``
    — the corpus never loads into host RAM, each batch slices seq_len+1
    windows (the +1 provides the shifted target) straight off the mapping.

    - windows are non-overlapping and epoch-shuffled when ``seed`` is set
      (None = sequential order, resumable streaming);
    - ``doc_sep``: positions holding this token id get target -1 (don't
      predict across document boundaries), the separator itself still
      conditions the following text;
    - ``n_epochs=None`` streams forever.
    """
    data = np.memmap(path, dtype="<i4", mode="r")
    window = seq_len + 1
    n_windows = (len(data) - 1) // seq_len
    if n_windows < batch_size:
        raise ValueError(
            f"{path}: {len(data)} tokens give {n_windows} {window}-token "
            f"windows < batch_size {batch_size} — the loader would yield "
            f"nothing (or spin forever with n_epochs=None)")
    rng = np.random.default_rng(seed) if seed is not None else None
    epoch = 0
    while n_epochs is None or epoch < n_epochs:
        order = np.arange(n_windows)
        if rng is not None:
            rng.shuffle(order)
        for start in range(0, n_windows - batch_size + 1, batch_size):
            idx = order[start:start + batch_size]
            rows = np.stack([data[i * seq_len:i * seq_len + window]
                             for i in idx]).astype(np.int32)
            tokens, targets = rows[:, :-1], rows[:, 1:].copy()
            if doc_sep is not None:
                targets[targets == doc_sep] = -1
            yield tokens, targets
        epoch += 1


def tokenize_corpus(text_path, tokenizer, out_path, *,
                    doc_sep: int | None = None,
                    encoding: str = "utf-8") -> int:
    """One-time corpus preparation: tokenize a text file into the raw
    int32 token-file format ``token_file_batches`` memmaps — the bridge
    from "I have a .txt" to the packed training pipeline.

    Documents are blank-line-separated paragraphs; with ``doc_sep`` set,
    that id is written between documents so the loader can mask
    cross-document targets (its ``doc_sep`` argument). Tokenization is
    streamed paragraph-at-a-time — the corpus never loads into RAM —
    and the token count is returned (and is the out file's length / 4).

    ``tokenizer`` is duck-typed like the serving server's: anything with
    ``encode(text, add_special_tokens=False) -> ids``."""
    import itertools

    n = 0
    with open(text_path, encoding=encoding) as fh, \
            open(out_path, "wb") as out:
        for is_blank, lines in itertools.groupby(
                fh, key=lambda ln: not ln.strip()):
            if is_blank:
                continue
            text = " ".join(ln.strip() for ln in lines)
            ids = tokenizer.encode(text, add_special_tokens=False)
            if not ids:
                continue
            arr = np.asarray(ids, dtype="<i4")
            if doc_sep is not None:
                if doc_sep in arr:
                    # a tokenizer that can emit the separator id would
                    # make the loader silently mask REAL mid-document
                    # targets — surface the collision at write time
                    raise ValueError(
                        f"tokenizer emitted doc_sep id {doc_sep} inside "
                        f"a document; pick an id outside its vocab")
                if n:
                    out.write(np.asarray([doc_sep], dtype="<i4").tobytes())
                    n += 1
            out.write(arr.tobytes())
            n += len(arr)
    return n


class _Stop:
    pass


_STOP = _Stop()


def prefetch_to_device(source: Iterable, mesh: Mesh,
                       sharding: NamedSharding | None = None,
                       buffer_size: int = 2) -> Iterator:
    """Iterate ``source`` with batches staged onto ``mesh``'s devices ahead
    of consumption.

    Each yielded element is the source element with every array leaf
    device_put with ``sharding`` (default: the batch sharding over
    (dp, fsdp) × sp). A background thread keeps ``buffer_size`` batches in
    flight; transfers are async (device_put returns immediately), so the
    device DMA of the next batch overlaps the current step's compute.
    Exceptions in the source propagate to the consumer; the thread exits
    when the source ends, the consumer stops iterating, or an error occurs.
    """
    sharding = sharding or batch_sharding(mesh)
    buf: queue.Queue = queue.Queue(maxsize=buffer_size)
    done = threading.Event()

    def put(item) -> bool:
        """Blocking put that gives up when the consumer is gone."""
        while not done.is_set():
            try:
                buf.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer() -> None:
        try:
            for batch in source:
                staged = jax.tree.map(
                    lambda x: jax.device_put(x, sharding), batch)
                if not put(staged):
                    return
        except BaseException as exc:  # noqa: BLE001 — hand to the consumer
            put(exc)
            return
        put(_STOP)

    thread = threading.Thread(target=producer, daemon=True,
                              name="kubeflow-tpu-prefetch")
    thread.start()

    class _PrefetchIterator:
        def __iter__(self):
            return self

        def __next__(self):
            item = buf.get()
            if isinstance(item, _Stop):
                done.set()
                raise StopIteration
            if isinstance(item, BaseException):
                done.set()
                raise item
            return item

        def close(self) -> None:
            done.set()
            # unblock a producer waiting on a full queue
            while True:  # bounded: drains buffer until Empty
                try:
                    buf.get_nowait()
                except queue.Empty:
                    break
            thread.join(timeout=5)

        def __enter__(self):
            return self

        def __exit__(self, *exc) -> None:
            self.close()

    return _PrefetchIterator()
