"""HTTP serving endpoint for the generation engines.

The last mile of the serving story: a provisioned notebook that serves
its model needs a wire protocol, not just a Python API. This is a
stdlib-only JSON-over-HTTP server in the shape such endpoints take:

    POST /v1/generate   {"prompt": [ids...] | "text": "...",
                         "max_new_tokens": N,
                         "temperature": t, "top_k": k, "top_p": p}
                      → {"ids": [ids...], "usage": {prompt_tokens,
                         completion_tokens}, "text": "..." (text mode)}
                        with "stream": true → text/event-stream, one
                        data: {"token": id, "text": delta?} event per
                        token as generated, then
                        data: {"done": true, "ids": [...], "text"?}
                        ("text" requires --tokenizer; stream deltas use
                        incremental detokenization)
    POST /v1/completions  OpenAI-compatible completions (requires
                        --tokenizer): {"prompt": str|[ids], "max_tokens",
                        "temperature", "top_p", "stream"} → the standard
                        text_completion object / SSE chunk stream ending
                        in data: [DONE]
    POST /v1/chat/completions  OpenAI-compatible chat (requires
                        --tokenizer): {"messages": [{role, content}...],
                        "max_tokens"|"max_completion_tokens",
                        "temperature", "top_p", "stream"} → the standard
                        chat.completion object; streaming emits
                        chat.completion.chunk deltas (role on the first,
                        finish_reason on the last) ending in data: [DONE].
                        Messages render through a configurable chat
                        template (--chat-template: role-tags | chatml |
                        tokenizer | a JSON file; runtime/chat_template.py)
    GET  /metrics       Prometheus text exposition (engine counters +
                        HTTP request/latency series)
    GET  /healthz       liveness + engine stats (what the culler's
                        activity probe and the auth sidecar front)
    GET  /v1/models     the serving configuration (model shape, engine,
                        quantization), for client capability discovery

Backed by either generator (``ContinuousBatchedGenerator`` by default —
a serving endpoint lives on continuous batching; ``BatchedGenerator``
for phased/templated load). Requests block on the engine future, so the
HTTP layer is a ThreadingHTTPServer: one thread per in-flight request,
all batching intelligence stays in the engine.

Run standalone (inside the provisioned container):

    python -m kubeflow_tpu.runtime.server --config model.json \
        --checkpoint /ckpt --port 8890 --kv-quant --quantize

The reference has no model code (SURVEY §2d) — this is part of the TPU
workload layer its Jupyter images leave to the user.
"""

from __future__ import annotations

import argparse
import json
import logging
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

log = logging.getLogger("kubeflow_tpu.serving_server")

MAX_BODY_BYTES = 8 << 20  # an 8 MB prompt is a client error, not an OOM


class IncrementalDetokenizer:
    """Streaming detokenization in the standard (HF TextStreamer / vLLM)
    form: decode a trailing id window, withhold output while it ends in
    U+FFFD (a multi-byte character still split across tokens), advance
    the window offsets once the text stabilizes. O(total ids) — the
    window stays small because the prefix offset advances — and correct
    for byte-level BPE, where decode() can REWRITE the tail rather than
    extend it. Genuinely invalid byte sequences (a model emitting bytes,
    not text) surface as U+FFFD once a following token forces the window
    to stabilize — held forever would stall the stream."""

    # ids held back while the window tail is U+FFFD: a real split UTF-8
    # character completes within 3 follow-up bytes, so a window still
    # unstable after this many ids is invalid bytes, not a character —
    # force stabilization (bounds the re-decoded window, keeping feed()
    # O(total ids) even for a model emitting pure garbage)
    MAX_HOLD = 8

    def __init__(self, tokenizer):
        self.tokenizer = tokenizer
        self._ids: list[int] = []
        self._prefix = 0
        self._read = 0

    def feed(self, tok: int) -> str:
        """One generated id in → the text delta now safe to emit."""
        self._ids.append(tok)
        window = self.tokenizer.decode(self._ids[self._prefix:])
        forced = False
        if window.endswith("�"):
            if len(self._ids) - self._read < self.MAX_HOLD:
                return ""                 # held back until complete
            forced = True                 # invalid bytes: stabilize
        prev = self.tokenizer.decode(self._ids[self._prefix:self._read])
        if forced:
            # the emitted tail is replacement chars for invalid bytes,
            # not a character prefix — the NEXT window must not re-decode
            # across it (a later completing byte would re-interpret the
            # boundary and the length-diff would drop text)
            self._prefix = self._read = len(self._ids)
        else:
            self._prefix = self._read
            self._read = len(self._ids)
        return window[len(prev):]

    def flush(self) -> str:
        """Text still held back when the stream ends (generation stopped
        mid-character): emit it so concatenated deltas equal the full
        decode, replacement chars and all."""
        if self._read == len(self._ids):
            return ""
        window = self.tokenizer.decode(self._ids[self._prefix:])
        prev = self.tokenizer.decode(self._ids[self._prefix:self._read])
        self._prefix = self._read = len(self._ids)
        return window[len(prev):]


class ServingServer:
    """HTTP front for a generation engine. ``generator`` is either
    engine class (both expose submit/generate_sync/close)."""

    ENGINE_COUNTERS = (
        "requests_total", "batches_total", "admitted_total",
        "admitted_while_running", "steps_total", "prefill_chunks_total",
        "prefix_cache_hits_total", "cancelled_total", "spec_batches",
        "spec_ticks", "spec_accepted", "spec_drafted")

    def __init__(self, generator, config, *, host: str = "127.0.0.1",
                 port: int = 8890, request_timeout_s: float = 300.0,
                 tokenizer=None, model_name: str | None = None,
                 chat_template=None):
        from ..utils.metrics import MetricsRegistry
        from .chat_template import BUILTIN
        self.generator = generator
        self.config = config
        self.request_timeout_s = request_timeout_s
        # duck-typed: anything with encode(text, add_special_tokens=False)
        # -> ids and decode(ids) -> text (a transformers tokenizer works).
        # With one configured, requests may pass "text" instead of
        # "prompt" ids and responses/stream events carry decoded text.
        self.tokenizer = tokenizer
        self.model_name = model_name or self.MODEL_NAME
        # messages → prompt rendering for /v1/chat/completions; anything
        # with render(messages, add_generation_prompt=) works
        # (runtime/chat_template.py load_template resolves CLI specs)
        self.chat_template = chat_template or BUILTIN["role-tags"]
        self._started_at = int(time.time())
        # Prometheus exposition (GET /metrics): engine counters mirrored at
        # scrape time, plus the HTTP layer's own request/latency series —
        # the serving analog of the controller's metrics endpoint
        self.metrics = MetricsRegistry(include_notebook_metrics=False)
        self._m_http = self.metrics.counter(
            "serving_http_requests_total",
            "HTTP requests by route and status code")
        self._m_lat_sum = self.metrics.counter(
            "serving_generate_seconds_sum",
            "Cumulative wall seconds spent in /v1/generate requests")
        self._m_lat_count = self.metrics.counter(
            "serving_generate_seconds_count",
            "Completed /v1/generate requests")
        engine_metrics = {
            name: self.metrics.gauge(
                f"serving_engine_{name}",
                f"Engine counter {name} (mirrored at scrape)")
            for name in self.ENGINE_COUNTERS if hasattr(generator, name)}

        def mirror_engine() -> None:
            for name, metric in engine_metrics.items():
                metric.set(float(getattr(self.generator, name)))
        self.metrics.on_scrape(mirror_engine)
        server = self

        KNOWN_ROUTES = frozenset(
            {"/healthz", "/v1/models", "/metrics", "/v1/generate",
             "/v1/completions", "/v1/chat/completions"})

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                log.debug("http: " + fmt, *args)

            def _count(self, code: int) -> None:
                # unknown paths collapse to one label bucket: the route
                # label must not be attacker-controlled cardinality (a
                # crawler probing thousands of paths would otherwise leak
                # one permanent series per path)
                route = self.path.split("?")[0]
                if route not in KNOWN_ROUTES:
                    route = "other"
                server._m_http.inc({"route": route, "method": self.command,
                                    "code": str(code)})

            def _json(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                self._count(code)

            def do_GET(self):
                if self.path == "/healthz":
                    self._json(200, server.health())
                elif self.path == "/v1/models":
                    self._json(200, server.model_info())
                elif self.path == "/metrics":
                    body = server.metrics.expose().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    self._count(200)
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path not in ("/v1/generate", "/v1/completions",
                                     "/v1/chat/completions"):
                    self._json(404, {"error": f"no route {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    if length < 0 or length > MAX_BODY_BYTES:
                        # a negative (lying) Content-Length must not reach
                        # rfile.read(-1) — that reads until EOF, unbounded
                        self._json(413, {"error": "invalid request size"})
                        return
                    req = json.loads(self.rfile.read(length))
                    if not isinstance(req, dict):
                        # valid JSON of the wrong shape ([1,2], "x") is a
                        # client error, not an AttributeError 500
                        raise ValueError(
                            "request body must be a JSON object")
                    # oai_mode: None (internal shape) | "completions" |
                    # "chat" — picks the translator, response object, and
                    # stream chunk framing
                    oai_mode = {"/v1/completions": "completions",
                                "/v1/chat/completions": "chat"}.get(
                                    self.path)
                    if oai_mode == "completions":
                        req = server.translate_completions(req)
                    elif oai_mode == "chat":
                        req = server.translate_chat(req)
                    stream = req.get("stream", False)
                    if not isinstance(stream, bool):
                        # '"stream": "false"' is a client bug; guessing a
                        # truthiness here silently switches content types
                        raise ValueError("'stream' must be a boolean")
                    if stream:
                        t0 = time.monotonic()
                        server.stream_generate(req, self,
                                               oai_mode=oai_mode)
                        server._m_lat_sum.inc(by=time.monotonic() - t0)
                        server._m_lat_count.inc()
                        self._count(200)
                        return
                    t0 = time.monotonic()
                    out = server.generate(req)
                    server._m_lat_sum.inc(by=time.monotonic() - t0)
                    server._m_lat_count.inc()
                    if oai_mode == "completions":
                        out = server.to_completions_response(out)
                    elif oai_mode == "chat":
                        out = server.to_chat_response(out)
                    self._json(200, out)
                except (ValueError, KeyError, TypeError) as e:
                    self._json(400, {"error": str(e)})
                except TimeoutError:
                    self._json(504, {"error": "generation timed out"})
                except RuntimeError as e:  # engine closed mid-request
                    self._json(503, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — an engine error
                    # (e.g. XLA OOM) must surface as a JSON 500, not a
                    # dropped connection with a server-side traceback
                    log.exception("generate failed")
                    self._json(500, {"error":
                                     f"{type(e).__name__}: {e}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._started = False
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="kubeflow-tpu-serving-http")

    # ------------------------------------------------------------ lifecycle
    @property
    def port(self) -> int:
        return self._httpd.server_port

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ServingServer":
        self._started = True
        self._thread.start()
        log.info("serving endpoint on %s", self.url)
        return self

    def stop(self) -> None:
        if self._started:
            # shutdown() waits on an event only serve_forever() sets —
            # calling it on a never-started server would block forever
            self._httpd.shutdown()
        self._httpd.server_close()
        self.generator.close()

    def __enter__(self) -> "ServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- handlers
    def _cancel(self, future) -> None:
        """Cooperative cancel for abandoned requests (disconnect/timeout)
        — a no-op on engines without cancellation support."""
        cancel = getattr(self.generator, "cancel", None)
        if cancel is not None:
            cancel(future)

    def _validate(self, req: dict):
        prompt = req.get("prompt")
        text = req.get("text")
        if (prompt is None) == (text is None):
            raise ValueError("provide exactly one of 'prompt' (token ids)"
                             " or 'text'")
        if text is not None:
            if self.tokenizer is None:
                raise ValueError("'text' requires the server to be "
                                 "started with a tokenizer "
                                 "(--tokenizer DIR)")
            if not isinstance(text, str) or not text:
                raise ValueError("'text' must be a non-empty string")
            prompt = list(self.tokenizer.encode(
                text, add_special_tokens=False))
            if not prompt:
                raise ValueError("'text' tokenized to an empty prompt")
            if max(prompt) >= self.config.vocab_size:
                raise ValueError(
                    f"tokenizer produced id {max(prompt)} outside the "
                    f"model vocab ({self.config.vocab_size}) — wrong "
                    f"tokenizer for this model")
        elif not isinstance(prompt, list) or not prompt or \
                not all(isinstance(t, int) for t in prompt):
            raise ValueError("'prompt' must be a non-empty list of "
                             "token ids")
        if not all(0 <= t < self.config.vocab_size for t in prompt):
            # an out-of-range id would hit XLA's clamping gather and
            # return a silently-wrong embedding row, not an error
            raise ValueError(f"prompt ids must be in [0, "
                             f"{self.config.vocab_size})")
        max_new = req.get("max_new_tokens", 64)
        if not isinstance(max_new, int) or max_new < 1:
            raise ValueError("'max_new_tokens' must be a positive integer")
        return (np.asarray(prompt, np.int32), max_new,
                float(req.get("temperature", 0.0)),
                int(req.get("top_k", 0)), float(req.get("top_p", 1.0)),
                text is not None)

    def _live_ids(self, ids) -> list[int]:
        """The generated ids up to (and excluding) the engine's EOS —
        the pad filler after it AND the EOS token's own surface form do
        not belong in client-facing text."""
        ids = [int(t) for t in ids]
        eos = getattr(self.generator, "eos_id", None)
        if eos is not None and eos in ids:
            ids = ids[:ids.index(eos)]
        return ids

    MODEL_NAME = "kubeflow-tpu"

    def _check_openai_common(self, req: dict, route: str,
                             unsupported: tuple) -> None:
        """The checks both OpenAI routes share: tokenizer present (the
        response format is text), model-name match, and loud failure on
        any knob that would CHANGE semantics if silently ignored
        (0/None/empty are the no-op values)."""
        if self.tokenizer is None:
            raise ValueError(f"{route} requires the server to run with "
                             f"--tokenizer (responses are text)")
        # SDKs always send 'model': a mismatch means the client thinks
        # it is talking to a different deployment — refuse rather than
        # silently serve the wrong weights
        want_model = req.get("model")
        if want_model is not None and want_model != self.model_name:
            raise ValueError(f"model {want_model!r} is not served here "
                             f"(this endpoint serves "
                             f"{self.model_name!r})")
        if req.get("n", 1) != 1 or req.get("best_of", 1) != 1:
            raise ValueError("'n'/'best_of' > 1 not supported")
        for knob in unsupported:
            if req.get(knob):
                raise ValueError(f"'{knob}' is not supported")

    def _openai_sampling(self, req: dict, max_default: int = 16) -> dict:
        return {"max_new_tokens": req.get("max_tokens", max_default),
                # OpenAI defaults temperature to 1.0 (ours is greedy 0.0)
                "temperature": float(req.get("temperature", 1.0)),
                "top_p": float(req.get("top_p", 1.0)),
                "stream": req.get("stream", False)}

    def translate_completions(self, req: dict) -> dict:
        """OpenAI `/v1/completions` body → the internal request shape.
        The legacy-but-ubiquitous surface: a completions client switching
        from any OpenAI-compatible server points its base_url here.
        Unsupported knobs fail loudly rather than silently changing
        semantics."""
        self._check_openai_common(
            req, "/v1/completions",
            ("logprobs", "echo", "stop", "suffix", "logit_bias",
             "frequency_penalty", "presence_penalty", "seed",
             # chat-only knob: a confused client mixing surfaces should
             # hear about it, not get silently truncated output
             "max_completion_tokens"))
        prompt = req.get("prompt")
        out = self._openai_sampling(req)
        if isinstance(prompt, str) and prompt:
            out["text"] = prompt
        elif isinstance(prompt, list):
            out["prompt"] = prompt
        else:
            raise ValueError("'prompt' must be a non-empty string or a "
                             "token id list")
        return out

    def translate_chat(self, req: dict) -> dict:
        """OpenAI `/v1/chat/completions` body → the internal request
        shape: ``messages`` render to ONE prompt string through the
        configured chat template (runtime/chat_template.py) with the
        assistant generation cue appended — the default surface modern
        OpenAI SDK clients call (VERDICT r4 ask #4)."""
        self._check_openai_common(
            req, "/v1/chat/completions",
            ("logprobs", "top_logprobs", "stop", "logit_bias",
             "frequency_penalty", "presence_penalty", "seed", "tools",
             "tool_choice", "functions", "function_call",
             "response_format"))
        out = self._openai_sampling(req)
        out["text"] = self.chat_template.render(req.get("messages"),
                                                add_generation_prompt=True)
        if "max_completion_tokens" in req:
            # the chat surface's newer name wins over legacy max_tokens
            out["max_new_tokens"] = req["max_completion_tokens"]
        elif "max_tokens" not in req:
            # chat clients routinely omit the budget (OpenAI's chat
            # surface generates to the limit by default) — the legacy
            # completions default of 16 would silently truncate, and a
            # fixed large default would 400 on short-context models; do
            # what OpenAI does: generate to the context limit (capped at
            # 256 so an omitted budget can't monopolize engine slots)
            n_prompt = len(self.tokenizer.encode(
                out["text"], add_special_tokens=False))
            out["max_new_tokens"] = max(
                1, min(256, self.config.max_seq_len - n_prompt))
        return out

    def _envelope(self, prefix: str, obj: str) -> dict:
        import uuid
        return {"id": prefix + uuid.uuid4().hex[:24], "object": obj,
                "created": int(time.time()), "model": self.model_name}

    def _completions_envelope(self) -> dict:
        return self._envelope("cmpl-", "text_completion")

    def _finish_and_usage(self, usage: dict, ids: list) -> tuple:
        """(finish_reason, OpenAI usage) — ONE definition for the
        streaming and non-streaming completions responses. "stop" means
        the engine's EOS appeared among the generated ids (including on
        the very last slot, where a budget-based check would mislabel it
        "length")."""
        eos = getattr(self.generator, "eos_id", None)
        finish = "stop" if eos is not None and eos in ids else "length"
        return finish, {**usage,
                        "total_tokens": usage["prompt_tokens"]
                        + usage["completion_tokens"]}

    def to_completions_response(self, out: dict) -> dict:
        """Internal generate() result → OpenAI text_completion shape."""
        finish, usage = self._finish_and_usage(out["usage"], out["ids"])
        text = out.get("text")
        if text is None:
            text = self.tokenizer.decode(self._live_ids(out["ids"]))
        return {**self._completions_envelope(),
                "choices": [{"text": text, "index": 0, "logprobs": None,
                             "finish_reason": finish}],
                "usage": usage}

    def to_chat_response(self, out: dict) -> dict:
        """Internal generate() result → OpenAI chat.completion shape."""
        finish, usage = self._finish_and_usage(out["usage"], out["ids"])
        text = out.get("text")
        if text is None:
            text = self.tokenizer.decode(self._live_ids(out["ids"]))
        return {**self._envelope("chatcmpl-", "chat.completion"),
                "choices": [{"index": 0,
                             "message": {"role": "assistant",
                                         "content": text},
                             "logprobs": None,
                             "finish_reason": finish}],
                "usage": usage}

    def _usage(self, prompt, ids) -> dict:
        """Accounting for the response: completion_tokens counts every
        GENERATED token including a terminating EOS (matching the stream's
        n_tokens), not the pad filler after it."""
        ids = [int(t) for t in ids]
        eos = getattr(self.generator, "eos_id", None)
        n = ids.index(eos) + 1 if eos is not None and eos in ids \
            else len(ids)
        return {"prompt_tokens": int(prompt.shape[0]),
                "completion_tokens": n}

    def generate(self, req: dict) -> dict:
        prompt, max_new, temp, top_k, top_p, was_text = self._validate(req)
        future = self.generator.submit(prompt, max_new, temp, top_k=top_k,
                                       top_p=top_p)
        try:
            ids = future.result(timeout=self.request_timeout_s)
        except TimeoutError:
            # the 504 goes to the client; the engine must not keep the
            # slot decoding for a response nobody will read
            self._cancel(future)
            raise
        out = {"ids": [int(t) for t in ids],
               "usage": self._usage(prompt, ids)}
        if was_text:
            out["text"] = self.tokenizer.decode(self._live_ids(ids))
        return out

    def stream_generate(self, req: dict, handler,
                        oai_mode: str | None = None) -> None:
        """``"stream": true``: per-token SSE emission. The engine already
        works at token boundaries (ContinuousBatchedGenerator admits and
        samples per step); this hands each sampled id straight to the wire
        instead of parking it until completion — time-to-first-token
        becomes prefill + one step, not the full generation.

        Wire format: ``Content-Type: text/event-stream``, one
        ``data: {"token": id}`` event per token actually SAMPLED — when
        the engine stops at an EOS id, the token events end there — then a
        final ``data: {"done": true, "n_tokens": n, "ids": [...],
        "usage": {...}}`` event
        whose ``ids`` is the engine's result exactly as the non-streaming
        response would return it (padded to max_new_tokens after an early
        EOS) and ``n_tokens`` counts the token events that preceded it.
        In text mode, a multi-byte character still split across tokens at
        the end of generation is flushed as ONE extra token-less
        ``data: {"text": ...}`` event between the last token event and the
        done event — clients keying on ``"token"`` must treat a frame
        without it as text-only continuation, not a protocol error.
        The response is delimited by connection close (no
        Content-Length).

        ``oai_mode`` swaps the frame shapes: ``"completions"`` emits
        text_completion SSE chunks, ``"chat"`` emits chat.completion.chunk
        deltas (``role`` on the first content chunk, ``finish_reason`` +
        ``usage`` on the final empty-delta chunk), both ending with the
        literal ``data: [DONE]`` sentinel."""
        prompt, max_new, temp, top_k, top_p, was_text = self._validate(req)
        if not getattr(self.generator, "supports_streaming", False):
            raise ValueError(
                f"engine {type(self.generator).__name__} does not "
                f"support streaming; use the continuous engine")
        q: queue.Queue = queue.Queue()
        future = self.generator.submit(prompt, max_new, temp, top_k=top_k,
                                       top_p=top_p, on_token=q.put)

        # text mode: each token event carries the incremental decoded
        # suffix (IncrementalDetokenizer — held back while a multi-byte
        # character is still split across tokens). The OpenAI routes
        # always stream text (their translators guarantee the
        # tokenizer), even for token-array prompts.
        detok = IncrementalDetokenizer(self.tokenizer) \
            if (was_text or oai_mode) else None
        eos = getattr(self.generator, "eos_id", None)

        def token_payload(tok: int) -> dict:
            payload = {"token": tok}
            if detok is not None:
                # the EOS token itself contributes no text (the done
                # event's text excludes its surface form)
                payload["text"] = "" if (eos is not None and tok == eos) \
                    else detok.feed(tok)
            return payload

        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-cache")
        handler.send_header("Connection", "close")
        handler.end_headers()

        def event(payload: dict) -> bool:
            try:
                handler.wfile.write(
                    b"data: " + json.dumps(payload).encode() + b"\n\n")
                handler.wfile.flush()
                return True
            except OSError:
                # client went away: cancel cooperatively so the engine
                # frees the slot at the next token boundary instead of
                # finishing a generation nobody will read
                self._cancel(future)
                return False

        if oai_mode == "chat":
            envelope = self._envelope("chatcmpl-", "chat.completion.chunk")
        elif oai_mode == "completions":
            envelope = self._completions_envelope()
        else:
            envelope = None
        first_chunk = [True]  # chat: "role" rides the first delta only

        def _sentinel() -> bool:
            try:
                handler.wfile.write(b"data: [DONE]\n\n")
                handler.wfile.flush()
                return True
            except OSError:
                return False

        def _content_chunk(text: str) -> dict:
            if oai_mode == "chat":
                delta = {"content": text}
                if first_chunk[0]:
                    delta["role"] = "assistant"
                    first_chunk[0] = False
                choice = {"index": 0, "delta": delta,
                          "logprobs": None, "finish_reason": None}
            else:
                choice = {"text": text, "index": 0, "logprobs": None,
                          "finish_reason": None}
            return {**envelope, "choices": [choice]}

        def _final_chunk(finish: str, usage: dict) -> dict:
            if oai_mode == "chat":
                choice = {"index": 0, "delta": {}, "logprobs": None,
                          "finish_reason": finish}
            else:
                choice = {"text": "", "index": 0, "logprobs": None,
                          "finish_reason": finish}
            return {**envelope, "choices": [choice], "usage": usage}

        def send(payload: dict) -> bool:
            """Wire emission: internal event shape, or the OpenAI chunk
            framing (content deltas; finish_reason on the final chunk;
            the literal [DONE] sentinel) on the /v1/*completions routes."""
            if not oai_mode:
                return event(payload)
            if "error" in payload:
                # OpenAI-SDK-parseable error frame, then the sentinel so
                # stream consumers terminate cleanly
                return event({"error": {"message": str(payload["error"]),
                                        "type": "server_error"}}) \
                    and _sentinel()
            if payload.get("done"):
                finish, usage = self._finish_and_usage(payload["usage"],
                                                       payload["ids"])
                return event(_final_chunk(finish, usage)) and _sentinel()
            return event(_content_chunk(payload.get("text", "")))

        t_end = time.monotonic() + self.request_timeout_s
        n_tokens = 0
        while True:  # bounded: t_end deadline raises/returns within request_timeout_s
            try:
                tok = q.get(timeout=min(0.25, max(0.0, t_end -
                                                  time.monotonic())))
                if not send(token_payload(tok)):
                    return
                n_tokens += 1
                continue
            except queue.Empty:
                pass
            if future.done():
                # drain ids emitted between the last get and completion
                while True:  # bounded: drains queue until Empty
                    try:
                        tok = q.get_nowait()
                    except queue.Empty:
                        break
                    if not send(token_payload(tok)):
                        return
                    n_tokens += 1
                break
            if time.monotonic() >= t_end:
                # free the slot: nobody will read the rest of this
                # generation (same cooperative cancel as a disconnect)
                self._cancel(future)
                send({"error": "generation timed out"})
                return
        try:
            ids = [int(t) for t in future.result(timeout=0)]
            if detok is not None:
                held = detok.flush()
                if held and not send({"text": held}):
                    return   # token-less flush event: mid-character tail
            done = {"done": True, "n_tokens": n_tokens, "ids": ids,
                    "usage": self._usage(prompt, ids)}
            if was_text:
                done["text"] = self.tokenizer.decode(self._live_ids(ids))
            send(done)
        except Exception as e:  # noqa: BLE001 — surface as a final event
            send({"error": f"{type(e).__name__}: {e}"})

    def health(self) -> dict:
        gen = self.generator
        out = {"status": "ok", "engine": type(gen).__name__}
        for attr in self.ENGINE_COUNTERS:
            if hasattr(gen, attr):
                out[attr] = getattr(gen, attr)
        return out

    def model_info(self) -> dict:
        c = self.config
        return {
            # OpenAI list-shape alongside the native fields, so SDK
            # clients pointed at this base_url can enumerate models
            "object": "list",
            "data": [{"id": self.model_name, "object": "model",
                      "created": self._started_at,
                      "owned_by": self.model_name}],
            "engine": type(self.generator).__name__,
            "tokenizer": self.tokenizer is not None,
            "model": {
                "d_model": c.d_model, "n_layers": c.n_layers,
                "n_heads": c.n_heads, "n_kv_heads": c.n_kv_heads,
                "vocab_size": c.vocab_size, "max_seq_len": c.max_seq_len,
            },
        }


# -------------------------------------------------------------- entrypoint
def build_generator(params, config, args, draft=None):
    from .serving import BatchedGenerator, ContinuousBatchedGenerator
    if args.engine == "bucketed":
        if args.kv_quant or args.eos_id >= 0 or \
                getattr(args, "steps_per_sync", 1) > 1:
            # refuse rather than silently ignore: the operator asked for
            # behavior this engine does not implement
            raise SystemExit("--kv-quant/--eos-id/--steps-per-sync "
                             "require --engine continuous")
        kw = {}
        if draft is not None:
            kw = dict(draft_params=draft[0], draft_config=draft[1],
                      spec_k=args.spec_k,
                      spec_exact_only=not getattr(args, "spec_inexact",
                                                  False))
        return BatchedGenerator(params, config, max_batch=args.slots,
                                quantize=args.quantize, **kw)
    kw = {}
    if draft is not None:
        kw = dict(draft_params=draft[0], draft_config=draft[1],
                  spec_k=args.spec_k,
                  spec_exact_only=not getattr(args, "spec_inexact",
                                              False))
    return ContinuousBatchedGenerator(
        params, config, n_slots=args.slots, quantize=args.quantize,
        kv_quant=args.kv_quant,
        steps_per_sync=getattr(args, "steps_per_sync", 1),
        eos_id=args.eos_id if args.eos_id >= 0 else None, **kw)


def main(argv=None) -> int:
    from ..models.transformer import TransformerConfig, init_params

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", required=True,
                    help="JSON file of TransformerConfig fields")
    ap.add_argument("--checkpoint", default=None,
                    help="TrainCheckpointer directory (runtime/"
                         "checkpoint.py layout; latest step's params are "
                         "restored); absent → randomly initialized "
                         "params (dev only)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8890)
    ap.add_argument("--engine", choices=("continuous", "bucketed"),
                    default="continuous")
    ap.add_argument("--slots", type=int, default=8,
                    help="engine slots / max batch")
    ap.add_argument("--quantize", action="store_true",
                    help="int8 weight-only serving")
    ap.add_argument("--kv-quant", action="store_true",
                    help="int8 KV cache (continuous engine)")
    ap.add_argument("--steps-per-sync", type=int, default=1,
                    help="decode steps per host round-trip (continuous "
                         "engine): >1 amortizes scheduler latency at the "
                         "cost of token-burst streaming; admissions "
                         "always drop back to single-step")
    ap.add_argument("--eos-id", type=int, default=-1)
    ap.add_argument("--draft-config", default=None,
                    help="JSON TransformerConfig for a speculative draft "
                         "model (bucketed engine): un-warped batches run "
                         "draft-propose/verify-once with identical "
                         "outputs")
    ap.add_argument("--draft-checkpoint", default=None,
                    help="TrainCheckpointer dir for the draft params; "
                         "absent with --draft-config -> random draft "
                         "(dev only)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft tokens proposed per speculative block")
    ap.add_argument("--spec-inexact", action="store_true",
                    help="allow speculation where plain decode would use "
                         "the flash kernel: the einsum verify window can "
                         "differ in last-bit rounding, so a greedy "
                         "near-tie may flip (sampled requests' "
                         "distribution is unaffected)")
    ap.add_argument("--model-name", default=None,
                    help="model id reported on /v1/models and in "
                         "completions responses (default: kubeflow-tpu)")
    ap.add_argument("--tokenizer", default=None,
                    help="local tokenizer directory (transformers "
                         "AutoTokenizer, local_files_only): enables "
                         "'text' requests and decoded responses")
    ap.add_argument("--chat-template", default=None,
                    help="messages->prompt template for /v1/chat/"
                         "completions: a builtin name (role-tags "
                         "[default], chatml), 'tokenizer' (use the HF "
                         "tokenizer's own apply_chat_template), or a "
                         "path to a JSON file with 'turn' + "
                         "'generation_prompt' fields")
    ap.add_argument("--lora-config", default=None,
                    help="JSON of LoRAConfig fields (rank/alpha/targets):"
                         " merge a finetuned adapter into the base "
                         "weights at startup")
    ap.add_argument("--lora-checkpoint", default=None,
                    help="TrainCheckpointer dir holding the adapters "
                         "(Trainer lora-mode checkpoints); required with "
                         "--lora-config")
    ap.add_argument("--platform", default=None,
                    help="force the jax platform (e.g. 'cpu' for dev "
                         "boxes): applied via jax.config BEFORE backend "
                         "init — a JAX_PLATFORMS env var can be "
                         "re-asserted by the image and is not sufficient")
    args = ap.parse_args(argv)

    with open(args.config) as fh:
        config = TransformerConfig(**json.load(fh))
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    def _serving_abstract(tree):
        # serving restores onto THIS process's device regardless of the
        # training mesh: without explicit target shardings orbax falls
        # back to the sharding file (the SAVED topology) and a checkpoint
        # from a multi-chip trainer fails or misplaces on a dev box
        from .checkpoint import abstract_state
        dev = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        return abstract_state(tree, jax.tree.map(lambda _: dev, tree))

    if args.checkpoint:
        from .checkpoint import TrainCheckpointer
        abstract = _serving_abstract(
            jax.eval_shape(lambda: init_params(jax.random.key(0), config)))
        with TrainCheckpointer(args.checkpoint) as ckpt:
            restored = ckpt.restore_params(abstract)
        if restored is None:
            raise SystemExit(f"no checkpoint found in {args.checkpoint}")
        step, params = restored
        log.info("restored params from step %d", step)
    else:
        log.warning("no --checkpoint: serving randomly initialized params")
        params = init_params(jax.random.key(0), config)

    if (args.lora_config is None) != (args.lora_checkpoint is None):
        raise SystemExit("--lora-config and --lora-checkpoint must be "
                         "provided together")
    if args.lora_config:
        # serve a finetune: restore the adapters and bake them into the
        # base weights — downstream is a plain model (models/lora.py)
        from ..models.lora import (LoRAConfig, init_lora_params,
                                   merge_lora)
        from .checkpoint import TrainCheckpointer
        with open(args.lora_config) as fh:
            lora_cfg = LoRAConfig(**json.load(fh))
        abstract = _serving_abstract(jax.eval_shape(
            lambda: init_lora_params(jax.random.key(0), config, lora_cfg)))
        with TrainCheckpointer(args.lora_checkpoint) as ckpt:
            restored = ckpt.restore_params(abstract)
        if restored is None:
            raise SystemExit(
                f"no adapter checkpoint found in {args.lora_checkpoint}")
        lstep, lora_params = restored
        params = merge_lora(params, lora_params, lora_cfg)
        log.info("merged LoRA adapters from step %d (rank %d, %s)",
                 lstep, lora_cfg.rank, ",".join(lora_cfg.targets))

    draft = None
    if args.draft_checkpoint and not args.draft_config:
        raise SystemExit("--draft-checkpoint requires --draft-config")
    if args.draft_config:
        with open(args.draft_config) as fh:
            draft_config = TransformerConfig(**json.load(fh))
        if args.draft_checkpoint:
            from .checkpoint import TrainCheckpointer
            abstract = _serving_abstract(jax.eval_shape(
                lambda: init_params(jax.random.key(0), draft_config)))
            with TrainCheckpointer(args.draft_checkpoint) as ckpt:
                restored = ckpt.restore_params(abstract)
            if restored is None:
                raise SystemExit(
                    f"no checkpoint found in {args.draft_checkpoint}")
            _, draft_params = restored
        else:
            log.warning("no --draft-checkpoint: random draft (dev only)")
            draft_params = init_params(jax.random.key(1), draft_config)
        draft = (draft_params, draft_config)

    tokenizer = None
    if args.tokenizer:
        from transformers import AutoTokenizer
        tokenizer = AutoTokenizer.from_pretrained(args.tokenizer,
                                                  local_files_only=True)

    from .chat_template import load_template
    try:
        chat_template = load_template(args.chat_template, tokenizer)
    except ValueError as e:
        raise SystemExit(f"--chat-template: {e}")

    server = ServingServer(build_generator(params, config, args, draft),
                           config, host=args.host, port=args.port,
                           tokenizer=tokenizer,
                           model_name=args.model_name,
                           chat_template=chat_template).start()
    log.info("ready on %s", server.url)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    import sys
    logging.basicConfig(level=logging.INFO)
    sys.exit(main())
