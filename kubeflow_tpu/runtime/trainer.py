"""Trainer: the training loop a provisioned notebook runs on its slice.

Composes the pieces the framework provides — sharded train step
(models/train.py, models/moe.py), host input pipeline with device prefetch
(runtime/data.py), sharded checkpoint/resume (runtime/checkpoint.py) — into
the loop the culler interrupts and the resume path restarts. The reference
has no workload code (SURVEY §2d); this is the TPU-native layer its notebook
images leave to the user.

Loop design for TPU throughput:
- one jitted step per iteration, params/opt donated; the host never reads
  the loss inside the loop (``loss.block_until_ready`` only at log points),
  so steps dispatch ahead of the device — the classic async dispatch queue;
- input batches arrive pre-sharded from the prefetch thread;
- checkpoint saves are async (orbax) and ride the save-interval policy;
- on construction the trainer restores the latest checkpoint if one exists:
  a culled slice resumes where it stopped, on whatever mesh it now has.
"""

from __future__ import annotations

import itertools
import logging
import time
from collections import deque
from dataclasses import dataclass, field

import jax
from jax.sharding import Mesh

from ..models import moe as moe_model
from ..models import train as train_lib
from ..models.moe import MoEConfig
from ..models.transformer import (TransformerConfig, init_params,
                                  model_flops_per_token, param_logical_specs)
from ..parallel.sharding import param_shardings
from .checkpoint import TrainCheckpointer, abstract_state
from .data import prefetch_to_device

log = logging.getLogger("kubeflow_tpu.trainer")


@dataclass
class TrainerStats:
    step: int = 0
    last_loss: float | None = None
    tokens_seen: int = 0
    steps_per_sec: float = 0.0
    tokens_per_sec: float = 0.0
    model_tflops_per_sec: float = 0.0
    # (step, loss) / (step, eval loss) at log points — bounded: a
    # week-long elastic run hits log points forever, and an unbounded
    # list is a slow host-memory leak (Trainer's stats_history_cap
    # overrides the maxlen)
    losses: deque = field(default_factory=lambda: deque(maxlen=1000))
    evals: deque = field(default_factory=lambda: deque(maxlen=1000))


class Trainer:
    """Drive sharded training with prefetch, periodic checkpointing, and
    throughput accounting.

    ``config`` may be a dense ``TransformerConfig`` or an ``MoEConfig`` —
    the matching sharded step is selected automatically.
    """

    def __init__(self, mesh: Mesh, config: TransformerConfig,
                 train_config: train_lib.TrainConfig | None = None,
                 checkpoint_dir=None, *, checkpoint_interval: int = 100,
                 max_checkpoints: int = 3, seed: int = 0,
                 profile_dir=None, profile_steps: tuple = (10, 15),
                 lora=None, base_params=None, partition_rules=None,
                 stats_history_cap: int = 1000):
        self.mesh = mesh
        self.config = config
        self.tc = train_config or train_lib.TrainConfig()
        self.is_moe = isinstance(config, MoEConfig)
        # regex partition rules (parallel/partition_rules.py): when set,
        # restore targets are matched from the rules instead of the
        # per-model hand specs, so a checkpoint reshards onto whatever
        # mesh this trainer holds — the elastic resize path. "auto"
        # selects the family table from the config type.
        if partition_rules == "auto":
            from ..parallel.partition_rules import rules_for
            partition_rules = rules_for(config)
        self.partition_rules = partition_rules
        # LoRA finetune mode: self.params are the ADAPTERS (tiny), the
        # frozen base rides every step as a non-donated input; the
        # checkpoint/resume/eval machinery below sees adapters where it
        # would see params — which is the point (a finetune checkpoint is
        # megabytes; eval runs the merged model)
        self.lora = lora
        self._base = None
        if lora is not None:
            from ..models.lora import make_sharded_lora_step
            if self.is_moe:
                raise ValueError("LoRA targets the dense family; MoE "
                                 "adapter routing is not implemented")
            if base_params is None:
                raise ValueError("lora mode requires base_params (the "
                                 "pretrained weights being finetuned)")
            self._base = jax.device_put(
                base_params,
                param_shardings(mesh, param_logical_specs(config)))
            self.init_fn, self._lora_step = make_sharded_lora_step(
                mesh, config, lora, tc=self.tc)
            self.step_fn = lambda p, o, t, tg: self._lora_step(
                self._base, p, o, t, tg)
        elif self.is_moe:
            self.init_fn, self.step_fn = moe_model.make_sharded_moe_train_step(
                mesh, config, tc=self.tc)
        else:
            self.init_fn, self.step_fn = train_lib.make_sharded_train_step(
                mesh, config, tc=self.tc)
        self.stats = TrainerStats(
            losses=deque(maxlen=stats_history_cap),
            evals=deque(maxlen=stats_history_cap))
        self.checkpointer = None
        if checkpoint_dir is not None:
            self.checkpointer = TrainCheckpointer(
                checkpoint_dir, max_to_keep=max_checkpoints,
                save_interval_steps=checkpoint_interval)
        # optional XLA/TPU trace window (the aux-subsystem analog of the
        # reference's OTel webhook spans, SURVEY §5 — but for the workload:
        # view with tensorboard / xprof)
        self.profile_dir = str(profile_dir) if profile_dir else None
        self.profile_steps = profile_steps
        self._profiling = False
        self.params, self.opt_state = self.init_fn(jax.random.key(seed))
        if self.checkpointer is not None:
            self._maybe_resume()

    # ------------------------------------------------------------- resume
    def _restore_targets(self):
        """Abstract (params, opt_state) with THIS mesh's shardings, so a
        checkpoint from a different topology reshards on load."""
        if self.lora is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..models.lora import init_lora_params, lora_logical_specs
            lp_sh = param_shardings(
                self.mesh, lora_logical_specs(self.config, self.lora))
            opt_sh = train_lib.opt_state_shardings(
                train_lib.make_optimizer(self.tc),
                lambda k: init_lora_params(k, self.config, self.lora),
                lp_sh, NamedSharding(self.mesh, P()))
            return (abstract_state(self.params, lp_sh),
                    abstract_state(self.opt_state, opt_sh))
        if self.partition_rules is not None:
            # rules engine: one table shards params AND the optimizer
            # state embedding them (suffix match), so no hand-written
            # opt_state mirror — whatever the step function's state
            # pytree looks like (optax, MasterOptState, ...), matching
            # THIS trainer's live trees yields restore targets with the
            # right structure by construction
            from ..parallel.partition_rules import (match_partition_rules,
                                                    named_shardings)
            p_sh = named_shardings(self.mesh, match_partition_rules(
                self.partition_rules, self.params))
            o_sh = named_shardings(self.mesh, match_partition_rules(
                self.partition_rules, self.opt_state))
            return (abstract_state(self.params, p_sh),
                    abstract_state(self.opt_state, o_sh))
        if self.is_moe:
            specs = moe_model.moe_param_logical_specs(self.config)
            init = lambda k: moe_model.init_moe_params(k, self.config)  # noqa: E731
        else:
            specs = param_logical_specs(self.config)
            init = lambda k: init_params(k, self.config)  # noqa: E731
        from jax.sharding import NamedSharding, PartitionSpec as P
        p_sh = param_shardings(self.mesh, specs)
        opt_sh = train_lib.opt_state_shardings(
            train_lib.make_optimizer(self.tc), init, p_sh,
            NamedSharding(self.mesh, P()))
        if not self.is_moe and self.tc.bf16_params:
            # the dense step wraps the optax state in MasterOptState with
            # the f32 masters sharded like the params; the restore target
            # must mirror that structure or abstract_state's tree.map
            # fails on the mismatch
            opt_sh = train_lib.MasterOptState(inner=opt_sh, master=p_sh)
        return (abstract_state(self.params, p_sh),
                abstract_state(self.opt_state, opt_sh))

    def _maybe_resume(self) -> None:
        abstract_p, abstract_o = self._restore_targets()
        restored = self.checkpointer.restore(abstract_p, abstract_o)
        if restored is None:
            return
        step, self.params, self.opt_state = restored
        self.stats.step = step
        log.info("resumed from checkpoint at step %d", step)

    # --------------------------------------------------------------- loop
    def fit(self, source, *, steps: int, log_every: int = 50,
            prefetch_buffer: int = 2) -> TrainerStats:
        """Train for ``steps`` steps over ``source`` (an iterable of
        (tokens, targets) host batches). Returns the updated stats; call
        again to continue (step count persists)."""
        flops_tok = model_flops_per_token(self.config)
        target = self.stats.step + steps
        t0 = time.perf_counter()
        tokens_t0 = self.stats.tokens_seen
        loss = None
        # bound the draw count BEFORE prefetch: a stateful source reused
        # across fit() calls must not lose the batch the old loop fetched
        # just to notice the step target, nor the buffered ones behind it
        bounded = itertools.islice(iter(source), steps)
        with prefetch_to_device(bounded, self.mesh,
                                buffer_size=prefetch_buffer) as batches:
            for tokens, targets in batches:
                if self.stats.step >= target:
                    break
                self._profile_tick()
                self.params, self.opt_state, loss = self.step_fn(
                    self.params, self.opt_state, tokens, targets)
                self.stats.step += 1
                self.stats.tokens_seen += int(tokens.size)
                if self.checkpointer is not None:
                    self.checkpointer.save(self.stats.step, self.params,
                                           self.opt_state)
                if self.stats.step % log_every == 0 or \
                        self.stats.step == target:
                    # the only host sync point in the loop
                    self.stats.last_loss = float(loss)
                    self.stats.losses.append(
                        (self.stats.step, self.stats.last_loss))
                    dt = time.perf_counter() - t0
                    dtok = self.stats.tokens_seen - tokens_t0
                    if dt > 0:
                        self.stats.tokens_per_sec = dtok / dt
                        self.stats.steps_per_sec = \
                            (self.stats.step - (target - steps)) / dt
                        # 3x forward FLOPs for fwd+bwd, per-device
                        self.stats.model_tflops_per_sec = (
                            3 * flops_tok * dtok / dt / 1e12
                            / max(1, self.mesh.size))
                    log.info("step %d loss %.4f %.0f tok/s",
                             self.stats.step, self.stats.last_loss,
                             self.stats.tokens_per_sec)
        if loss is not None and self.stats.last_loss is None:
            self.stats.last_loss = float(loss)
        return self.stats

    # --------------------------------------------------------------- eval
    def _eval_step(self):
        """Lazily-built jitted eval step: (params, tokens, targets) →
        (loss·n_valid, n_valid) device scalars. The loss dispatch is
        train_lib.build_eval_loss — the SAME pp-aware forward selection
        and fused-CE gating as the training step (kept in one place so
        they cannot drift), with the MoE router aux excluded so
        exp(loss) is a real perplexity for both families."""
        if getattr(self, "_eval_fn", None) is not None:
            return self._eval_fn
        import jax.numpy as jnp

        eval_loss = train_lib.build_eval_loss(self.mesh, self.config,
                                              self.tc)
        lora = self.lora
        if lora is not None:
            from ..models.lora import merge_lora

        @jax.jit
        def eval_jit(base, params, tokens, targets):
            if lora is not None:
                # params are the adapters: evaluate the merged model.
                # base rides as a traced ARGUMENT — a closure capture
                # would bake a full extra copy of the weights into the
                # executable's constants
                params = merge_lora(base, params, lora)
            loss = eval_loss(params, tokens, targets)
            n = jnp.sum(targets >= 0)
            return loss * n, n

        def eval_fn(params, tokens, targets):
            return eval_jit(self._base, params, tokens, targets)
        self._eval_fn = eval_fn
        return eval_fn

    def evaluate(self, source, *, max_batches: int | None = None,
                 prefetch_buffer: int = 2) -> dict:
        """Held-out evaluation: token-weighted mean cross entropy and
        perplexity over ``source`` (an iterable of (tokens, targets) host
        batches; ``max_batches`` bounds a generator). No parameter or
        optimizer state changes — safe mid-training; the result is also
        appended to ``stats.evals`` as (step, loss)."""
        eval_fn = self._eval_step()
        bounded = iter(source) if max_batches is None else \
            itertools.islice(iter(source), max_batches)
        # device-side accumulation: the loop dispatches ahead without a
        # per-batch host sync (the same async-queue discipline as fit());
        # the one readback happens after the last batch
        totals = []
        counts = []
        n_batches = 0
        with prefetch_to_device(bounded, self.mesh,
                                buffer_size=prefetch_buffer) as batches:
            for tokens, targets in batches:
                weighted, n_valid = eval_fn(self.params, tokens, targets)
                totals.append(weighted)
                counts.append(n_valid)
                n_batches += 1
        n_tokens = int(sum(int(c) for c in counts))
        if n_tokens == 0:
            raise ValueError("evaluate() saw no valid tokens")
        mean_loss = float(sum(float(t) for t in totals)) / n_tokens
        result = {"loss": mean_loss,
                  "perplexity": float(jax.numpy.exp(mean_loss)),
                  "batches": n_batches, "tokens": n_tokens,
                  "step": self.stats.step}
        self.stats.evals.append((self.stats.step, mean_loss))
        log.info("eval @ step %d: loss %.4f ppl %.2f (%d tokens)",
                 self.stats.step, mean_loss, result["perplexity"],
                 n_tokens)
        return result

    def _profile_tick(self) -> None:
        """Open/close the jax.profiler trace when the step counter crosses
        the [start, stop) profile window."""
        if self.profile_dir is None:
            return
        start, stop = self.profile_steps
        if not self._profiling and self.stats.step == start:
            jax.profiler.start_trace(self.profile_dir)
            self._profiling = True
        elif self._profiling and self.stats.step >= stop:
            jax.tree.map(lambda x: x.block_until_ready(), self.params)
            jax.profiler.stop_trace()
            self._profiling = False
            log.info("profile trace written to %s", self.profile_dir)

    def merged_params(self):
        """LoRA mode: the base + trained-adapter merged tree — a plain
        servable model for generate/speculation/the engines."""
        if self.lora is None:
            raise ValueError("merged_params() is for lora mode; in full "
                             "training self.params already IS the model")
        from ..models.lora import merge_lora
        return merge_lora(self._base, self.params, self.lora)

    def save(self, *, force: bool = True) -> None:
        """Durably persist the current step (idempotent: a step the interval
        policy already wrote is not re-written)."""
        if self.checkpointer is None:
            return
        if self.stats.step not in self.checkpointer.all_steps():
            self.checkpointer.save(self.stats.step, self.params,
                                   self.opt_state, force=force)
        self.checkpointer.wait()

    def close(self) -> None:
        if self._profiling:
            jax.profiler.stop_trace()
            self._profiling = False
        if self.checkpointer is not None:
            self.checkpointer.wait()
            self.checkpointer.close()

    def __enter__(self) -> "Trainer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
