"""Elastic training: shrink on preemption, grow back on repair — no restart.

Two halves, one handshake:

``ElasticTrainer`` (runtime) wraps :class:`~..runtime.trainer.Trainer` on a
``build_hybrid_mesh`` whose ``dp`` axis spans slices. ``resize(n)`` is the
Podracer move — drain the async dispatch queue, force an orbax save, rebuild
the mesh with the new slice count, and let the trainer's cross-mesh restore
path (regex partition rules → restore targets on the NEW mesh) re-shard
params/opt-state. The step counter and loss curve continue; the only cost is
the drain+save+restore blip.

The controller side (controllers/slicerepair.py) drives WHEN to resize via
the ``tpu.kubeflow.org/elastic-resize`` annotation machine
(Stable → Draining → Resharding → Stable). The trainer-side agent here
answers it: ack Draining once the queue is drained and the checkpoint
durable, perform the resize when the controller advances to Resharding, ack
again, and the controller completes the cycle — the slice is never released
before the runtime has confirmed it no longer needs it.

``SimulatedElasticAgent`` is the chaos-tier stand-in: same protocol thread,
but productive work is a virtual step counter with a deterministic loss
curve, so the elastic-preemption experiment can assert step monotonicity,
loss continuity, and an MFU floor without real devices or wall-clock flake.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

import jax

from ..parallel.mesh import MeshConfig, build_hybrid_mesh
from ..utils import k8s, names
from .trainer import Trainer

log = logging.getLogger("kubeflow_tpu.elastic")

# states the runtime agent writes into ELASTIC_ACK_ANNOTATION (the echo of
# the controller's carrier states, plus the dead-agent latch the controller
# stamps on abort and only a LIVE agent clears)
ACK_DRAINING = "Draining"
ACK_RESHARDING = "Resharding"
ACK_ABORTED = "Aborted"

# virtual-tick cost of one resize in the simulated agent's MFU accounting:
# a drain + forced save + cross-mesh restore is worth about this many lost
# productive steps at chaos scale (deterministic — no wall-clock)
ELASTIC_BLIP_STEPS = 2


class ElasticTrainer:
    """A Trainer that can change its slice count mid-run.

    ``per_slice`` is the intra-slice mesh (fsdp/tp/... over ICI);
    ``n_slices`` multiplies ``dp`` across slices (DCN). ``checkpoint_dir``
    is mandatory — resize IS checkpoint-mediated, there is nothing elastic
    about a trainer that cannot save.

    ``resize`` rebuilds the inner Trainer; construction re-inits params on
    the new mesh and immediately overwrites them from the checkpoint (the
    same resume path a culled slice takes), so correctness never depends on
    in-memory state surviving the mesh swap.
    """

    def __init__(self, per_slice: MeshConfig, n_slices: int, config,
                 train_config=None, checkpoint_dir=None, *, devices=None,
                 resize_events_cap: int = 1000, **trainer_kwargs):
        if checkpoint_dir is None:
            raise ValueError("ElasticTrainer requires checkpoint_dir: "
                             "resize is checkpoint-mediated")
        self.per_slice = per_slice
        self.config = config
        self.train_config = train_config
        self.checkpoint_dir = checkpoint_dir
        self._devices = list(devices) if devices is not None \
            else list(jax.devices())
        self._kwargs = dict(trainer_kwargs)
        self.n_slices = n_slices
        # (old_n, new_n, step, seconds) per completed resize — bounded
        # like TrainerStats' losses/evals (stats_history_cap): a
        # long-lived run under preemption churn must not leak host memory
        # one tuple per shrink/grow cycle, so the deque drops oldest
        self.resize_events: deque = deque(maxlen=resize_events_cap)
        self.trainer = self._build(n_slices)

    def _build(self, n_slices: int) -> Trainer:
        devs = self._devices[: n_slices * self.per_slice.size]
        mesh, _full = build_hybrid_mesh(n_slices, self.per_slice,
                                        devices=devs)
        return Trainer(mesh, self.config, self.train_config,
                       self.checkpoint_dir, partition_rules="auto",
                       **self._kwargs)

    # ------------------------------------------------------------- resize
    def resize(self, n_slices: int) -> None:
        """Drain → save → rebuild mesh → cross-mesh restore → keep going."""
        if n_slices == self.n_slices:
            return
        if n_slices < 1:
            raise ValueError(f"n_slices must be >= 1, got {n_slices}")
        if n_slices * self.per_slice.size > len(self._devices):
            raise ValueError(
                f"{n_slices} slices × {self.per_slice.size} devices/slice "
                f"exceed the {len(self._devices)} available devices")
        t0 = time.perf_counter()
        old = self.trainer
        # drain the async dispatch queue: every in-flight step must land
        # before the snapshot, or the checkpoint would be mid-step
        jax.block_until_ready((old.params, old.opt_state))
        old.save(force=True)
        old_stats = old.stats
        old.close()
        self.trainer = self._build(n_slices)
        st = self.trainer.stats
        if st.step != old_stats.step:
            raise RuntimeError(
                f"elastic restore landed on step {st.step}, expected "
                f"{old_stats.step} — checkpoint continuity broken")
        # history/counters live host-side; carry them across the rebuild
        st.losses.extend(old_stats.losses)
        st.evals.extend(old_stats.evals)
        st.tokens_seen = old_stats.tokens_seen
        st.last_loss = old_stats.last_loss
        dt = time.perf_counter() - t0
        self.resize_events.append((self.n_slices, n_slices, st.step, dt))
        log.info("elastic resize %d → %d slices at step %d (%.2fs)",
                 self.n_slices, n_slices, st.step, dt)
        self.n_slices = n_slices

    def shrink(self) -> None:
        self.resize(self.n_slices - 1)

    def grow(self) -> None:
        self.resize(self.n_slices + 1)

    # ---------------------------------------------------------- delegates
    @property
    def mesh(self):
        return self.trainer.mesh

    @property
    def params(self):
        return self.trainer.params

    @property
    def opt_state(self):
        return self.trainer.opt_state

    @property
    def stats(self):
        return self.trainer.stats

    def fit(self, source, **kw):
        return self.trainer.fit(source, **kw)

    def evaluate(self, source, **kw):
        return self.trainer.evaluate(source, **kw)

    def save(self, **kw):
        return self.trainer.save(**kw)

    def close(self) -> None:
        self.trainer.close()

    def __enter__(self) -> "ElasticTrainer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _ElasticAgentBase:
    """The runtime half of the elastic-resize handshake.

    Polls the Notebook's elastic-resize carrier and answers it:

    - ``Draining``    → :meth:`_on_drain` (stop stepping, durable save),
                        then ack ``Draining``;
    - ``Resharding``  → :meth:`_on_reshard` (rebuild onto the target slice
                        count), then ack ``Resharding`` (the controller
                        stamps the new current-slices count when it
                        completes the cycle);
    - absent (Stable) → :meth:`_on_tick` (productive work), and clear the
                        ``Aborted`` dead-agent latch if the controller left
                        one — only a live agent may clear it, which is
                        exactly what clearing it proves.

    Acks are idempotent (state-compared before writing) so a poll racing a
    controller patch never double-writes.
    """

    def __init__(self, client, namespace: str, name: str, *,
                 poll_s: float = 0.02):
        self.client = client
        self.namespace = namespace
        self.name = name
        self.poll_s = poll_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # hooks -------------------------------------------------------------
    def _on_drain(self) -> None:
        raise NotImplementedError

    def _on_reshard(self, target: int) -> None:
        raise NotImplementedError

    def _on_tick(self) -> None:
        raise NotImplementedError

    # wire --------------------------------------------------------------
    def _patch(self, annotations: dict) -> None:
        self.client.patch("Notebook", self.namespace, self.name,
                          {"metadata": {"annotations": annotations}})

    def poll_once(self) -> None:
        """One handshake turn. Drive this from a thread (:meth:`start`) or
        synchronously between fit() chunks when the resize work must run on
        the caller's thread (real JAX resizes are not thread-safe against a
        concurrently stepping loop)."""
        nb = self.client.get("Notebook", self.namespace, self.name)
        state = k8s.get_annotation(nb, names.ELASTIC_RESIZE_ANNOTATION)
        ack = k8s.get_annotation(nb, names.ELASTIC_ACK_ANNOTATION)
        if state == ACK_DRAINING:
            if ack != ACK_DRAINING:
                self._on_drain()
                self._patch({names.ELASTIC_ACK_ANNOTATION: ACK_DRAINING})
        elif state == ACK_RESHARDING:
            if ack != ACK_RESHARDING:
                target = k8s.get_annotation(
                    nb, names.ELASTIC_TARGET_ANNOTATION)
                if target is not None:
                    self._on_reshard(int(target))
                    # the ack is the agent's ONLY annotation: the
                    # controller stamps current-slices itself when it
                    # completes the cycle (single writer, and the
                    # pre-resize count stays readable until then)
                    self._patch({
                        names.ELASTIC_ACK_ANNOTATION: ACK_RESHARDING,
                    })
        else:
            if ack == ACK_ABORTED:
                self._patch({names.ELASTIC_ACK_ANNOTATION: None})
            self._on_tick()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — agent must outlive races
                log.debug("elastic agent poll failed", exc_info=True)
            self._stop.wait(self.poll_s)

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"elastic-agent-{self.name}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


class ElasticAgent(_ElasticAgentBase):
    """Handshake agent bound to a real :class:`ElasticTrainer`. Drive it
    with :meth:`poll_once` between fit() chunks — the resize must run on
    the training thread."""

    def __init__(self, trainer: ElasticTrainer, client, namespace: str,
                 name: str, **kw):
        super().__init__(client, namespace, name, **kw)
        self.trainer = trainer

    def _on_drain(self) -> None:
        t = self.trainer.trainer
        jax.block_until_ready((t.params, t.opt_state))
        t.save(force=True)

    def _on_reshard(self, target: int) -> None:
        self.trainer.resize(target)

    def _on_tick(self) -> None:
        pass


class SimulatedElasticAgent(_ElasticAgentBase):
    """Protocol-faithful agent with virtual training: each Stable-state
    poll is one productive step on a deterministic loss curve; each resize
    costs :data:`ELASTIC_BLIP_STEPS` virtual steps of MFU. Chaos checks
    read ``steps``/``resizes``/``current``/``violations``/``mfu()``."""

    def __init__(self, client, namespace: str, name: str, *,
                 poll_s: float = 0.02, current_slices: int | None = None):
        super().__init__(client, namespace, name, poll_s=poll_s)
        self.steps = 0
        self.resizes = 0
        self.current = current_slices
        self.losses: list = []
        self.violations: list = []

    def _loss_at(self, step: int) -> float:
        # smooth monotone-decreasing curve: per-step delta < 0.02, so a
        # step-counter reset (loss jumping back toward 2.0) is detectable
        # while honest resumption is continuous by construction
        return 2.0 / (1.0 + 0.01 * step)

    def _on_drain(self) -> None:
        pass

    def _on_reshard(self, target: int) -> None:
        self.current = target
        self.resizes += 1

    def _on_tick(self) -> None:
        self.steps += 1
        loss = self._loss_at(self.steps)
        if self.losses:
            last_step, last_loss = self.losses[-1]
            if self.steps <= last_step:
                self.violations.append(
                    f"step counter reset: {last_step} → {self.steps}")
            if abs(loss - last_loss) > 0.05:
                self.violations.append(
                    f"loss discontinuity at step {self.steps}: "
                    f"{last_loss:.4f} → {loss:.4f}")
        self.losses.append((self.steps, loss))

    def mfu(self) -> float:
        """Fraction of virtual ticks spent stepping vs. resize blips,
        relative to a static mesh (which spends every tick stepping)."""
        blip = ELASTIC_BLIP_STEPS * self.resizes
        return self.steps / (self.steps + blip) if self.steps else 0.0
