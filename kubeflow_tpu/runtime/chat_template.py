"""Chat templates: OpenAI ``messages`` → a single model prompt string.

The ``/v1/chat/completions`` surface (runtime/server.py) receives a role-
tagged conversation; the model consumes one token stream. The mapping is
a *template* — deployment configuration, not code: real checkpoints ship
their own conversation format, and serving the wrong one silently
degrades the model. Three sources, picked by ``load_template``:

- a builtin name (``role-tags`` — the default, a simple explicit format
  appropriate for the untrained/finetuned-here models; ``chatml`` — the
  widely-adopted ``<|im_start|>`` format many public checkpoints use);
- ``tokenizer`` — delegate to the configured HuggingFace tokenizer's own
  ``apply_chat_template`` (the format the checkpoint was trained with);
- a path to a JSON file ``{"turn": "...{role}...{content}...",
  "generation_prompt": "..."}`` for custom formats without code changes.

The reference (a notebook provisioning controller) has no serving layer;
this is part of the TPU workload stack's OpenAI-compatible surface
(SURVEY §2d), shaped so "point your OpenAI SDK's base_url here" holds
for chat clients — the default surface modern SDKs call.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib

# OpenAI chat roles this server accepts. "tool"/"function" messages carry
# call results that need model-specific formats — rejected loudly rather
# than rendered as a guess.
ALLOWED_ROLES = ("system", "user", "assistant")


def validate_messages(messages) -> list[dict]:
    """OpenAI-shape validation, loud on anything we would misrender."""
    if not isinstance(messages, list) or not messages:
        raise ValueError("'messages' must be a non-empty array")
    for i, msg in enumerate(messages):
        if not isinstance(msg, dict):
            raise ValueError(f"messages[{i}] must be an object")
        role = msg.get("role")
        if role not in ALLOWED_ROLES:
            raise ValueError(
                f"messages[{i}].role must be one of {ALLOWED_ROLES} "
                f"(got {role!r}; tool/function messages need a "
                f"model-specific template this server does not guess)")
        content = msg.get("content")
        if not isinstance(content, str) or not content:
            # OpenAI allows content parts (arrays) for multimodal input;
            # a text-only LM server must refuse, not str() them
            raise ValueError(f"messages[{i}].content must be a non-empty "
                             f"string")
    return messages


@dataclasses.dataclass(frozen=True)
class ChatTemplate:
    """One conversation turn format + the assistant generation cue.

    ``turn`` is a ``str.format`` template with ``{role}`` and
    ``{content}`` placeholders applied per message;
    ``generation_prompt`` is appended once at the end so the model
    continues as the assistant."""
    name: str
    turn: str
    generation_prompt: str

    def render(self, messages, add_generation_prompt: bool = True) -> str:
        validate_messages(messages)
        text = "".join(
            self.turn.format(role=m["role"], content=m["content"])
            for m in messages)
        return text + (self.generation_prompt if add_generation_prompt
                       else "")


BUILTIN = {
    "role-tags": ChatTemplate(
        name="role-tags",
        turn="<|{role}|>\n{content}\n",
        generation_prompt="<|assistant|>\n"),
    "chatml": ChatTemplate(
        name="chatml",
        turn="<|im_start|>{role}\n{content}<|im_end|>\n",
        generation_prompt="<|im_start|>assistant\n"),
}


class TokenizerChatTemplate:
    """Delegates to a HuggingFace tokenizer's own chat template — the
    conversation format the checkpoint was actually trained with."""

    name = "tokenizer"

    def __init__(self, tokenizer):
        if not callable(getattr(tokenizer, "apply_chat_template", None)):
            raise ValueError(
                "chat template 'tokenizer' requires a tokenizer with "
                "apply_chat_template (pass --tokenizer with a chat-"
                "templated HF tokenizer, or pick a builtin template)")
        self._tokenizer = tokenizer

    def render(self, messages, add_generation_prompt: bool = True) -> str:
        validate_messages(messages)
        try:
            return self._tokenizer.apply_chat_template(
                messages, tokenize=False,
                add_generation_prompt=add_generation_prompt)
        except ValueError:
            raise
        except Exception as e:  # noqa: BLE001 — a jinja TemplateError
            # (e.g. a Llama/Mistral template rejecting non-alternating
            # roles) is a CLIENT-conversation error: surface as
            # ValueError so the HTTP layer answers 400, not 500
            raise ValueError(
                f"chat template rejected the conversation: "
                f"{type(e).__name__}: {e}") from e


def load_template(spec: str | None = None, tokenizer=None):
    """Resolve a template spec: builtin name, ``tokenizer``, or a JSON
    file path. ``None`` → the ``role-tags`` default."""
    if spec is None or spec in BUILTIN:
        return BUILTIN[spec or "role-tags"]
    if spec == "tokenizer":
        return TokenizerChatTemplate(tokenizer)
    path = pathlib.Path(spec)
    try:
        raw = json.loads(path.read_text())
    except OSError as e:
        raise ValueError(
            f"chat template {spec!r} is neither a builtin "
            f"({', '.join(sorted(BUILTIN))}, tokenizer) nor a readable "
            f"JSON file: {e}") from None
    except ValueError as e:
        raise ValueError(f"chat template file {spec!r} is not valid "
                         f"JSON: {e}") from None
    if not isinstance(raw, dict) or \
            not isinstance(raw.get("turn"), str) or \
            not isinstance(raw.get("generation_prompt"), str):
        raise ValueError(
            f"chat template file {spec!r} must be an object with string "
            f"'turn' (with {{role}}/{{content}} placeholders) and "
            f"'generation_prompt' fields")
    try:  # fail at load time, not on the first request
        ChatTemplate("_probe", raw["turn"],
                     raw["generation_prompt"]).render(
            [{"role": "user", "content": "probe"}])
    except (KeyError, IndexError, ValueError, AttributeError) as e:
        # AttributeError: format placeholders like {role.nope} fail at
        # attribute access, not key lookup
        raise ValueError(f"chat template file {spec!r} has a bad 'turn' "
                         f"format string: {e}") from None
    return ChatTemplate(name=str(raw.get("name", path.stem)),
                        turn=raw["turn"],
                        generation_prompt=raw["generation_prompt"])
