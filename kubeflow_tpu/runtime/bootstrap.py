"""In-container slice bootstrap: TPU_WORKER_* env → a JAX distributed world.

The other half of the control plane's provisioning contract: the controller
injects ``TPU_WORKER_ID`` (StatefulSet pod ordinal) and
``TPU_WORKER_HOSTNAMES`` (headless-Service DNS names) into every worker pod
(controllers/notebook.py:_apply_tpu_spec); this module consumes them inside
the container to form the DCN mesh and verify the slice — the
``jax.device_count()==16`` check that defines readiness in BASELINE.md.

The reference has no in-container component at all (its pods are plain
Jupyter images); this is the TPU-native addition that makes a provisioned
notebook a working multi-host JAX environment out of the box.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass

log = logging.getLogger("kubeflow_tpu.runtime")

DEFAULT_COORDINATOR_PORT = 8476


@dataclass(frozen=True)
class SliceEnv:
    worker_id: int
    hostnames: tuple[str, ...]
    accelerator: str = ""   # e.g. "v5e-16"
    topology: str = ""      # e.g. "4x4"
    coordinator_port: int = DEFAULT_COORDINATOR_PORT

    @property
    def num_workers(self) -> int:
        return max(len(self.hostnames), 1)

    @property
    def multi_host(self) -> bool:
        return self.num_workers > 1

    @property
    def coordinator_address(self) -> str:
        host = self.hostnames[0] if self.hostnames else "localhost"
        return f"{host}:{self.coordinator_port}"

    @classmethod
    def from_env(cls, environ=None) -> "SliceEnv":
        env = environ if environ is not None else os.environ
        raw_hosts = env.get("TPU_WORKER_HOSTNAMES", "localhost")
        hostnames = tuple(h.strip() for h in raw_hosts.split(",") if h.strip())
        try:
            port = int(env.get("KFTPU_COORDINATOR_PORT", "") or
                       DEFAULT_COORDINATOR_PORT)
        except ValueError:
            log.warning("ignoring non-numeric KFTPU_COORDINATOR_PORT")
            port = DEFAULT_COORDINATOR_PORT
        return cls(
            worker_id=int(env.get("TPU_WORKER_ID", "0") or 0),
            hostnames=hostnames,
            accelerator=env.get("TPU_ACCELERATOR_TYPE", ""),
            topology=env.get("TPU_TOPOLOGY", ""),
            coordinator_port=port,
        )


def initialize_slice(env: SliceEnv | None = None) -> SliceEnv:
    """Form the DCN world for a multi-host slice via jax.distributed —
    worker 0 (headless DNS name [0]) is the coordinator. Single-host slices
    need no initialization. Idempotent."""
    env = env or SliceEnv.from_env()
    if env.multi_host:
        import jax
        try:
            jax.distributed.initialize(
                coordinator_address=env.coordinator_address,
                num_processes=env.num_workers,
                process_id=env.worker_id,
            )
            log.info("jax.distributed initialized: process %d/%d via %s",
                     env.worker_id, env.num_workers, env.coordinator_address)
        except RuntimeError as exc:
            if "already initialized" not in str(exc):
                raise
    return env


def expected_device_count(env: SliceEnv, chips_per_worker: int | None = None) -> int:
    """Total chips the formed slice must expose. Derived from the accelerator
    shorthand when present (authoritative), else workers × chips/worker."""
    if env.accelerator:
        try:
            from ..tpu.topology import parse_short_name
            return parse_short_name(env.accelerator).chips
        except Exception:  # noqa: BLE001 — fall through to the env math
            pass
    return env.num_workers * (chips_per_worker or 1)


def verify_slice(env: SliceEnv | None = None, *, timeout_s: float = 60.0,
                 expected: int | None = None) -> dict:
    """The slice-readiness check: jax.device_count() must match the expected
    chip count (mesh formed over ICI+DCN); returns a report dict, raises
    TimeoutError otherwise — the readiness probe turns that into
    pod-not-ready, which keeps SliceReady=False on the CR.

    Note: device_count is fixed once the backend initializes, so this is a
    single check, not a poll (``timeout_s`` kept for API stability; waiting
    happens in jax.distributed.initialize, which blocks until all workers
    join)."""
    import jax

    env = env or SliceEnv.from_env()
    want = expected if expected is not None else expected_device_count(env)
    last_seen = jax.device_count()
    if want > 1 and last_seen != want:
        raise TimeoutError(
            f"slice mesh incomplete: jax.device_count()={last_seen}, "
            f"want {want}")
    return {
        "worker_id": env.worker_id,
        "num_workers": env.num_workers,
        "device_count": last_seen,
        "local_device_count": jax.local_device_count(),
        "accelerator": env.accelerator,
        "topology": env.topology,
        "backend": jax.default_backend(),
    }
