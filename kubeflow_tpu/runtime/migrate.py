"""Checkpoint-based notebook migration drivers.

The control-plane side of a migration (controllers/slicerepair.py) is a
three-step annotation machine — Checkpointing → Binding → Resuming — and
this module supplies the step that touches runtime state: *checkpoint the
training/serving state on the dying slice, resume it on the freshly bound
one*. Two drivers share one seam:

- ``SimulatedMigrationDriver`` (default wiring): annotation-carried step
  bookkeeping for the in-process cluster, where pods hold no real JAX
  processes. ``checkpoint`` snapshots the runtime-step annotation (the
  simulator analog of "the step the trainer had reached") into a token;
  ``resume`` writes it back as the resumed step. Chaos asserts
  resumed == checkpointed: step continuity across the migration.

- ``CheckpointMigrationDriver``: the real thing, backed by
  runtime/checkpoint.TrainCheckpointer (orbax, sharding-aware). The save
  taken at ``checkpoint`` restores onto the NEW slice's mesh via abstract
  shardings — the cross-mesh restore the checkpointer already supports is
  exactly why migration needs no same-topology-layout guarantee beyond the
  worker count. Orbax imports stay inside methods: constructing the driver
  must not force jax into a control-plane-only process.

Identity note: migration preserves ``TPU_WORKER_HOSTNAMES`` (the slice
identity annotation) by construction — the pool controller imposes the
notebook's identity on the new slice's template — so a resumed
``jax.distributed`` client re-initializes against the same coordinator
address list it formed the original mesh on.
"""

from __future__ import annotations

import json
import logging

from ..utils import k8s, names

log = logging.getLogger("kubeflow_tpu.migrate")


class MigrationError(RuntimeError):
    """A checkpoint or resume step failed; the caller falls back to the
    cold-roll path instead of retrying blindly."""


# Token schema version, embedded as "v" by both drivers. During a rolling
# deploy an OLD manager can pick up a Resuming-phase notebook whose token a
# NEW manager wrote; a version it does not know means fields it cannot
# half-read (e.g. future elastic-resize metadata) — fail the migration
# (MigrationError → cold-roll fallback) instead of resuming on a guess.
TOKEN_VERSION = 1


def _check_token_version(meta: dict, token: str) -> None:
    v = meta.get("v", TOKEN_VERSION)  # pre-versioning tokens are v1 shaped
    if v != TOKEN_VERSION:
        raise MigrationError(
            f"checkpoint token version {v!r} not supported "
            f"(this manager speaks v{TOKEN_VERSION}): {token!r}")


class SimulatedMigrationDriver:
    """Annotation-carried checkpoint/resume for the in-process cluster.

    The token is self-contained JSON (not controller memory), so a manager
    restart between Checkpointing and Resuming still resumes the right
    step — the same restart-safety contract the repair annotations keep.
    """

    def checkpoint(self, client, notebook: dict) -> str:
        step_raw = k8s.get_annotation(notebook,
                                      names.RUNTIME_STEP_ANNOTATION)
        try:
            step = int(step_raw) if step_raw is not None else 0
        except ValueError as exc:
            raise MigrationError(
                f"unparseable runtime step {step_raw!r}") from exc
        return json.dumps({"v": TOKEN_VERSION, "step": step})

    def resume(self, client, notebook: dict, token: str) -> None:
        try:
            meta = json.loads(token)
            _check_token_version(meta, token)
            step = int(meta["step"])
        except (ValueError, KeyError, TypeError, AttributeError) as exc:
            raise MigrationError(f"bad checkpoint token {token!r}") from exc
        client.patch(k8s.kind(notebook), k8s.namespace(notebook),
                     k8s.name(notebook), {"metadata": {"annotations": {
                         names.RESUMED_STEP_ANNOTATION: str(step)}}})


class CheckpointMigrationDriver:
    """Orbax-backed migration: force-save the live train state before the
    slice dies, restore it (resharded onto the new mesh) once the bound
    slice is up.

    ``state_provider(notebook) -> (step, params, opt_state)`` and
    ``abstract_provider(notebook) -> (abstract_params, abstract_opt_state)``
    are the seams an in-pod agent fills in (the controller process does not
    hold user pytrees); ``directory_for(notebook)`` maps a notebook to its
    checkpoint location (its PVC path in production)."""

    def __init__(self, directory_for, state_provider, abstract_provider):
        self.directory_for = directory_for
        self.state_provider = state_provider
        self.abstract_provider = abstract_provider

    def checkpoint(self, client, notebook: dict) -> str:
        from .checkpoint import TrainCheckpointer
        directory = self.directory_for(notebook)
        step, params, opt_state = self.state_provider(notebook)
        with TrainCheckpointer(directory, async_save=False) as ckpt:
            if not ckpt.save(step, params, opt_state, force=True):
                raise MigrationError(f"save at step {step} was skipped")
        return json.dumps({"v": TOKEN_VERSION, "step": int(step),
                           "directory": str(directory)})

    def resume(self, client, notebook: dict, token: str):
        from .checkpoint import TrainCheckpointer
        try:
            meta = json.loads(token)
            _check_token_version(meta, token)
            step, directory = int(meta["step"]), meta["directory"]
        except (ValueError, KeyError, TypeError, AttributeError) as exc:
            raise MigrationError(f"bad checkpoint token {token!r}") from exc
        abstract_params, abstract_opt = self.abstract_provider(notebook)
        with TrainCheckpointer(directory) as ckpt:
            restored = ckpt.restore(abstract_params, abstract_opt, step=step)
        if restored is None:
            raise MigrationError(f"no checkpoint at step {step} "
                                 f"in {directory}")
        client.patch(k8s.kind(notebook), k8s.namespace(notebook),
                     k8s.name(notebook), {"metadata": {"annotations": {
                         names.RESUMED_STEP_ANNOTATION: str(restored[0])}}})
        return restored
