from .bootstrap import SliceEnv, initialize_slice, verify_slice

__all__ = ["SliceEnv", "initialize_slice", "verify_slice"]
