from .bootstrap import SliceEnv, initialize_slice, verify_slice

__all__ = ["SliceEnv", "initialize_slice", "verify_slice",
           "TrainCheckpointer", "abstract_state",
           "Trainer", "TrainerStats",
           "prefetch_to_device", "synthetic_lm_batches",
           "token_file_batches", "write_token_file",
           "BatchedGenerator", "GenerateRequest"]

_LAZY = {
    # checkpoint/trainer pull in orbax, which the orbax-free bootstrap path
    # (bench, in-container slice verification) must not pay for or require
    "TrainCheckpointer": "checkpoint",
    "abstract_state": "checkpoint",
    "Trainer": "trainer",
    "TrainerStats": "trainer",
    "prefetch_to_device": "data",
    "synthetic_lm_batches": "data",
    "token_file_batches": "data",
    "write_token_file": "data",
    "BatchedGenerator": "serving",
    "GenerateRequest": "serving",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f".{_LAZY[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
