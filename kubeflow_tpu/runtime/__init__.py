from .bootstrap import SliceEnv, initialize_slice, verify_slice

__all__ = ["SliceEnv", "initialize_slice", "verify_slice",
           "TrainCheckpointer", "abstract_state"]


def __getattr__(name):
    # lazy: checkpoint pulls in orbax, which the orbax-free bootstrap path
    # (bench, in-container slice verification) must not pay for or require
    if name in ("TrainCheckpointer", "abstract_state"):
        from . import checkpoint
        return getattr(checkpoint, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
