"""Sharded train-state checkpoint/resume (orbax-backed).

The reference's checkpoint story is control-plane only: stop/resume is the
replicas 0↔N flip keyed on the stop annotation, and user data persistence is
delegated to PVCs in the pod spec (SURVEY §5; culling_controller.go:53-54).
This module is the compute-side counterpart the TPU workload needs: when the
culler reaps an idle slice mid-training, the notebook resumes from the last
checkpoint on its PVC instead of from scratch.

TPU-first details:
- saves are sharding-aware: each host writes only its addressable shards
  (orbax OCDBT), so multi-host slices checkpoint in parallel over DCN;
- restore takes an *abstract* state (ShapeDtypeStructs carrying
  NamedShardings), so a checkpoint written on one mesh restores onto a
  different mesh/topology — resharding happens at load, not via a separate
  conversion step;
- saves are async by default: the step returns to training while the write
  drains in the background (wait() before exit).
"""

from __future__ import annotations

import jax
import orbax.checkpoint as ocp


def abstract_state(state, shardings=None):
    """ShapeDtypeStruct skeleton of ``state`` (any pytree of arrays), with
    ``shardings`` (a matching pytree of NamedShardings) attached when given —
    the restore target for cross-mesh resume. ``state`` may itself already be
    abstract (e.g. from jax.eval_shape).

    Without an explicit ``shardings`` tree, each leaf's own sharding is
    preserved when it has one: jax.eval_shape on a jitted init attaches the
    out_shardings (the *target* mesh layout), and dropping them here made
    orbax fall back to the sharding file — i.e. the SAVED mesh — so a
    cross-mesh restore returned arrays the target-mesh step rejected."""
    if shardings is None:
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
            state)
    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        state, shardings)


class TrainCheckpointer:
    """Checkpoint manager for (params, opt_state) train state.

    Retention and cadence mirror common trainer policy: keep the newest
    ``max_to_keep`` checkpoints, persist every ``save_interval_steps`` steps
    (off-cadence saves are no-ops unless forced)."""

    def __init__(self, directory, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1, async_save: bool = True):
        self._options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._mngr = ocp.CheckpointManager(directory, options=self._options)

    # ------------------------------------------------------------------ save
    def save(self, step: int, params, opt_state, *, force: bool = False) -> bool:
        """Persist train state at ``step``; returns False when skipped by the
        save-interval policy."""
        return self._mngr.save(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardSave(params),
                opt_state=ocp.args.StandardSave(opt_state)),
            force=force)

    # --------------------------------------------------------------- restore
    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def restore(self, abstract_params, abstract_opt_state,
                step: int | None = None):
        """Restore (step, params, opt_state); the abstract trees' shardings
        decide the on-device layout (pass the *target* mesh's shardings to
        reshard). Returns None when no checkpoint exists at ``step`` (or at
        all), e.g. when retention already evicted a pinned step."""
        if step is None:
            step = self._mngr.latest_step()
        if step is None or step not in self._mngr.all_steps():
            return None
        out = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardRestore(abstract_params),
                opt_state=ocp.args.StandardRestore(abstract_opt_state)))
        return step, out["params"], out["opt_state"]

    def restore_params(self, abstract_params, step: int | None = None):
        """Params-only restore from the same layout (serving does not
        carry optimizer state — runtime/server.py). Returns (step, params)
        or None when no checkpoint exists."""
        if step is None:
            step = self._mngr.latest_step()
        if step is None or step not in self._mngr.all_steps():
            return None
        out = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardRestore(abstract_params)))
        return step, out["params"]

    # -------------------------------------------------------------- lifecycle
    def all_steps(self) -> list[int]:
        return sorted(self._mngr.all_steps())

    def wait(self) -> None:
        """Block until pending async saves are durable."""
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.close()

    def __enter__(self) -> "TrainCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.wait()
        self.close()
