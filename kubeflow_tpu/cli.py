"""kubectl-style CLI against the HTTP apiserver (facade or real cluster).

The reference's operational surface is kubectl: apply Notebook CRs, inspect
status, set the stop annotation, delete (SURVEY §3; the load test scripts
kubectl directly, loadtest/start_notebooks.py). This module is that surface
for the framework's own transport — it speaks the same REST protocol through
HttpApiClient, so it works against ``--serve-apiserver`` standalone clusters
and real apiservers alike.

    python -m kubeflow_tpu.cli --server http://127.0.0.1:6443 apply -f nb.yaml
    python -m kubeflow_tpu.cli get notebooks -n proj
    python -m kubeflow_tpu.cli stop notebook proj/demo
    python -m kubeflow_tpu.cli delete notebook proj/demo
"""

from __future__ import annotations

import argparse
import json
import sys

from .cluster.errors import ApiError, NotFoundError
from .cluster.http_client import HttpApiClient
from .utils import k8s, names

# plural/short → canonical kind (the CLI analog of kubectl's RESTMapper)
KIND_ALIASES = {
    "notebook": "Notebook", "notebooks": "Notebook", "nb": "Notebook",
    "statefulset": "StatefulSet", "statefulsets": "StatefulSet",
    "sts": "StatefulSet",
    "service": "Service", "services": "Service", "svc": "Service",
    "pod": "Pod", "pods": "Pod", "po": "Pod",
    "configmap": "ConfigMap", "configmaps": "ConfigMap", "cm": "ConfigMap",
    "secret": "Secret", "secrets": "Secret",
    "event": "Event", "events": "Event", "ev": "Event",
    "httproute": "HTTPRoute", "httproutes": "HTTPRoute",
    "referencegrant": "ReferenceGrant", "referencegrants": "ReferenceGrant",
    "networkpolicy": "NetworkPolicy", "networkpolicies": "NetworkPolicy",
    "netpol": "NetworkPolicy",
    "serviceaccount": "ServiceAccount", "serviceaccounts": "ServiceAccount",
    "sa": "ServiceAccount",
    "lease": "Lease", "leases": "Lease",
    "namespace": "Namespace", "namespaces": "Namespace", "ns": "Namespace",
}


def resolve_kind(token: str) -> str:
    kind = KIND_ALIASES.get(token.lower())
    if kind is None:
        # accept exact CamelCase kinds too
        if token[:1].isupper():
            return token
        raise SystemExit(f"error: unknown resource type {token!r}")
    return kind


def split_ref(ref: str, namespace: str) -> tuple[str, str]:
    """'ns/name' or 'name' (+ -n namespace) → (ns, name)."""
    if "/" in ref:
        ns, _, name = ref.partition("/")
        return ns, name
    return namespace, ref


def build_client(args) -> HttpApiClient:
    if args.kubeconfig:
        return HttpApiClient.from_kubeconfig(args.kubeconfig)
    return HttpApiClient(args.server, token=args.token,
                         verify=not args.insecure_skip_tls_verify)


def load_documents(path: str):
    import contextlib

    import yaml
    ctx = contextlib.nullcontext(sys.stdin) if path == "-" else open(path)
    with ctx as stream:
        for doc in yaml.safe_load_all(stream):
            if doc:
                yield doc


# ------------------------------------------------------------------ commands
def cmd_apply(client, args) -> int:
    rc = 0
    for obj in load_documents(args.filename):
        kind, ns, name = k8s.kind(obj), k8s.namespace(obj), k8s.name(obj)
        try:
            existing = client.get_or_none(kind, ns, name) if name else None
            if existing is None:
                created = client.create(obj)
                print(f"{kind.lower()}/{k8s.name(created)} created")
            else:
                obj.setdefault("metadata", {})["resourceVersion"] = \
                    existing["metadata"]["resourceVersion"]
                client.update(obj)
                print(f"{kind.lower()}/{name} configured")
        except ApiError as err:
            print(f"error applying {kind}/{name}: {err.message}",
                  file=sys.stderr)
            rc = 1
        except KeyError as err:  # unmapped kind: keep applying the rest
            print(f"error applying {kind}/{name}: {err.args[0]}",
                  file=sys.stderr)
            rc = 1
    return rc


def _ready_of(obj: dict) -> str:
    from .api import types as api
    if k8s.kind(obj) == "Notebook":
        cond = api.get_condition(obj, api.CONDITION_SLICE_READY)
        if cond:
            return cond["status"]
        return "Stopped" if k8s.get_annotation(
            obj, names.STOP_ANNOTATION) is not None else "Unknown"
    if k8s.kind(obj) == "Pod":
        return k8s.get_in(obj, "status", "phase", default="Unknown")
    return ""


def _get_or_complain(client, kind: str, ns: str, name: str):
    """THE fetch-or-report-not-found step shared by get/describe/delete —
    one place owns the kubectl-style error message."""
    try:
        return client.get(kind, ns, name)
    except NotFoundError:
        print(f"Error: {kind.lower()} {ns}/{name} not found",
              file=sys.stderr)
        return None


def cmd_get(client, args) -> int:
    kind = resolve_kind(args.resource)
    if args.name:
        ns, name = split_ref(args.name, args.namespace)
        obj = _get_or_complain(client, kind, ns, name)
        if obj is None:
            return 1
        _print_objs(args.output, obj, [obj])
        return 0
    objs = client.list(kind, args.namespace or None)
    _print_objs(args.output, {"kind": f"{kind}List", "items": objs}, objs)
    return 0


def _print_objs(output: str, raw, objs) -> None:
    if output == "json":
        print(json.dumps(raw, indent=2))
    elif output == "yaml":
        import yaml
        print(yaml.safe_dump(raw, sort_keys=False), end="")
    else:
        _print_table(objs)


def _print_table(objs) -> None:
    rows = [("NAMESPACE", "NAME", "READY", "AGE")]
    for obj in objs:
        rows.append((k8s.namespace(obj) or "-", k8s.name(obj),
                     _ready_of(obj) or "-",
                     k8s.get_in(obj, "metadata", "creationTimestamp",
                                default="-")))
    widths = [max(len(r[i]) for r in rows) for i in range(4)]
    for row in rows:
        print("  ".join(cell.ljust(widths[i])
                        for i, cell in enumerate(row)).rstrip())


def cmd_delete(client, args) -> int:
    kind = resolve_kind(args.resource)
    ns, name = split_ref(args.name, args.namespace)
    try:
        client.delete(kind, ns, name)
    except NotFoundError:
        print(f"Error: {kind.lower()} {ns}/{name} not found",
              file=sys.stderr)
        return 1
    print(f"{kind.lower()}/{name} deleted")
    return 0


def cmd_stop(client, args) -> int:
    ns, name = split_ref(args.name, args.namespace)
    client.patch("Notebook", ns, name, {"metadata": {"annotations": {
        names.STOP_ANNOTATION: k8s.now_iso()}}})
    print(f"notebook/{name} stopped")
    return 0


def cmd_resume(client, args) -> int:
    ns, name = split_ref(args.name, args.namespace)
    client.patch("Notebook", ns, name, {"metadata": {"annotations": {
        names.STOP_ANNOTATION: None}}})
    print(f"notebook/{name} resumed")
    return 0


def cmd_restart(client, args) -> int:
    """Set the restart annotation — the reference's dashboard workflow
    (upstream reconciler deletes the pod and strips the annotation,
    notebook_controller.go:259-294); also how parked ``update-pending``
    webhook mutations get applied."""
    ns, name = split_ref(args.name, args.namespace)
    client.patch("Notebook", ns, name, {"metadata": {"annotations": {
        names.RESTART_ANNOTATION: "true"}}})
    print(f"notebook/{name} restart requested")
    return 0


def cmd_describe(client, args) -> int:
    """kubectl-describe analog: metadata, conditions, and the Events whose
    involvedObject is this resource (the reference re-emits pod/STS events
    onto the CR, so this is where slice failures surface)."""
    kind = resolve_kind(args.resource)
    ns, name = split_ref(args.name, args.namespace)
    obj = _get_or_complain(client, kind, ns, name)
    if obj is None:
        return 1
    print(f"Name:         {name}")
    print(f"Namespace:    {ns}")
    print(f"Kind:         {kind}")
    labels = k8s.get_in(obj, "metadata", "labels", default={}) or {}
    anns = k8s.get_in(obj, "metadata", "annotations", default={}) or {}
    print("Labels:       " + (", ".join(f"{k}={v}" for k, v in
                                        sorted(labels.items())) or "<none>"))
    print("Annotations:  " + (", ".join(f"{k}={v}" for k, v in
                                        sorted(anns.items())) or "<none>"))
    conditions = k8s.get_in(obj, "status", "conditions", default=[]) or []
    if conditions:
        print("Conditions:")
        for cond in conditions:
            print(f"  {cond.get('type', '?'):<16} "
                  f"{cond.get('status', '?'):<8} "
                  f"{cond.get('reason', '')} {cond.get('message', '')}"
                  .rstrip())
    events = [ev for ev in client.list("Event", ns)
              if ev.get("involvedObject", {}).get("name") == name
              and ev.get("involvedObject", {}).get("kind") == kind]
    print("Events:" if events else "Events:       <none>")
    for ev in events:
        print(f"  {ev.get('type', ''):<8} {ev.get('reason', ''):<20} "
              f"x{ev.get('count', 1)}  {ev.get('message', '')}".rstrip())
    return 0


def cmd_watch(client, args) -> int:
    """Stream watch events as table rows (kubectl get -w): the resync on
    connect lists current state as ADDED rows, then live changes follow
    until interrupted, the downstream pipe closes (head/less), or
    --timeout (for scripts)."""
    import threading

    kind = resolve_kind(args.resource)
    stop = threading.Event()

    def on_event(ev) -> None:
        if stop.is_set():
            return
        try:
            print(f"{ev.type:<9} {k8s.namespace(ev.obj) or '-':<12} "
                  f"{k8s.name(ev.obj):<24} {_ready_of(ev.obj) or '-'}",
                  flush=True)
        except BrokenPipeError:
            # prints happen on the watch thread — main()'s handler can't
            # see this; signal the wait below instead of letting the
            # delivery loop log-and-retry the same event forever
            stop.set()
    client.watch(kind, on_event, namespace=args.namespace or None)
    try:
        stop.wait(args.timeout)
    except KeyboardInterrupt:
        pass
    return 0


def _critical_path(spans: list[dict]) -> set[str]:
    """Span ids on the latency-critical chain: from the latest-ending root,
    descend at each level into the child that finished last — that chain is
    what determined when the trace finished. (Walking UP from the
    latest-ending span would degenerate to just the root: in a synchronous
    trace the root always ends last.)"""
    if not spans:
        return set()
    by_id = {s["span_id"]: s for s in spans}
    children: dict = {}
    for s in spans:
        children.setdefault(s.get("parent_id"), []).append(s)
    roots = [s for s in spans if s.get("parent_id") not in by_id]
    cur = max(roots or spans, key=lambda s: s["end"])
    path: set[str] = set()
    while cur is not None and cur["span_id"] not in path:
        path.add(cur["span_id"])
        kids = children.get(cur["span_id"], [])
        cur = max(kids, key=lambda s: s["end"]) if kids else None
    return path


def _span_depth(span: dict, by_id: dict) -> int:
    depth, seen = 0, set()
    parent = span.get("parent_id")
    while parent in by_id and parent not in seen:
        seen.add(parent)
        depth += 1
        parent = by_id[parent].get("parent_id")
    return depth


def render_trace(payload: dict, width: int = 32) -> str:
    """CR→Ready timeline for one notebook: each recorded trace (one per
    reconcile dispatch) as an indented span tree with offset/duration
    columns, a proportional bar, ``*`` on the critical path, and a phase
    breakdown footer (queue / APF / wire / reconcile). Pure — testable
    without an HTTP server."""
    from .utils.tracing import trace_phase_breakdown

    traces = payload.get("traces", [])
    out = [f"Notebook:  {payload.get('namespace', '?')}/"
           f"{payload.get('name', '?')}",
           f"Traces:    {len(traces)} recorded (oldest first)"]
    first_start = last_end = None
    for i, trace in enumerate(traces):
        spans = trace.get("spans", [])
        if not spans:
            continue
        t0 = min(s["start"] for s in spans)
        t_end = max(s["end"] for s in spans)
        wall = max(t_end - t0, 1e-9)
        first_start = t0 if first_start is None else min(first_start, t0)
        last_end = t_end if last_end is None else max(last_end, t_end)
        critical = _critical_path(spans)
        by_id = {s["span_id"]: s for s in spans}
        out.append("")
        out.append(f"Trace {i + 1}/{len(traces)}  {trace['trace_id']}  "
                   f"wall {wall:.3f}s")
        for s in spans:
            offset = s["start"] - t0
            bar_from = int(offset / wall * width)
            bar_len = max(int(s["duration_s"] / wall * width), 1)
            bar = (" " * bar_from +
                   "#" * min(bar_len, width - bar_from)).ljust(width)
            mark = "*" if s["span_id"] in critical else " "
            indent = "  " * _span_depth(s, by_id)
            label = s["name"]
            status = s.get("status")
            if status == "ERROR":
                label += " [ERROR]"
            retries = s.get("attributes", {}).get("retries")
            if retries:
                label += f" (retries={retries})"
            out.append(f"  {mark} +{offset:7.3f}s {s['duration_s']:8.3f}s "
                       f"|{bar}| {indent}{label}")
        phases = trace_phase_breakdown(spans)
        out.append(f"    phases: queue {phases['queue']:.3f}s  "
                   f"apf {phases['apf']:.3f}s (within wire)  "
                   f"wire {phases['wire']:.3f}s  "
                   f"reconcile {phases['reconcile']:.3f}s")
    if first_start is not None:
        out.append("")
        out.append(f"Lifecycle: {last_end - first_start:.3f}s from first "
                   f"dispatch to last span end (* = critical path)")
    return "\n".join(out) + "\n"


def cmd_trace(client, args) -> int:
    """Fetch the manager flight recorder's traces for one notebook from
    the health server's debug endpoint and render the timeline."""
    import urllib.error
    import urllib.request

    ns, name = split_ref(args.name, args.namespace)
    url = (f"{args.debug_server.rstrip('/')}"
           f"/debug/notebooks/{ns}/{name}/trace")
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            payload = json.loads(resp.read().decode())
    except urllib.error.HTTPError as err:
        detail = err.read().decode(errors="replace").strip()
        print(f"Error: HTTP {err.code} from {url}: {detail}",
              file=sys.stderr)
        return 1
    except (urllib.error.URLError, OSError) as err:
        print(f"Error: cannot reach debug server {url}: {err}",
              file=sys.stderr)
        return 1
    if args.last:
        payload["traces"] = payload.get("traces", [])[-args.last:]
    if args.output == "json":
        print(json.dumps(payload, indent=2))
    else:
        print(render_trace(payload), end="")
    return 0


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="kubeflow-tpu", description=__doc__.splitlines()[0])
    ap.add_argument("--server", default="http://127.0.0.1:6443")
    ap.add_argument("--kubeconfig", default=None)
    ap.add_argument("--token", default=None)
    ap.add_argument("--insecure-skip-tls-verify", action="store_true")
    ap.add_argument("-n", "--namespace", default="default")
    sub = ap.add_subparsers(dest="command", required=True)

    p_apply = sub.add_parser("apply", help="apply YAML manifests")
    p_apply.add_argument("-f", "--filename", required=True,
                         help="path or - for stdin")

    p_get = sub.add_parser("get", help="list/show resources")
    p_get.add_argument("resource")
    p_get.add_argument("name", nargs="?")
    p_get.add_argument("-o", "--output", choices=("table", "json", "yaml"),
                       default="table")

    p_del = sub.add_parser("delete", help="delete a resource")
    p_del.add_argument("resource")
    p_del.add_argument("name")

    for verb in ("stop", "resume", "restart"):
        p = sub.add_parser(verb, help=f"{verb} a notebook (slice-atomic)")
        p.add_argument("resource", choices=("notebook", "nb"))
        p.add_argument("name")

    p_desc = sub.add_parser("describe",
                            help="metadata + conditions + events")
    p_desc.add_argument("resource")
    p_desc.add_argument("name")

    p_watch = sub.add_parser("watch", help="stream watch events (get -w)")
    p_watch.add_argument("resource")
    p_watch.add_argument("--timeout", type=float, default=None,
                         help="exit after N seconds (default: forever)")

    p_trace = sub.add_parser(
        "trace", help="per-notebook reconcile timeline (flight recorder)")
    p_trace.add_argument("name", help="notebook as ns/name or name")
    p_trace.add_argument("--debug-server", default="http://127.0.0.1:8081",
                         help="manager health server base URL")
    p_trace.add_argument("--last", type=int, default=0,
                         help="show only the last N traces (0 = all)")
    p_trace.add_argument("-o", "--output", choices=("timeline", "json"),
                         default="timeline")
    return ap


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    client = build_client(args)
    try:
        return _dispatch(client, args)
    finally:
        # stop any watch threads — an in-process caller (tests, notebooks)
        # would otherwise leak a reconnecting stream past this command
        client.close()


def _dispatch(client, args) -> int:
    handler = {"apply": cmd_apply, "get": cmd_get, "delete": cmd_delete,
               "stop": cmd_stop, "resume": cmd_resume,
               "restart": cmd_restart, "describe": cmd_describe,
               "watch": cmd_watch, "trace": cmd_trace}[args.command]
    try:
        return handler(client, args)
    except ApiError as err:
        print(f"Error from server: {err.message}", file=sys.stderr)
        return 1
    except KeyError as err:  # restmapper: kind without a REST mapping
        print(f"Error: {err.args[0]}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        # downstream consumer (head, less) closed the pipe — normal CLI
        # usage, not an error; point stdout at devnull so the interpreter's
        # exit flush doesn't print a second traceback
        import os
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
