"""Speculative decoding: a small draft model proposes, the target verifies.

Decode throughput is weight-bandwidth bound: every generated token re-reads
the target model's weights once (PERF.md rule 4). Speculative decoding
breaks that coupling — a cheap draft model proposes ``k`` tokens
sequentially, then ONE target forward (models/decode.decode_window) scores
the whole block, so the target's weights are read once per accepted-block
instead of once per token. With a well-matched draft, accepted blocks
average well above 1 token, multiplying target-model tokens/s.

TPU-first shape of the loop:
- everything runs under one jit: a ``lax.while_loop`` whose carry holds
  both KV caches, the per-row output cursor, and the emit buffer — no
  per-iteration host round-trips, no dynamic shapes;
- acceptance is per-row (rows advance at their own rate, like continuous
  batching), so the emit scatter uses per-row cursors with mode="drop"
  masking instead of ragged shapes;
- rejected draft/verify cache rows are never rolled back: positions are
  masked by each row's live frontier, and the next block's writes overwrite
  the stale rows in place (the same static-shape discipline as the decode
  cache itself).

Greedy only (temperature 0): acceptance is exact token match, which makes
speculative output IDENTICAL to ``generate``'s greedy output — pinned by
tests/test_speculative.py. Sampled speculative decoding (Leviathan-style
accept/reject on probability ratios) is a planned extension; the verify
window already returns full distributions.

The reference (a notebook provisioning controller) has no decode path;
this belongs to the TPU workload layer (SURVEY §2d serving).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .decode import decode_step, decode_window, prefill
from .transformer import TransformerConfig


class SpecStats(NamedTuple):
    """Observability for the acceptance dynamics (per batch, summed)."""
    blocks: jax.Array          # verify iterations run
    drafted: jax.Array         # draft tokens proposed
    accepted: jax.Array        # draft tokens accepted


@partial(jax.jit,
         static_argnames=("config", "draft_config", "max_new_tokens",
                          "k", "eos_id", "pad_id"))
def speculative_generate(params: dict, draft_params: dict,
                         prompt: jax.Array, config: TransformerConfig,
                         draft_config: TransformerConfig,
                         max_new_tokens: int, k: int = 4,
                         eos_id: int | None = None,
                         pad_id: int = 0) -> tuple[jax.Array, SpecStats]:
    """Greedy speculative decode: (batch, max_new_tokens) ids + SpecStats.

    Contract matches ``generate(..., temperature=0)`` exactly, including
    the EOS semantics (positions after a row's first EOS hold ``pad_id``).
    Requires ``prompt_len + max_new_tokens + k <= max_seq_len`` on BOTH
    configs (the verify window may overhang the last emitted position by
    up to ``k`` rejected rows before they are overwritten).
    """
    tc, dc = config, draft_config
    B, P = prompt.shape
    if P + max_new_tokens + k > min(tc.max_seq_len, dc.max_seq_len):
        raise ValueError(
            f"prompt_len {P} + max_new_tokens {max_new_tokens} + k {k} "
            f"exceeds max_seq_len {min(tc.max_seq_len, dc.max_seq_len)}")
    if k < 1:
        raise ValueError("k must be >= 1")

    t_logits, t_cache = prefill(params, prompt, tc)
    _, d_cache = prefill(draft_params, prompt, dc)

    # the first generated token comes straight from the target's prefill
    # logits — no draft needed, and it seeds the block loop's `last`
    first = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
    done0 = jnp.zeros((B,), bool)
    if eos_id is not None:
        done0 = first == eos_id
    # emit buffer overhangs by k+1: a block may complete a row past
    # max_new_tokens; the result is sliced back to max_new_tokens
    out0 = jnp.full((B, max_new_tokens + k + 1), pad_id, jnp.int32)
    out0 = out0.at[:, 0].set(first)

    class Carry(NamedTuple):
        t_cache: dict
        d_cache: dict
        last: jax.Array        # (B,) newest emitted token, not yet consumed
        n_out: jax.Array       # (B,) tokens emitted so far
        out: jax.Array         # (B, max_new + k + 1)
        done: jax.Array        # (B,) row hit EOS
        stats: SpecStats

    def draft_block(d_cache, last, q_pos):
        """k+1 sequential greedy draft steps consuming
        [last, d_0 .. d_{k-1}] at positions q_pos .. q_pos+k → (B, k)
        proposals + advanced cache. The extra step exists for the cache,
        not the proposal: when all k drafts are accepted the next block
        starts at q_pos+k+1, so the draft cache must already hold
        d_{k-1}'s K/V at q_pos+k — without consuming it, that row would
        be a permanent hole the draft then attends through."""
        def body(carry, j):
            cache, tok = carry
            logits, cache = decode_step(draft_params, cache, tok,
                                        q_pos + j, dc)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (cache, nxt), nxt
        (d_cache, _), drafts = lax.scan(
            body, (d_cache, last), jnp.arange(k + 1, dtype=jnp.int32))
        return d_cache, jnp.moveaxis(drafts[:k], 0, 1)      # (B, k)

    def block(carry: Carry) -> Carry:
        q_pos = P + carry.n_out - 1          # (B,) position of `last`
        d_cache, drafts = draft_block(carry.d_cache, carry.last, q_pos)
        window = jnp.concatenate([carry.last[:, None], drafts], axis=1)
        t_logits, t_cache = decode_window(params, carry.t_cache, window,
                                          q_pos, tc)
        greedy = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # (B, k+1)
        # accept drafts while they match the target's greedy pick given
        # the (known-correct) prefix; the first mismatch position gets the
        # target's own token as the bonus emission
        match = drafts == greedy[:, :k]                      # (B, k)
        n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                        axis=1)                              # (B,) in [0, k]
        # emitted block: drafts[0..n_acc-1] then greedy[n_acc]
        j = jnp.arange(k + 1, dtype=jnp.int32)[None, :]      # (1, k+1)
        emit = jnp.where(j < n_acc[:, None],
                         jnp.pad(drafts, ((0, 0), (0, 1))),
                         jnp.take_along_axis(greedy, jnp.minimum(
                             j, n_acc[:, None]), axis=1))
        emit_len = jnp.where(carry.done, 0, n_acc + 1)
        if eos_id is not None:
            # truncate the block at its first EOS: everything after it in
            # THIS block is suppressed, and the row goes done
            is_eos = (emit == eos_id) & (j < emit_len[:, None])
            eos_before = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) \
                - is_eos.astype(jnp.int32)
            emit = jnp.where(eos_before > 0, pad_id, emit)
            new_done = carry.done | jnp.any(is_eos, axis=1)
        else:
            new_done = carry.done
        # scatter the block at each row's cursor; finished rows drop
        idx = jnp.where((j < emit_len[:, None]) & ~carry.done[:, None],
                        carry.n_out[:, None] + j,
                        jnp.int32(out0.shape[1] + 1))        # OOB → drop
        out = carry.out.at[jnp.arange(B)[:, None], idx].set(
            emit, mode="drop")
        n_out = carry.n_out + emit_len
        last = jnp.where(carry.done, carry.last,
                         jnp.take_along_axis(
                             emit, jnp.maximum(emit_len - 1, 0)[:, None],
                             axis=1)[:, 0])
        stats = SpecStats(
            blocks=carry.stats.blocks + 1,
            drafted=carry.stats.drafted
            + jnp.sum(jnp.where(carry.done, 0, k)),
            accepted=carry.stats.accepted
            + jnp.sum(jnp.where(carry.done, 0, n_acc)))
        return Carry(t_cache, d_cache, last, n_out, out, new_done, stats)

    def cond(carry: Carry):
        return jnp.any((carry.n_out < max_new_tokens) & ~carry.done)

    init = Carry(t_cache, d_cache, first, jnp.ones((B,), jnp.int32),
                 out0, done0,
                 SpecStats(jnp.int32(0), jnp.int32(0), jnp.int32(0)))
    final = lax.while_loop(cond, block, init)
    return final.out[:, :max_new_tokens], final.stats
