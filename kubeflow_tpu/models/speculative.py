"""Speculative decoding: a small draft model proposes, the target verifies.

Decode throughput is weight-bandwidth bound: every generated token re-reads
the target model's weights once (PERF.md rule 4). Speculative decoding
breaks that coupling — a cheap draft model proposes ``k`` tokens
sequentially, then ONE target forward (models/decode.decode_window) scores
the whole block, so the target's weights are read once per accepted-block
instead of once per token. With a well-matched draft, accepted blocks
average well above 1 token, multiplying target-model tokens/s.

TPU-first shape of the loop:
- everything runs under one jit: a ``lax.while_loop`` whose carry holds
  both KV caches, the per-row output cursor, and the emit buffer — no
  per-iteration host round-trips, no dynamic shapes;
- acceptance is per-row (rows advance at their own rate, like continuous
  batching), so the emit scatter uses per-row cursors with mode="drop"
  masking instead of ragged shapes;
- rejected draft/verify cache rows are never rolled back: positions are
  masked by each row's live frontier, and the next block's writes overwrite
  the stale rows in place (the same static-shape discipline as the decode
  cache itself).

Two acceptance rules, selected per row by its traced temperature:
- **temperature 0 (greedy)**: accept while the draft token equals the
  target's argmax — output IDENTICAL to ``generate``'s greedy stream
  (pinned by tests/test_speculative.py);
- **temperature > 0 (sampled)**: the Leviathan accept/reject rule —
  accept draft token x with probability min(1, p(x)/q(x)) where p/q are
  the temperature-scaled target/draft distributions; on rejection sample
  the replacement from norm(max(p − q, 0)); after a fully-accepted block
  sample the bonus from p. Each emitted token is then distributed exactly
  as target sampling (the residual construction cancels the draft's
  bias), verified distributionally in the tests.

``top_k``/``top_p`` warps are not supported here (both distributions
would need the warp applied before the ratio test); ``generate`` remains
the path for nucleus/top-k sampling. MoE targets compose (the verify
window routes (B, W) token blocks) with the usual serving caveat: expert
capacity must be non-binding for window-vs-step routing to agree, the
same condition models/decode.py already states for decode parity.

The reference (a notebook provisioning controller) has no decode path;
this belongs to the TPU workload layer (SURVEY §2d serving).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .decode import decode_step, decode_window, prefill
from .transformer import TransformerConfig


class SpecStats(NamedTuple):
    """Observability for the acceptance dynamics. ``drafted``/``accepted``
    are PER-ROW (batch,) vectors — callers that pad the batch (the serving
    engine's power-of-two dummy rows) sum only the rows that are real.
    Rows stop counting once they are done or have filled max_new_tokens
    (they keep riding the while-loop for the stragglers, but their traffic
    is bookkeeping, not requested work)."""
    blocks: jax.Array          # scalar: verify iterations run
    drafted: jax.Array         # (B,) draft tokens proposed per row
    accepted: jax.Array        # (B,) draft tokens accepted per row


def _scaled_probs(logits: jax.Array, temperature: jax.Array) -> jax.Array:
    """softmax(logits / temp) with temp broadcast over trailing axes; the
    temp<=0 guard keeps the division finite (greedy rows never read it)."""
    t = jnp.maximum(temperature, 1e-6)
    while t.ndim < logits.ndim:
        t = t[..., None]
    return jax.nn.softmax(logits / t, axis=-1)


def propose_and_verify(params: dict, draft_params: dict, t_cache: dict,
                       d_cache: dict, last: jax.Array, q_pos: jax.Array,
                       temp: jax.Array, key: jax.Array,
                       config: TransformerConfig,
                       draft_config: TransformerConfig, k: int):
    """One speculative block with no emit bookkeeping: draft k proposals
    sequentially, verify with ONE target decode_window, accept per row
    (greedy exact-match for temp==0 rows, Leviathan accept/reject for
    sampled rows), and select the block's closing token (target pick /
    residual resample / bonus). Shared by ``speculative_generate``'s
    while-loop and the continuous serving engine's spec tick
    (runtime/serving.py) — the math lives once.

    last: (B,) newest emitted, not yet consumed, at positions q_pos.
    Returns (t_cache, d_cache, drafts (B, k), n_acc (B,), tail (B,)):
    the emitted block for a row is drafts[:n_acc] then tail. The k+1th
    draft step exists for the cache (see the body comment)."""
    tc, dc = config, draft_config
    B = last.shape[0]
    sampled = temp > 0.0
    key_blk, key_u, key_rej, key_bonus = jax.random.split(key, 4)

    # k+1 sequential draft steps consuming [last, d_0 .. d_{k-1}] at
    # positions q_pos .. q_pos+k → (B, k) proposals, their (B, k, V)
    # draft distributions, advanced cache. The extra step exists for the
    # cache, not the proposal: when all k drafts are accepted the next
    # block starts at q_pos+k+1, so the draft cache must already hold
    # d_{k-1}'s K/V at q_pos+k — without consuming it, that row would be
    # a permanent hole the draft then attends through. Draft proposals
    # are greedy for greedy rows and drawn from q for sampled rows (the
    # acceptance rule needs proposals actually distributed as q).
    def body(bcarry, j):
        cache, tok, bkey = bcarry
        logits, cache = decode_step(draft_params, cache, tok,
                                    q_pos + j, dc)
        bkey, sub = jax.random.split(bkey)
        probs = _scaled_probs(logits, temp)
        nxt_greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt_sampled = jax.random.categorical(
            sub, jnp.log(probs + 1e-30), axis=-1).astype(jnp.int32)
        nxt = jnp.where(sampled, nxt_sampled, nxt_greedy)
        return (cache, nxt, bkey), (nxt, probs)

    (d_cache, _, _), (drafts_t, q_probs_t) = lax.scan(
        body, (d_cache, last, key_blk), jnp.arange(k + 1, dtype=jnp.int32))
    drafts = jnp.moveaxis(drafts_t[:k], 0, 1)                # (B, k)
    q_probs = jnp.moveaxis(q_probs_t[:k], 0, 1)              # (B, k, V)

    window = jnp.concatenate([last[:, None], drafts], axis=1)
    t_logits, t_cache = decode_window(params, t_cache, window, q_pos, tc)
    p_probs = _scaled_probs(t_logits, temp)                  # (B, k+1, V)
    greedy = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)

    # acceptance, per rule
    p_at_d = jnp.take_along_axis(p_probs[:, :k], drafts[..., None],
                                 axis=-1)[..., 0]            # (B, k)
    q_at_d = jnp.take_along_axis(q_probs, drafts[..., None],
                                 axis=-1)[..., 0]
    u = jax.random.uniform(key_u, (B, k))
    match_sampled = u * q_at_d < p_at_d      # u < p/q without the div
    match_greedy = drafts == greedy[:, :k]
    match = jnp.where(sampled[:, None], match_sampled, match_greedy)
    n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                    axis=1)                                  # (B,) in [0, k]

    # the block's closing token: greedy rows take the target's own pick
    # at the first mismatch (or the bonus after k accepts — greedy[n_acc]
    # covers both); sampled rows resample rejections from the residual
    # norm(max(p_r − q_r, 0)) and draw the bonus from p_k.
    p_r = jnp.take_along_axis(
        p_probs, jnp.minimum(n_acc, k - 1)[:, None, None], axis=1)[:, 0]
    q_r = jnp.take_along_axis(
        q_probs, jnp.minimum(n_acc, k - 1)[:, None, None], axis=1)[:, 0]
    resid = jnp.maximum(p_r - q_r, 0.0)
    resid_mass = jnp.sum(resid, axis=-1, keepdims=True)
    # p == q makes the residual empty; rejection then cannot happen
    # (accept prob was 1), but guard the log anyway
    resid = jnp.where(resid_mass > 1e-12, resid / resid_mass, p_r)
    rej_tok = jax.random.categorical(
        key_rej, jnp.log(resid + 1e-30), axis=-1).astype(jnp.int32)
    bonus_tok = jax.random.categorical(
        key_bonus, jnp.log(p_probs[:, k] + 1e-30),
        axis=-1).astype(jnp.int32)
    tail_sampled = jnp.where(n_acc == k, bonus_tok, rej_tok)
    tail_greedy = jnp.take_along_axis(greedy, n_acc[:, None], axis=1)[:, 0]
    tail = jnp.where(sampled, tail_sampled, tail_greedy)
    return t_cache, d_cache, drafts, n_acc, tail


@partial(jax.jit,
         static_argnames=("config", "draft_config", "max_new_tokens",
                          "k", "eos_id", "pad_id", "kv_quant"))
def speculative_generate(params: dict, draft_params: dict,
                         prompt: jax.Array, config: TransformerConfig,
                         draft_config: TransformerConfig,
                         max_new_tokens: int, k: int = 4,
                         temperature: float = 0.0,
                         key: jax.Array | None = None,
                         eos_id: int | None = None,
                         pad_id: int = 0,
                         kv_quant: bool = False) \
        -> tuple[jax.Array, SpecStats]:
    """Speculative decode: (batch, max_new_tokens) ids + SpecStats.

    ``temperature`` is traced — a scalar or per-row (batch,) vector, 0 for
    greedy rows (exact ``generate`` greedy parity) and >0 for sampled rows
    (exact target-sampling distribution via accept/reject); mixed batches
    share one executable. ``kv_quant``: int8 TARGET cache with
    per-position scales — bit-identical to ``generate(kv_quant=True)``
    (the verify window quantizes its writes exactly like decode_step);
    the draft cache stays full precision (it is small; its bandwidth is
    not the bottleneck). EOS semantics match ``generate`` (positions
    after a row's first EOS hold ``pad_id``). Requires
    ``prompt_len + max_new_tokens + k <= max_seq_len`` on BOTH configs
    (the verify window may overhang the last emitted position by up to
    ``k`` rejected rows before they are overwritten).
    """
    tc, dc = config, draft_config
    B, P = prompt.shape
    if P + max_new_tokens + k > min(tc.max_seq_len, dc.max_seq_len):
        raise ValueError(
            f"prompt_len {P} + max_new_tokens {max_new_tokens} + k {k} "
            f"exceeds max_seq_len {min(tc.max_seq_len, dc.max_seq_len)}")
    if k < 1:
        raise ValueError("k must be >= 1")
    if key is None:
        key = jax.random.key(0)
    temp = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (B,))
    sampled = temp > 0.0                                     # (B,)

    # kv_quant: int8 TARGET cache with per-position scales — the verify
    # window quantizes its writes exactly like decode_step does, so the
    # stored cache equals generate(kv_quant=True)'s and greedy parity
    # holds bit-for-bit; the draft stays full-precision (it is small)
    t_logits, t_cache = prefill(params, prompt, tc, kv_quant=kv_quant)
    _, d_cache = prefill(draft_params, prompt, dc)

    # the first generated token comes straight from the target's prefill
    # logits — greedy rows argmax, sampled rows draw from p
    key, sub = jax.random.split(key)
    first_greedy = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
    first_sampled = jax.random.categorical(
        sub, jnp.log(_scaled_probs(t_logits, temp) + 1e-30),
        axis=-1).astype(jnp.int32)
    first = jnp.where(sampled, first_sampled, first_greedy)
    done0 = jnp.zeros((B,), bool)
    if eos_id is not None:
        done0 = first == eos_id
    # emit buffer overhangs by k+1: a block may complete a row past
    # max_new_tokens; the result is sliced back to max_new_tokens
    out0 = jnp.full((B, max_new_tokens + k + 1), pad_id, jnp.int32)
    out0 = out0.at[:, 0].set(first)

    class Carry(NamedTuple):
        t_cache: dict
        d_cache: dict
        last: jax.Array        # (B,) newest emitted token, not yet consumed
        n_out: jax.Array       # (B,) tokens emitted so far
        out: jax.Array         # (B, max_new + k + 1)
        done: jax.Array        # (B,) row hit EOS
        key: jax.Array
        stats: SpecStats

    def block(carry: Carry) -> Carry:
        q_pos = P + carry.n_out - 1          # (B,) position of `last`
        key_blk, key_next = jax.random.split(carry.key)
        t_cache, d_cache, drafts, n_acc, tail = propose_and_verify(
            params, draft_params, carry.t_cache, carry.d_cache,
            carry.last, q_pos, temp, key_blk, tc, dc, k)

        # --- emit the block ---
        j = jnp.arange(k + 1, dtype=jnp.int32)[None, :]      # (1, k+1)
        emit = jnp.where(j < n_acc[:, None],
                         jnp.pad(drafts, ((0, 0), (0, 1))),
                         tail[:, None])
        # a row participates while un-done AND still short of max_new —
        # full rows ride along for the stragglers without advancing
        # cursors or stats
        alive = ~carry.done & (carry.n_out < max_new_tokens)
        emit_len = jnp.where(alive, n_acc + 1, 0)
        if eos_id is not None:
            # truncate the block at its first EOS: everything after it in
            # THIS block is suppressed, and the row goes done
            is_eos = (emit == eos_id) & (j < emit_len[:, None])
            eos_before = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) \
                - is_eos.astype(jnp.int32)
            emit = jnp.where(eos_before > 0, pad_id, emit)
            new_done = carry.done | jnp.any(is_eos, axis=1)
        else:
            new_done = carry.done
        # scatter the block at each row's cursor; non-alive rows drop
        idx = jnp.where(j < emit_len[:, None],
                        carry.n_out[:, None] + j,
                        jnp.int32(out0.shape[1] + 1))        # OOB → drop
        out = carry.out.at[jnp.arange(B)[:, None], idx].set(
            emit, mode="drop")
        n_out = carry.n_out + emit_len
        last = jnp.where(alive,
                         jnp.take_along_axis(
                             emit, jnp.maximum(emit_len - 1, 0)[:, None],
                             axis=1)[:, 0],
                         carry.last)
        stats = SpecStats(
            blocks=carry.stats.blocks + 1,
            drafted=carry.stats.drafted + jnp.where(alive, k, 0),
            accepted=carry.stats.accepted + jnp.where(alive, n_acc, 0))
        return Carry(t_cache, d_cache, last, n_out, out, new_done,
                     key_next, stats)

    def cond(carry: Carry):
        return jnp.any((carry.n_out < max_new_tokens) & ~carry.done)

    init = Carry(t_cache, d_cache, first, jnp.ones((B,), jnp.int32),
                 out0, done0, key,
                 SpecStats(jnp.int32(0), jnp.zeros((B,), jnp.int32),
                           jnp.zeros((B,), jnp.int32)))
    final = lax.while_loop(cond, block, init)
    return final.out[:, :max_new_tokens], final.stats
