"""Flagship workload: decoder-only transformer LM, TPU-first.

This is the model the framework provisions into notebook slices for
verification and benchmarking (BASELINE.md configs; the reference provisions
Jupyter images and has no model code — SURVEY §2d — so this model is the
TPU-native analog of its workload layer).

Design for the MXU/XLA:
- pure functional: params are an explicit pytree; every weight carries a
  logical-axis spec (parallel/sharding.py) so one model definition runs under
  any MeshConfig (dp/fsdp/tp/sp) without edits;
- bfloat16 activations/matmuls, float32 params + softmax/norm accumulation;
- static shapes everywhere; layers iterated with lax.scan over stacked
  weights (one compiled layer body, no Python unrolling);
- optional jax.checkpoint (remat) per layer to trade FLOPs for HBM;
- attention dispatches to ring attention (parallel/ring.py) when the mesh has
  sp>1, else a fused XLA softmax path (ops/attention.py provides the Pallas
  flash kernel used on real TPU).
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .quant import wcast

from ..parallel.sharding import PartitionRules


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8          # < n_heads ⇒ grouped-query attention
    d_ff: int = 1376
    max_seq_len: int = 2048
    rope_theta: float = 10_000.0
    dtype: str = "bfloat16"      # activation/compute dtype
    param_dtype: str = "float32"
    # False: save everything (fastest while it fits); "mlp": remat only the
    # FFN — the saved bf16 [L,b,s,d_ff] gate/up activations dominate HBM,
    # and recomputing just them holds ~47% MFU at batches that OOM
    # un-remated (v5e, d1024 flagship: b16/b32 run at 69.7k/67.6k tokens/s
    # vs OOM); "attn": remat the whole layer EXCEPT the attention output —
    # backward recomputes norms/projections/FFN but never re-runs the
    # O(s²) attention forward, the right point for long contexts where
    # whole-layer remat's attention recompute dominates; True: remat the
    # whole layer (absolute smallest footprint)
    remat: bool | str = False
    attention: str = "auto"      # auto | xla | ring | ulysses | flash
    # decode-time attention over the KV cache: "flash" streams the cache
    # through the Pallas flash-decode kernel (ops/decode_attention.py);
    # "auto" engages it on TPU at long max_seq_len where the cache read
    # dominates the step; "xla" keeps the einsum path
    decode_attention: str = "auto"

    def __post_init__(self):
        if self.remat not in (False, True, "mlp", "attn"):
            raise ValueError(f"remat must be False, True, 'mlp', or "
                             f"'attn'; got {self.remat!r}")
        if self.decode_attention not in ("auto", "xla", "flash"):
            raise ValueError(f"decode_attention must be auto, xla, or "
                             f"flash; got {self.decode_attention!r}")

    @property
    def d_head(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    def replace(self, **kw) -> "TransformerConfig":
        return dataclasses.replace(self, **kw)


# ------------------------------------------------------------------ params
def param_logical_specs(config: TransformerConfig) -> dict:
    """Logical-axis names per weight; parallel.param_shardings turns these
    into NamedShardings for any mesh. Layer weights are stacked on a leading
    'layers' axis (scanned, not unrolled)."""
    return {
        "embed": ("vocab", "embed"),
        "blocks": {
            "attn_norm": ("layers", "norm"),
            "wq": ("layers", "embed", "heads", "head_dim"),
            "wk": ("layers", "embed", "kv_heads", "head_dim"),
            "wv": ("layers", "embed", "kv_heads", "head_dim"),
            "wo": ("layers", "heads", "head_dim", "embed"),
            "mlp_norm": ("layers", "norm"),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": ("norm",),
        "lm_head": ("embed", "vocab"),
    }


def init_params(key: jax.Array, config: TransformerConfig) -> dict:
    c = config
    pdt = jnp.dtype(c.param_dtype)
    k_embed, k_blocks, k_head = jax.random.split(key, 3)

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, pdt) / math.sqrt(fan_in))

    L = c.n_layers
    kb = jax.random.split(k_blocks, 7)
    blocks = {
        "attn_norm": jnp.ones((L, c.d_model), pdt),
        "wq": dense(kb[0], (L, c.d_model, c.n_heads, c.d_head), c.d_model),
        "wk": dense(kb[1], (L, c.d_model, c.n_kv_heads, c.d_head), c.d_model),
        "wv": dense(kb[2], (L, c.d_model, c.n_kv_heads, c.d_head), c.d_model),
        "wo": dense(kb[3], (L, c.n_heads, c.d_head, c.d_model),
                    c.n_heads * c.d_head),
        "mlp_norm": jnp.ones((L, c.d_model), pdt),
        "w_gate": dense(kb[4], (L, c.d_model, c.d_ff), c.d_model),
        "w_up": dense(kb[5], (L, c.d_model, c.d_ff), c.d_model),
        "w_down": dense(kb[6], (L, c.d_ff, c.d_model), c.d_ff),
    }
    return {
        "embed": jax.random.normal(k_embed, (c.vocab_size, c.d_model), pdt),
        "blocks": blocks,
        "final_norm": jnp.ones((c.d_model,), pdt),
        "lm_head": dense(k_head, (c.d_model, c.vocab_size), c.d_model),
    }


# ------------------------------------------------------------------- layers
def resolve_remat_mlp(config, mlp_fn):
    """One resolution of the ``remat="mlp"`` policy for every forward path
    (dense scan, pipelined stages, MoE experts): checkpoint only the FFN
    whose saved activations dominate HBM; everything else stays saved."""
    if config.remat == "mlp":
        return jax.checkpoint(mlp_fn, static_argnums=(2,))
    return mlp_fn


def tag_attn_out(x: jax.Array) -> jax.Array:
    """Name the post-attention residual stream for the ``remat="attn"``
    policy (identity under every other policy)."""
    from jax.ad_checkpoint import checkpoint_name
    return checkpoint_name(x, "attn_out")


def resolve_layer_remat(config, body):
    """One resolution of the whole-layer remat policies for a scanned
    layer body whose attention output is tagged via ``tag_attn_out``:

    - True   → checkpoint everything (smallest footprint; backward re-runs
               the full layer forward including O(s²) attention);
    - "attn" → checkpoint everything EXCEPT the tagged attention output:
               backward recomputes norms/projections/FFN from the saved
               tensor but never re-runs the attention forward. Costs one
               extra (b, s, d_model) save per layer over True — the right
               trade at long context where attention recompute dominates
               (the attention VJP itself still streams its own O(s²) pass,
               as flash backward always does).
    """
    if config.remat is True:
        return jax.checkpoint(body)
    if config.remat == "attn":
        return jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_only_these_names("attn_out"))
    return body


def _rms_norm_impl(x, weight, eps):
    """One shared primal body for both the plain and the grad-traced
    forward — they must never diverge. Returns (y, inv)."""
    x32 = x.astype(jnp.float32)
    inv = lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * inv * weight.astype(jnp.float32)).astype(x.dtype), inv


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm in f32 with a custom VJP: autodiff of the naive form saves
    the full f32 normalized intermediate per call — 2 per layer, observed
    as f32[L,b,s,d] HLO temps that dominate HBM at batch>=16 and push XLA
    into slower memory-pressure schedules. The VJP saves only (x, w, inv)
    where inv is the per-ROW rsqrt scalar, d× smaller, and recomputes the
    rest in backward."""
    return _rms_norm_impl(x, weight, eps)[0]


def _rms_norm_fwd(x, weight, eps):
    y, inv = _rms_norm_impl(x, weight, eps)
    return y, (x, weight, inv)


def _rms_norm_bwd(eps, res, g):
    x, weight, inv = res
    x32 = x.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    w32 = weight.astype(jnp.float32)
    d = x.shape[-1]
    wg = g32 * w32
    # d(x·inv(x))·wg: product rule through the rsqrt(mean(x²)) term
    dot = jnp.sum(x32 * wg, axis=-1, keepdims=True)
    dx = inv * wg - (inv ** 3) * x32 * dot / d
    dw = jnp.sum(g32 * x32 * inv, axis=tuple(range(x.ndim - 1)))
    return dx.astype(x.dtype), dw.astype(weight.dtype)


rms_norm.defvjp(_rms_norm_fwd, _rms_norm_bwd)


def rope_frequencies(config: TransformerConfig, positions: jax.Array):
    """positions: (..., seq) int32 → cos/sin of shape (..., seq, d_head/2)."""
    d = config.d_head // 2
    inv_freq = config.rope_theta ** (-jnp.arange(0, d, dtype=jnp.float32) / d)
    angles = positions[..., None].astype(jnp.float32) * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: (batch, seq, heads, d_head); cos/sin: (batch, seq, d_head/2)."""
    dt = x.dtype
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos = cos[..., None, :]
    sin = sin[..., None, :]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(dt)


def repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """(b, s, kv_heads, d) → (b, s, kv_heads*n_rep, d) for GQA."""
    if n_rep == 1:
        return x
    return jnp.repeat(x, n_rep, axis=2)


def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = True) -> jax.Array:
    """Reference attention in pure XLA ops — fused well by the compiler;
    float32 softmax accumulation. Shapes: (b, s, h, d)."""
    b, sq, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sk = k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# below this sequence length XLA's fused attention beats the Pallas kernel
# (v5e measured: 0.7-0.8x at 512, 3-8x flash advantage from 1024 up — the
# kernel's streaming machinery only pays off once the s^2 term dominates)
FLASH_MIN_SEQ = 1024


def _select_attention(config: TransformerConfig, mesh, seq_len: int) -> str:
    if config.attention != "auto":
        return config.attention
    if mesh is not None and mesh.shape.get("sp", 1) > 1:
        return "ring"
    if jax.default_backend() == "tpu" and seq_len >= FLASH_MIN_SEQ:
        return "flash"
    return "xla"


def attention_block(x, layer, config: TransformerConfig, cos, sin, mesh=None,
                    return_kv: bool = False,
                    manual_sp: tuple[str, int] | None = None):
    """``return_kv=True`` additionally returns the post-RoPE, pre-GQA-repeat
    (k, v) — what a decode KV cache stores (models/decode.py prefill).

    ``manual_sp=(axis_name, axis_size)``: the caller is ALREADY inside a
    shard_map region where the sequence axis is manual (pipeline stages
    with sp>1) — run the per-device ring-attention body directly (bare
    ppermute over that axis) instead of opening a nested shard_map."""
    c = config
    h = rms_norm(x, layer["attn_norm"])
    q = jnp.einsum("bsd,dhk->bshk", h, wcast(layer["wq"], h.dtype))
    k = jnp.einsum("bsd,dhk->bshk", h, wcast(layer["wk"], h.dtype))
    v = jnp.einsum("bsd,dhk->bshk", h, wcast(layer["wv"], h.dtype))
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    kv = (k, v)
    n_rep = c.n_heads // c.n_kv_heads

    if manual_sp is not None:
        from ..parallel.ring import _ring_local
        axis_name, axis_size = manual_sp
        out = _ring_local(q, repeat_kv(k, n_rep), repeat_kv(v, n_rep),
                          axis_name=axis_name, axis_size=axis_size,
                          causal=True)
        x = x + jnp.einsum("bshk,hkd->bsd", out, wcast(layer["wo"], h.dtype))
        return (x, kv) if return_kv else x

    kind = _select_attention(c, mesh, x.shape[1])
    if kind == "ulysses":
        # takes the un-repeated K/V: its all-to-alls move 1/n_rep the bytes
        from ..parallel.ulysses import ulysses_attention
        out = ulysses_attention(q, k, v, mesh=mesh, axis_name="sp",
                                causal=True, n_rep=n_rep)
    else:
        k = repeat_kv(k, n_rep)
        v = repeat_kv(v, n_rep)
        if kind == "ring":
            from ..parallel.ring import ring_attention
            out = ring_attention(q, k, v, mesh=mesh, axis_name="sp",
                                 causal=True)
        elif kind == "flash":
            from ..ops.attention import flash_attention
            out = flash_attention(q, k, v, causal=True)
        else:
            out = xla_attention(q, k, v, causal=True)
    x = x + jnp.einsum("bshk,hkd->bsd", out, wcast(layer["wo"], h.dtype))
    return (x, kv) if return_kv else x


def mlp_block(x, layer, config: TransformerConfig):
    h = rms_norm(x, layer["mlp_norm"])
    dt = h.dtype
    gate = jnp.einsum("bsd,df->bsf", h, wcast(layer["w_gate"], dt))
    up = jnp.einsum("bsd,df->bsf", h, wcast(layer["w_up"], dt))
    return x + jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up,
                          wcast(layer["w_down"], dt))


def forward_hidden(params: dict, tokens: jax.Array,
                   config: TransformerConfig, mesh=None,
                   positions: jax.Array | None = None) -> jax.Array:
    """tokens: (batch, seq) int32 → final-norm hidden states (b, s, d).
    The LM-head projection is NOT applied — the fused chunked cross-entropy
    (models/train.py) consumes hidden states directly so the (b, s, vocab)
    f32 logits tensor never materializes.

    When the mesh has sp>1 the caller passes sequence-sharded tokens plus the
    matching global ``positions`` (runtime handles this; ring attention makes
    the causal math correct across shards)."""
    c = config
    x = params["embed"].astype(c.compute_dtype)[tokens]
    if positions is None:
        positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
        positions = jnp.broadcast_to(positions, tokens.shape)
    cos, sin = rope_frequencies(c, positions)

    mlp = resolve_remat_mlp(c, mlp_block)

    def layer_body(x, layer):
        x = attention_block(x, layer, c, cos, sin, mesh=mesh)
        x = tag_attn_out(x)
        x = mlp(x, layer, c)
        return x, None

    body = resolve_layer_remat(c, layer_body)
    x, _ = lax.scan(body, x, params["blocks"])

    return rms_norm(x, params["final_norm"])


def lm_head_logits(x: jax.Array, lm_head) -> jax.Array:
    """THE final projection: (b, s, d) hidden → (b, s, vocab) f32 logits.
    One definition shared by every forward path (dense, pipelined, MoE) —
    the f32 cast here is what CE numerics depend on."""
    return jnp.einsum("bsd,dv->bsv", x, wcast(lm_head, x.dtype)
                      ).astype(jnp.float32)


def forward(params: dict, tokens: jax.Array, config: TransformerConfig,
            mesh=None, positions: jax.Array | None = None) -> jax.Array:
    """tokens: (batch, seq) int32 → logits (batch, seq, vocab) float32."""
    x = forward_hidden(params, tokens, config, mesh=mesh, positions=positions)
    return lm_head_logits(x, params["lm_head"])


def pipelined_forward(params: dict, tokens: jax.Array,
                      config: TransformerConfig, mesh,
                      n_microbatches: int) -> jax.Array:
    """Forward pass with the layer stack pipelined over the ``pp`` mesh axis
    (parallel/pipeline.py). Embedding and LM head run outside the pipeline
    (they live on every stage's data shards); the blocks are split into
    contiguous stages. RoPE tables are position-only (batch-size 1) so they
    broadcast across microbatches.

    Composes with sequence parallelism: when the mesh has sp>1 the manual
    region extends over (pp, sp) — each stage runs ring attention via bare
    ppermute on sp while activations stay sequence-sharded; the RoPE
    tables ride along as sharded extra args so every stage sees its
    shard's global positions."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.pipeline import pipeline_apply, split_stages

    c = config
    x = params["embed"].astype(c.compute_dtype)[tokens]
    positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
    cos, sin = rope_frequencies(c, positions)

    stages = split_stages(params["blocks"], mesh.shape["pp"])

    mlp = resolve_remat_mlp(c, mlp_block)
    sp = mesh.shape.get("sp", 1)
    manual_sp = ("sp", sp) if sp > 1 else None

    def stage_fn(stage_layers, act, cos, sin):
        def body(h, layer):
            h = attention_block(h, layer, c, cos, sin, mesh=None,
                                manual_sp=manual_sp)
            h = tag_attn_out(h)
            h = mlp(h, layer, c)
            return h, None
        body_fn = resolve_layer_remat(c, body)
        act, _ = lax.scan(body_fn, act, stage_layers)
        return act

    if manual_sp is not None:
        x = pipeline_apply(
            stages, x, stage_fn, mesh=mesh, n_microbatches=n_microbatches,
            manual_axes=("pp", "sp"),
            act_spec=P(None, "sp", None),          # (batch, seq, d_model)
            extra_args=(cos, sin),
            extra_specs=(P(None, "sp", None), P(None, "sp", None)))
    else:
        x = pipeline_apply(stages, x, stage_fn, mesh=mesh,
                           n_microbatches=n_microbatches,
                           extra_args=(cos, sin), extra_specs=(P(), P()))
    x = rms_norm(x, params["final_norm"])
    return lm_head_logits(x, params["lm_head"])


def count_params(params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))


def model_flops_per_token(config: TransformerConfig) -> float:
    """Approximate forward FLOPs/token (2*params matmul convention)."""
    c = config
    per_layer = 2 * (c.d_model * c.n_heads * c.d_head * 2
                     + c.d_model * c.n_kv_heads * c.d_head * 2
                     + 3 * c.d_model * c.d_ff)
    return c.n_layers * per_layer + 2 * c.d_model * c.vocab_size
