"""Mixture-of-Experts transformer LM — the expert-parallel workload family.

Second model family the framework provisions into notebook slices (the
reference has no model code, SURVEY §2d; this extends the flagship dense LM
in transformer.py with sparse MoE MLPs). Reuses the dense model's attention
stack, norms, and RoPE wholesale — only the MLP is replaced.

TPU-first routing (GShard/Switch-style, GSPMD-friendly):
- static shapes end to end: top-k routing is expressed as one-hot dispatch /
  combine tensors (token, expert, capacity) contracted with einsum — no
  dynamic gathers, no data-dependent shapes, nothing XLA can't tile;
- experts are a leading weight axis sharded over the ``ep`` mesh axis
  (parallel/sharding.py "experts" rule); the dispatch/combine einsums are
  where GSPMD inserts the all-to-alls;
- router math in float32 (softmax + cumsum), expert FFN in the compute dtype
  on the MXU;
- Switch-style load-balance auxiliary loss (n_experts · Σ fraction·prob,
  minimized at 1.0 when routing is uniform) returned alongside the logits.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.sharding import (PartitionRules, batch_sharding,
                                 param_shardings)
from .quant import wcast
from .transformer import (TransformerConfig, attention_block,
                          lm_head_logits, resolve_layer_remat, rms_norm,
                          rope_frequencies, tag_attn_out)


@dataclass(frozen=True)
class MoEConfig(TransformerConfig):
    n_experts: int = 8
    experts_per_token: int = 2       # top-k routing
    capacity_factor: float = 1.25    # expert capacity ≈ group/E · factor
    router_aux_coef: float = 0.01    # weight of the load-balance loss
    # tokens are routed in groups of at most this many, with capacity computed
    # PER GROUP (GShard's grouping): dispatch/combine memory is then linear in
    # global token count instead of quadratic — at N=128k, E=8 an ungrouped
    # dispatch tensor is multi-GB per layer
    route_group_size: int = 2048


# ------------------------------------------------------------------ params
def moe_param_logical_specs(config: MoEConfig) -> dict:
    """Same attention weights as the dense model; MLP weights gain a leading
    'experts' axis (→ ep), plus the router projection."""
    return {
        "embed": ("vocab", "embed"),
        "blocks": {
            "attn_norm": ("layers", "norm"),
            "wq": ("layers", "embed", "heads", "head_dim"),
            "wk": ("layers", "embed", "kv_heads", "head_dim"),
            "wv": ("layers", "embed", "kv_heads", "head_dim"),
            "wo": ("layers", "heads", "head_dim", "embed"),
            "mlp_norm": ("layers", "norm"),
            "router": ("layers", "embed", "experts"),
            "w_gate": ("layers", "experts", "embed", "mlp"),
            "w_up": ("layers", "experts", "embed", "mlp"),
            "w_down": ("layers", "experts", "mlp", "embed"),
        },
        "final_norm": ("norm",),
        "lm_head": ("embed", "vocab"),
    }


def init_moe_params(key: jax.Array, config: MoEConfig) -> dict:
    c = config
    pdt = jnp.dtype(c.param_dtype)
    k_embed, k_blocks, k_head = jax.random.split(key, 3)

    def dense(k, shape, fan_in):
        return jax.random.normal(k, shape, pdt) / math.sqrt(fan_in)

    L, E = c.n_layers, c.n_experts
    kb = jax.random.split(k_blocks, 8)
    blocks = {
        "attn_norm": jnp.ones((L, c.d_model), pdt),
        "wq": dense(kb[0], (L, c.d_model, c.n_heads, c.d_head), c.d_model),
        "wk": dense(kb[1], (L, c.d_model, c.n_kv_heads, c.d_head), c.d_model),
        "wv": dense(kb[2], (L, c.d_model, c.n_kv_heads, c.d_head), c.d_model),
        "wo": dense(kb[3], (L, c.n_heads, c.d_head, c.d_model),
                    c.n_heads * c.d_head),
        "mlp_norm": jnp.ones((L, c.d_model), pdt),
        "router": dense(kb[4], (L, c.d_model, E), c.d_model),
        "w_gate": dense(kb[5], (L, E, c.d_model, c.d_ff), c.d_model),
        "w_up": dense(kb[6], (L, E, c.d_model, c.d_ff), c.d_model),
        "w_down": dense(kb[7], (L, E, c.d_ff, c.d_model), c.d_ff),
    }
    return {
        "embed": jax.random.normal(k_embed, (c.vocab_size, c.d_model), pdt),
        "blocks": blocks,
        "final_norm": jnp.ones((c.d_model,), pdt),
        "lm_head": dense(k_head, (c.d_model, c.vocab_size), c.d_model),
    }


# ------------------------------------------------------------------ routing
def expert_capacity(n_tokens: int, config: MoEConfig) -> int:
    """Static per-expert capacity for one routing group:
    ceil(group/E · factor · k), floor 4. Python int at trace time — shapes
    stay static."""
    c = config
    cap = math.ceil(n_tokens / c.n_experts * c.capacity_factor
                    * c.experts_per_token)
    return max(4, cap)


def num_route_groups(n_tokens: int, group_size: int) -> int:
    """Smallest group count G with N % G == 0 and N/G <= group_size (G = 1
    when N fits in one group). Static python math at trace time."""
    groups = max(1, math.ceil(n_tokens / group_size))
    while n_tokens % groups:
        groups += 1
    return groups


def route_tokens(router_logits: jax.Array, config: MoEConfig,
                 capacity: int):
    """Top-k token→expert assignment as dense one-hot tensors.

    router_logits: (N, E) float32 →
      combine  (N, E, C) float32 — gate weight where token n occupies slot c
                                   of expert e, 0 elsewhere;
      dispatch (N, E, C) bool    — combine > 0;
      aux      ()        float32 — Switch load-balance loss.

    Tokens beyond an expert's capacity are dropped (their combine weight is 0
    — the residual connection carries them through, standard GShard behavior).
    """
    c = config
    N, E = router_logits.shape
    probs = jax.nn.softmax(router_logits, axis=-1)          # (N, E) f32
    gate_vals, gate_idx = lax.top_k(probs, c.experts_per_token)  # (N, k)
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(axis=-1, keepdims=True), 1e-9)

    combine = jnp.zeros((N, E, capacity), dtype=jnp.float32)
    filled = jnp.zeros((E,), dtype=jnp.int32)   # slots used per expert so far
    top1_mask = None
    for j in range(c.experts_per_token):
        mask_j = jax.nn.one_hot(gate_idx[:, j], E, dtype=jnp.int32)  # (N, E)
        if j == 0:
            top1_mask = mask_j
        # slot index for each token within its chosen expert (first-come
        # order over the flattened token axis, GShard's cumsum assignment)
        pos = jnp.cumsum(mask_j, axis=0) - mask_j + filled[None, :]  # (N, E)
        keep = (pos < capacity) & (mask_j > 0)
        filled = filled + mask_j.sum(axis=0).clip(max=capacity)
        slot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)      # (N,E,C)
        combine = combine + (gate_vals[:, j, None, None]
                             * keep[..., None].astype(jnp.float32) * slot)
    dispatch = combine > 0.0

    # Switch aux loss: E · Σ_e fraction_routed(e) · mean_prob(e); == 1 at
    # perfect balance, grows as routing collapses onto few experts
    fraction = top1_mask.astype(jnp.float32).mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux = E * jnp.sum(fraction * mean_prob)
    return combine, dispatch, aux


def moe_mlp_block(x: jax.Array, layer: dict, config: MoEConfig,
                  mesh: Mesh | None = None):
    """Sparse MLP: group → route → dispatch einsum → per-expert gated FFN →
    combine einsum. Returns (x + out, aux_loss).

    Tokens are split into G groups of g <= route_group_size and routed
    independently with PER-GROUP capacity (GShard grouping): dispatch is
    (G, g, E, C_g) with C_g ~ g/E·factor·k, so activation memory is linear in
    global token count. Group order follows the (batch, seq) layout, so under
    dp/fsdp sharding groups stay device-local and only the expert axis
    all-to-alls."""
    c = config
    h = rms_norm(x, layer["mlp_norm"])
    B, S, D = h.shape
    N = B * S
    groups = num_route_groups(N, c.route_group_size)
    g = N // groups
    hg = h.reshape(groups, g, D)
    router_logits = jnp.einsum(
        "gnd,de->gne", hg.astype(jnp.float32),
        wcast(layer["router"], jnp.float32))
    capacity = expert_capacity(g, c)
    combine, dispatch, aux = jax.vmap(
        lambda logits: route_tokens(logits, c, capacity))(router_logits)
    aux = aux.mean()  # (G,) per-group losses → scalar

    dt = h.dtype
    # (G,g,E,C) × (G,g,D) → (G,E,C,D): the all-to-all under ep sharding
    expert_in = jnp.einsum("gnec,gnd->gecd", dispatch.astype(dt), hg)
    if mesh is not None and mesh.shape.get("ep", 1) > 1:
        expert_in = lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P(None, "ep", None, None)))
    gate = jnp.einsum("gecd,edf->gecf", expert_in, wcast(layer["w_gate"], dt))
    up = jnp.einsum("gecd,edf->gecf", expert_in, wcast(layer["w_up"], dt))
    expert_out = jnp.einsum("gecf,efd->gecd", jax.nn.silu(gate) * up,
                            wcast(layer["w_down"], dt))
    out = jnp.einsum("gnec,gecd->gnd", combine.astype(dt), expert_out)
    return x + out.reshape(B, S, D), aux


def moe_forward_hidden(params: dict, tokens: jax.Array, config: MoEConfig,
                       mesh: Mesh | None = None,
                       positions: jax.Array | None = None):
    """tokens (batch, seq) → (final-norm hidden (b, s, d), aux_loss scalar).
    Attention is shared with the dense model (ring/flash/xla dispatch)."""
    c = config
    x = params["embed"].astype(c.compute_dtype)[tokens]
    if positions is None:
        positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
        positions = jnp.broadcast_to(positions, tokens.shape)
    cos, sin = rope_frequencies(c, positions)

    # remat="mlp": checkpoint only the expert FFN (dispatch/combine +
    # expert matmuls dominate saved activations); c/mesh are captured
    # statically by the closure, not traced through the checkpoint
    expert_mlp = (jax.checkpoint(
        lambda x, layer: moe_mlp_block(x, layer, c, mesh=mesh))
        if c.remat == "mlp"
        else (lambda x, layer: moe_mlp_block(x, layer, c, mesh=mesh)))

    def layer_body(carry, layer):
        x, aux = carry
        x = attention_block(x, layer, c, cos, sin, mesh=mesh)
        x = tag_attn_out(x)
        x, layer_aux = expert_mlp(x, layer)
        return (x, aux + layer_aux), None

    body = resolve_layer_remat(c, layer_body)
    (x, aux), _ = lax.scan(body, (x, jnp.float32(0.0)), params["blocks"])

    return rms_norm(x, params["final_norm"]), aux / c.n_layers


def pipelined_moe_forward_hidden(params: dict, tokens: jax.Array,
                                 config: MoEConfig, mesh: Mesh,
                                 n_microbatches: int):
    """MoE forward with the layer stack pipelined over ``pp`` — the MoE
    counterpart of transformer.pipelined_forward. The stage activation is
    a PYTREE {x, aux}: the router load-balance loss accumulates per
    microbatch as it traverses the stages (pipeline_apply carries pytrees
    leaf-by-leaf through the ppermute ring). The expert all-to-all stays
    a GSPMD auto-axis collective inside the pp-manual region: ep is NOT a
    manual axis, so moe_mlp_block's with_sharding_constraint over ep
    works unchanged per stage. pp x sp for MoE is not supported (the
    pytree activation shares one act_spec)."""
    from ..parallel.pipeline import pipeline_apply, split_stages

    c = config
    if mesh.shape.get("sp", 1) > 1:
        raise NotImplementedError("MoE + pp + sp not supported; "
                                  "use pp x ep x tp (+dp/fsdp)")
    # Routing must be MICROBATCH-INVARIANT: groups/capacity are computed
    # from the local token set, so if microbatching changes the effective
    # group size, the same config would train differently on a pp mesh
    # than off it (different overflow drops, different aux statistics) —
    # with n_microbatches, a pure-parallelism knob, silently steering the
    # loss. Demand group sizes that agree and fail loudly otherwise.
    B, S = tokens.shape
    mb = B // n_microbatches
    g_full = (B * S) // num_route_groups(B * S, c.route_group_size)
    g_micro = (mb * S) // num_route_groups(mb * S, c.route_group_size)
    if g_full != g_micro:
        raise ValueError(
            f"pipelined MoE routing would not be microbatch-invariant: "
            f"effective group size {g_full} (full batch) vs {g_micro} "
            f"(microbatch of {mb}x{S} tokens). Pick route_group_size so "
            f"groups align within one microbatch — e.g. "
            f"route_group_size=seq_len ({S}) routes per sequence on any "
            f"mesh.")
    x = params["embed"].astype(c.compute_dtype)[tokens]
    positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
    cos, sin = rope_frequencies(c, positions)
    stages = split_stages(params["blocks"], mesh.shape["pp"])

    expert_mlp = (jax.checkpoint(
        lambda x, layer: moe_mlp_block(x, layer, c, mesh=mesh))
        if c.remat == "mlp"
        else (lambda x, layer: moe_mlp_block(x, layer, c, mesh=mesh)))

    def stage_fn(stage_layers, act, cos, sin):
        def body(carry, layer):
            h, aux = carry
            h = attention_block(h, layer, c, cos, sin, mesh=None)
            h = tag_attn_out(h)
            h, layer_aux = expert_mlp(h, layer)
            return (h, aux + layer_aux), None
        body_fn = resolve_layer_remat(c, body)
        (h, aux), _ = lax.scan(body_fn, (act["x"], act["aux"]),
                               stage_layers)
        return {"x": h, "aux": aux}

    B = tokens.shape[0]
    act = {"x": x, "aux": jnp.zeros((B, 1), jnp.float32)}
    out = pipeline_apply(stages, act, stage_fn, mesh=mesh,
                         n_microbatches=n_microbatches,
                         extra_args=(cos, sin), extra_specs=(P(), P()))
    # per-microbatch scalar aux rode row 0 of each (mb, 1) leaf slice; it
    # is identical across a microbatch's rows by construction (the scan
    # adds the same layer_aux scalar) — mean over batch recovers it
    aux = out["aux"].mean() / c.n_layers
    return rms_norm(out["x"], params["final_norm"]), aux


def moe_forward(params: dict, tokens: jax.Array, config: MoEConfig,
                mesh: Mesh | None = None,
                positions: jax.Array | None = None):
    """tokens (batch, seq) → (logits (b, s, vocab) f32, aux_loss scalar)."""
    x, aux = moe_forward_hidden(params, tokens, config, mesh=mesh,
                                positions=positions)
    return lm_head_logits(x, params["lm_head"]), aux


# ----------------------------------------------------------------- training
def moe_loss_fn(params, tokens, targets, config: MoEConfig, mesh=None,
                ce_chunk_tokens: int = 0, hidden_impl=None):
    """Next-token CE + router load-balance aux. ``ce_chunk_tokens`` > 0
    switches to the fused chunked CE (train.chunked_softmax_ce) so long
    contexts never materialize the full logits tensor. ``hidden_impl``
    swaps the forward (the pipelined stack for pp meshes); default is the
    scanned ``moe_forward_hidden``."""
    hidden_impl = hidden_impl or moe_forward_hidden
    x, aux = hidden_impl(params, tokens, config, mesh=mesh)
    if ce_chunk_tokens:
        from .train import chunked_softmax_ce
        ce = chunked_softmax_ce(x, params["lm_head"], targets,
                                ce_chunk_tokens)
        return ce + config.router_aux_coef * aux
    logits = lm_head_logits(x, params["lm_head"])
    valid = targets >= 0
    safe_targets = jnp.where(valid, targets, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_targets[..., None],
                               axis=-1).squeeze(-1)
    nll = jnp.where(valid, nll, 0.0)
    ce = nll.sum() / jnp.maximum(valid.sum(), 1)
    return ce + config.router_aux_coef * aux


def make_sharded_moe_train_step(mesh: Mesh, config: MoEConfig,
                                tc=None, rules: PartitionRules | None = None,
                                accum_steps: int = 1,
                                n_microbatches: int | None = None):
    """(init_fn, step_fn) jitted over ``mesh`` with dp/fsdp/tp/sp/ep/pp
    shardings — the MoE counterpart of train.make_sharded_train_step
    (which documents the opt-state sharding scheme and the accum_steps
    microbatch contract). With pp>1 the layer stack shards over pp and
    the forward pipelines (pipelined_moe_forward_hidden); the expert
    all-to-all stays an auto-axis collective inside each stage."""
    from .train import (TrainConfig, accumulated_value_and_grad,
                        apply_update, make_optimizer, opt_state_shardings,
                        pipeline_rules)

    pp = mesh.shape.get("pp", 1)
    if pp > 1:
        rules = rules or pipeline_rules()
    tc = tc or TrainConfig()
    rules = rules or PartitionRules()
    optimizer = make_optimizer(tc)
    p_shardings = param_shardings(mesh, moe_param_logical_specs(config), rules)
    batch_sh = batch_sharding(mesh, accum=accum_steps > 1)
    replicated = NamedSharding(mesh, P())
    opt_shardings = opt_state_shardings(
        optimizer, lambda k: init_moe_params(k, config), p_shardings,
        replicated)

    @partial(jax.jit, out_shardings=(p_shardings, opt_shardings))
    def init_fn(key):
        params = init_moe_params(key, config)
        return params, optimizer.init(params)

    # ONE loss dispatch shared with evaluation (train.build_loss): the
    # pipelined hidden for pp meshes, the shared fused-CE engagement
    # policy, aux included (this is the training objective)
    from .train import build_loss
    step_loss = build_loss(mesh, config, tc, n_microbatches)

    @partial(jax.jit,
             in_shardings=(p_shardings, opt_shardings, batch_sh, batch_sh),
             out_shardings=(p_shardings, opt_shardings, replicated),
             donate_argnums=(0, 1))
    def step_fn(params, opt_state, tokens, targets):
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(step_loss)(params, tokens,
                                                        targets)
        else:
            loss, grads = accumulated_value_and_grad(step_loss, params,
                                                     tokens, targets)
        params, opt_state = apply_update(optimizer, params, opt_state, grads)
        return params, opt_state, loss

    return init_fn, step_fn


def count_active_params(config: MoEConfig) -> float:
    """Per-token active parameter count (k of E experts) — the MoE efficiency
    headline."""
    c = config
    attn = c.n_layers * (c.d_model * c.n_heads * c.d_head * 2
                         + c.d_model * c.n_kv_heads * c.d_head * 2)
    mlp_active = c.n_layers * c.experts_per_token * 3 * c.d_model * c.d_ff
    embed = 2 * c.vocab_size * c.d_model
    return attn + mlp_active + embed
