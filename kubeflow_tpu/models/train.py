"""Training step: loss, optimizer wiring, and the sharded update.

The training loop the provisioned notebooks run on their slice. One jitted
function carries the whole step (forward, backward, optimizer) so XLA fuses
and schedules collectives; shardings come from the logical-axis rules, so the
same step runs dp/fsdp/tp/sp configurations unchanged."""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.sharding import (DEFAULT_RULES, PartitionRules,
                                 batch_sharding, param_shardings)
from .transformer import (TransformerConfig, forward, forward_hidden,
                          init_params, param_logical_specs, pipelined_forward)


@dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    weight_decay: float = 0.01
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    warmup_steps: int = 100
    # bf16 model params with f32 master copies held in the optimizer
    # state: forward+backward read/write HALF the weight and gradient HBM
    # bytes per step (the dominant non-activation traffic), while the
    # optimizer update keeps full f32 accumulation on the master copy —
    # standard TPU mixed precision. Costs +1x f32 params of HBM capacity.
    bf16_params: bool = False
    # fused cross-entropy: compute LM-head logits + logsumexp per sequence
    # chunk of this many tokens so the (b, s, vocab) f32 logits tensor never
    # materializes. Engaged automatically only when that tensor would exceed
    # CE_FUSE_THRESHOLD_BYTES: measured on v5e, the whole-logits path is ~4%
    # faster while it fits (XLA fuses the CE well; the chunk recompute costs
    # more than the bandwidth saved), but it stops COMPILING at long context
    # (batch 4 x seq 8192 x vocab 32k = 4 GB logits OOMs; fused runs it).
    # 0 disables fusion entirely.
    ce_chunk_tokens: int = 512
    # single-pass clip+adamw: one tree traversal computes the clip scale
    # application, both moment updates, bias correction, weight decay, and
    # the parameter delta per leaf, instead of optax.chain's staged trees
    # (clip's scaled-grad tree, adamw's mu_hat/nu_hat/update trees). Same
    # math to float tolerance (pinned by tests/test_fused_adamw.py); as a
    # measured MFU lever — whether XLA already fuses optax's stages is a
    # hardware question, answered by ci/tpu_mfu_ab.py.
    fused_adamw: bool = False


# above this per-step logits size the fused chunked CE engages (see
# TrainConfig.ce_chunk_tokens); 1.5 GB keeps comfortable headroom under the
# observed ~4 GB compile-OOM point on a 16 GB v5e
CE_FUSE_THRESHOLD_BYTES = 1.5e9


def make_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, tc.learning_rate, tc.warmup_steps, 10_000)
    if tc.fused_adamw:
        return fused_clip_adamw(schedule, b1=tc.b1, b2=tc.b2,
                                weight_decay=tc.weight_decay,
                                grad_clip=tc.grad_clip)
    return optax.chain(
        optax.clip_by_global_norm(tc.grad_clip),
        optax.adamw(schedule, b1=tc.b1, b2=tc.b2,
                    weight_decay=tc.weight_decay),
    )


class FusedAdamWState(NamedTuple):
    """State of fused_clip_adamw: step count + first/second moments, the
    moment trees shaped like the params (so opt_state_shardings maps them
    onto the param shardings by path suffix, same as optax's mu/nu)."""
    count: jax.Array
    mu: object
    nu: object


def fused_clip_adamw(schedule, *, b1: float, b2: float,
                     weight_decay: float, grad_clip: float,
                     eps: float = 1e-8) -> optax.GradientTransformation:
    """clip_by_global_norm + adamw in ONE pass per leaf.

    optax.chain materializes a full intermediate tree per stage (the
    clipped grads, mu_hat, nu_hat, the pre-decay updates, the decayed
    updates); each is an extra HBM round-trip per parameter unless XLA
    fuses across the stages. This transform computes the global norm
    (the one unavoidable all-leaf reduction), then produces the update
    and both new moments in a single jax.tree.map whose per-leaf body is
    one elementwise chain — trivially one fusion per parameter. Matches
    optax.chain(clip_by_global_norm, adamw) to float tolerance
    (tests/test_fused_adamw.py pins parity)."""

    def init(params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return FusedAdamWState(count=jnp.zeros((), jnp.int32),
                               mu=zeros,
                               nu=jax.tree.map(jnp.zeros_like, params))

    def update(grads, state, params):
        if params is None:
            raise ValueError("fused_clip_adamw requires params "
                             "(weight decay)")
        gnorm = optax.global_norm(grads)
        # optax.clip_by_global_norm semantics: scale only when over
        scale = jnp.minimum(1.0, grad_clip / jnp.maximum(gnorm, 1e-16))
        count = state.count + 1
        # optax.scale_by_schedule evaluates at the PRE-increment count
        # (first step uses schedule(0)); bias correction uses the
        # post-increment count (first step corrects with power 1)
        lr = schedule(state.count)
        # bias correction folded into scalar multipliers, computed once
        c1 = 1.0 / (1.0 - b1 ** count.astype(jnp.float32))
        c2 = 1.0 / (1.0 - b2 ** count.astype(jnp.float32))

        def leaf(g, m, v, p):
            g = g * scale
            m2 = b1 * m + (1.0 - b1) * g
            v2 = b2 * v + (1.0 - b2) * g * g
            upd = -lr * ((m2 * c1) / (jnp.sqrt(v2 * c2) + eps)
                         + weight_decay * p)
            return upd, m2, v2

        out = jax.tree.map(leaf, grads, state.mu, state.nu, params)
        three = jax.tree.transpose(
            jax.tree.structure(grads), jax.tree.structure((0, 0, 0)), out)
        updates, mu, nu = three
        return updates, FusedAdamWState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


def loss_fn(params, tokens, targets, config: TransformerConfig, mesh=None,
            forward_impl=forward):
    """Next-token cross entropy, mean over non-padding (-1 targets)."""
    logits = forward_impl(params, tokens, config, mesh=mesh)
    valid = targets >= 0
    safe_targets = jnp.where(valid, targets, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_targets[..., None],
                               axis=-1).squeeze(-1)
    nll = jnp.where(valid, nll, 0.0)
    return nll.sum() / jnp.maximum(valid.sum(), 1)


def ce_chunk_for(tc: "TrainConfig", tokens: jax.Array,
                 vocab_size: int) -> int:
    """The one fused-CE engagement policy, shared by the dense and MoE
    steps: chunk size to use (0 = whole-logits path), decided by the
    trace-time f32 logits size against CE_FUSE_THRESHOLD_BYTES."""
    logits_bytes = tokens.shape[0] * tokens.shape[1] * vocab_size * 4
    if tc.ce_chunk_tokens and logits_bytes > CE_FUSE_THRESHOLD_BYTES:
        return tc.ce_chunk_tokens
    return 0


def _ce_chunks(seq_len: int, chunk_tokens: int) -> int:
    """Chunk count dividing seq_len with chunks <= chunk_tokens (static)."""
    n = max(1, -(-seq_len // max(chunk_tokens, 1)))
    while seq_len % n:
        n += 1
    return n


def chunked_softmax_ce(x: jax.Array, lm_head: jax.Array,
                       targets: jax.Array, chunk_tokens: int) -> jax.Array:
    """Cross entropy fused with the LM-head projection, chunked over the
    sequence axis: each scan step projects one (b, chunk, d) slice onto the
    vocab, reduces it to logsumexp + target logit, and discards the chunk's
    logits. Peak logits memory drops from (b, s, V) to (b, s/n, V) and the
    full-logits round-trip to HBM disappears; jax.checkpoint on the chunk
    body recomputes the projection in backward (the standard remat trade —
    the LM-head matmul re-runs, the memory win dominates at long context).
    Shared by the dense and MoE loss paths."""
    b, s, d = x.shape
    n = _ce_chunks(s, chunk_tokens)
    xc = jnp.moveaxis(x.reshape(b, n, s // n, d), 1, 0)        # (n, b, c, d)
    tc = jnp.moveaxis(targets.reshape(b, n, s // n), 1, 0)     # (n, b, c)

    def chunk_body(carry, inp):
        nll_sum, n_valid = carry
        xs, ts = inp
        logits = jnp.einsum("bcd,dv->bcv", xs, lm_head.astype(xs.dtype),
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        valid = ts >= 0
        safe = jnp.where(valid, ts, 0)
        target_logit = jnp.take_along_axis(logits, safe[..., None],
                                           axis=-1).squeeze(-1)
        nll = jnp.where(valid, lse - target_logit, 0.0)
        return (nll_sum + nll.sum(), n_valid + valid.sum()), None

    (total, count), _ = lax.scan(jax.checkpoint(chunk_body),
                                 (jnp.float32(0.0), jnp.int32(0)), (xc, tc))
    return total / jnp.maximum(count, 1)


def fused_loss_fn(params, tokens, targets, config: TransformerConfig,
                  mesh=None, chunk_tokens: int = 512):
    """Numerically identical to loss_fn, via chunked_softmax_ce."""
    x = forward_hidden(params, tokens, config, mesh=mesh)
    return chunked_softmax_ce(x, params["lm_head"], targets, chunk_tokens)


def train_step(params, opt_state, tokens, targets, *,
               config: TransformerConfig, optimizer, mesh=None,
               forward_impl=forward):
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets,
                                              config, mesh, forward_impl)
    params, opt_state = apply_update(optimizer, params, opt_state, grads)
    return params, opt_state, loss


def pipeline_rules() -> PartitionRules:
    """Partition rules for pipeline configs: the stacked layer axis shards
    over pp (contiguous layer blocks per stage)."""
    rules = tuple(("layers", "pp") if k == "layers" else (k, v)
                  for k, v in DEFAULT_RULES)
    return PartitionRules(rules=rules)


def accumulated_value_and_grad(loss_fn, params, tokens, targets):
    """Gradient accumulation: scan the microbatches on tokens/targets'
    leading axis, summing grads in place — peak activation memory is one
    microbatch's. The divisor is the actual leading-axis length, so a
    batch shaped differently than the step was configured for cannot
    silently mis-scale. Loss/grads are microbatch means averaged over steps
    (exact for equal valid-token counts, the synthetic/packed case)."""
    def micro(carry, xs):
        loss_acc, grads_acc = carry
        t, tg = xs
        loss, grads = jax.value_and_grad(loss_fn)(params, t, tg)
        return (loss_acc + loss, jax.tree.map(jnp.add, grads_acc, grads)), None

    zeros = jax.tree.map(jnp.zeros_like, params)
    (loss_sum, grads_sum), _ = lax.scan(micro, (jnp.float32(0.0), zeros),
                                        (tokens, targets))
    inv = 1.0 / tokens.shape[0]
    return loss_sum * inv, jax.tree.map(lambda g: g * inv, grads_sum)


def apply_update(optimizer, params, opt_state, grads):
    """The shared optimizer tail: one place to change if the update step
    grows (e.g. grad-norm metrics). Dispatches on the opt-state shape:
    a ``MasterOptState`` means bf16 params + f32 master copies."""
    if isinstance(opt_state, MasterOptState):
        grads32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        updates, inner = optimizer.update(grads32, opt_state.inner,
                                          opt_state.master)
        master = optax.apply_updates(opt_state.master, updates)
        params = jax.tree.map(lambda m, p: m.astype(p.dtype), master, params)
        return params, MasterOptState(inner=inner, master=master)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state


class MasterOptState(NamedTuple):
    """bf16-params training: inner optax state + the f32 master params."""
    inner: object
    master: object


def opt_state_shardings(optimizer, init_params_fn, p_shardings, replicated):
    """Optimizer state mirrors param sharding: optax states embed pytrees
    with the params' structure (adamw mu/nu), so an optimizer-state leaf
    whose path *ends with* a param path gets that param's sharding;
    everything else (counters, scalars) replicates."""
    from jax.tree_util import tree_flatten_with_path

    params_shape = jax.eval_shape(init_params_fn, jax.random.key(0))
    opt_shape = jax.eval_shape(optimizer.init, params_shape)
    param_by_path = {
        tuple(str(k) for k in path): sh
        for (path, sh) in tree_flatten_with_path(p_shardings)[0]}

    leaves, treedef = tree_flatten_with_path(opt_shape)
    out = []
    for path, leaf in leaves:
        keys = tuple(str(k) for k in path)
        sh = replicated
        for start in range(len(keys)):
            if keys[start:] in param_by_path:
                sh = param_by_path[keys[start:]]
                break
        out.append(sh if leaf.ndim > 0 else replicated)
    return jax.tree.unflatten(treedef, out)


def make_sharded_train_step(mesh: Mesh, config: TransformerConfig,
                            tc: TrainConfig | None = None,
                            rules: PartitionRules | None = None,
                            n_microbatches: int | None = None,
                            accum_steps: int = 1):
    """Build (init_fn, step_fn) jitted with NamedShardings over ``mesh``.

    - params/optimizer state shard per the logical-axis rules (fsdp/tp; with
      pp>1 the layer stack shards over pp and the forward pass pipelines);
    - batches shard over (dp, fsdp) × sp;
    - params+opt_state buffers are donated (in-place update, halves HBM);
    - with ``accum_steps`` > 1, step_fn takes (accum, batch, seq)-shaped
      tokens/targets (leading axis unsharded) and accumulates grads over
      the microbatches before one optimizer update.
    """
    tc = tc or TrainConfig()
    pp = mesh.shape.get("pp", 1)
    if pp > 1:
        rules = rules or pipeline_rules()
        n_microbatches = n_microbatches or 2 * pp
    else:
        rules = rules or PartitionRules()
    optimizer = make_optimizer(tc)
    p_shardings = param_shardings(mesh, param_logical_specs(config), rules)
    batch_sh = batch_sharding(mesh, accum=accum_steps > 1)
    replicated = NamedSharding(mesh, P())

    opt_shardings = opt_state_shardings(
        optimizer, lambda k: init_params(k, config), p_shardings, replicated)
    if tc.bf16_params:
        # master copies shard exactly like the params they shadow
        opt_shardings = MasterOptState(inner=opt_shardings,
                                       master=p_shardings)

    @partial(jax.jit, out_shardings=(p_shardings, opt_shardings))
    def init_fn(key):
        params = init_params(key, config)
        if tc.bf16_params:
            master = jax.tree.map(lambda p: p.astype(jnp.float32), params)
            params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
            return params, MasterOptState(inner=optimizer.init(master),
                                          master=master)
        return params, optimizer.init(params)

    # ONE loss dispatch shared with evaluation (build_loss): pp-aware
    # forward selection + the fused-CE gate, which is disabled under pp
    # (the pipelined forward's per-stage LM head exposes no hidden states)
    step_loss = build_loss(mesh, config, tc, n_microbatches)

    @partial(jax.jit,
             in_shardings=(p_shardings, opt_shardings, batch_sh, batch_sh),
             out_shardings=(p_shardings, opt_shardings, replicated),
             donate_argnums=(0, 1))
    def step_fn(params, opt_state, tokens, targets):
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(step_loss)(params, tokens,
                                                        targets)
            params, opt_state = apply_update(optimizer, params, opt_state,
                                             grads)
            return params, opt_state, loss
        loss, grads = accumulated_value_and_grad(step_loss, params, tokens,
                                                 targets)
        params, opt_state = apply_update(optimizer, params, opt_state, grads)
        return params, opt_state, loss

    return init_fn, step_fn


def pipelined_forward_adapter(params, tokens, config, mesh=None, *,
                              n_microbatches):
    return pipelined_forward(params, tokens, config, mesh, n_microbatches)


def build_loss(mesh: Mesh, config: TransformerConfig,
               tc: TrainConfig | None = None,
               n_microbatches: int | None = None,
               include_aux: bool = True):
    """THE loss dispatch — one place for pp-aware forward selection and
    the fused-CE gate (disabled for the dense pipelined path, whose
    per-stage LM head exposes no hidden states). Both train factories
    and Trainer.evaluate build their loss from here, so the engagement
    policy cannot drift between training and evaluation.

    ``include_aux=False`` (evaluation) excludes the MoE router aux — a
    training regularizer; with it, exp(loss) would not be a perplexity.
    Returns ``loss(params, tokens, targets) -> scalar``."""
    import dataclasses

    from .moe import MoEConfig, moe_loss_fn, pipelined_moe_forward_hidden

    tc = tc or TrainConfig()
    pp = mesh.shape.get("pp", 1)
    n_micro = n_microbatches or 2 * pp

    if isinstance(config, MoEConfig):
        loss_config = config if include_aux else \
            dataclasses.replace(config, router_aux_coef=0.0)
        if pp > 1:
            def hidden_impl(p, t, c, mesh=mesh):
                return pipelined_moe_forward_hidden(p, t, c, mesh, n_micro)
        else:
            hidden_impl = None   # moe_loss_fn's default scanned forward

        def _loss(params, tokens, targets):
            chunk = ce_chunk_for(tc, tokens, loss_config.vocab_size)
            return moe_loss_fn(params, tokens, targets, loss_config,
                               mesh, ce_chunk_tokens=chunk,
                               hidden_impl=hidden_impl)
        return _loss

    fwd = partial(pipelined_forward_adapter, n_microbatches=n_micro) \
        if pp > 1 else forward

    def _loss(params, tokens, targets):
        chunk = ce_chunk_for(tc, tokens, config.vocab_size) \
            if pp == 1 else 0
        if chunk:
            return fused_loss_fn(params, tokens, targets, config, mesh,
                                 chunk_tokens=chunk)
        return loss_fn(params, tokens, targets, config, mesh, fwd)
    return _loss


def build_eval_loss(mesh: Mesh, config: TransformerConfig,
                    tc: TrainConfig | None = None,
                    n_microbatches: int | None = None):
    """build_loss with the MoE aux excluded — what evaluate() jits."""
    return build_loss(mesh, config, tc, n_microbatches,
                      include_aux=False)
