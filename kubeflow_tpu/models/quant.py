"""Int8 weight-only quantization for the serving path.

Decode on one chip is HBM-bandwidth bound: every generated token re-reads
the full weight set, so weight bytes ARE the decode speed ceiling.
Symmetric per-output-channel int8 halves the bf16 traffic (v5e measured:
1.25-1.4x end-to-end decode tokens/s — bench.py's
``decode_int8_tokens_per_sec`` — at ~3% model-level logits relative
error; the isolated lm-head matmul times 1.5x at ~1% error).

Design:
- a quantized weight is a plain pytree node ``{"q": int8, "s": f32}`` with
  the scale keeping reduced dims (``keepdims``), so ``jax.tree`` slicing
  over the stacked layer axis (decode's per-layer ``a[i]``) slices ``q``
  and ``s`` coherently;
- dequantization happens at the consumption site via :func:`wcast`, which
  is a no-op ``astype`` for regular arrays — the training path pays
  nothing; XLA fuses the int8 convert+multiply into the matmul's operand
  load, so only int8 bytes cross HBM;
- only matmul weights quantize. The embedding stays full precision (it is
  a gather — per-step traffic is batch rows, not the table) and the tiny
  norm vectors are irrelevant.

The reference has no model code (SURVEY §2d); this is part of the TPU
workload layer the controllers provision.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# contraction axes per weight leaf: the scale is computed over the axes the
# matmul reduces, yielding one scale per OUTPUT channel (keepdims=True)
_BLOCK_AXES = {
    "wq": (1,),        # (L, d, h, k)   contracts d
    "wk": (1,),
    "wv": (1,),
    "wo": (1, 2),      # (L, h, k, d)   contracts (h, k)
    "w_gate": (1,),    # (L, d, f)      contracts d
    "w_up": (1,),
    "w_down": (1,),    # (L, f, d)      contracts f
}

# MoE expert weights carry an (L, E, ...) experts axis: scales are
# per-expert per-output-channel. The router projection stays full
# precision — routing decisions (argmax over E) are far more sensitive to
# quantization than the expert FFN values, and it is tiny (d x E).
_MOE_BLOCK_AXES = {
    **_BLOCK_AXES,     # attention weights are identical in both families
    "w_gate": (2,),    # (L, E, d, f)   contracts d
    "w_up": (2,),
    "w_down": (2,),    # (L, E, f, d)   contracts f
}


def is_quantized(w) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def quantize_weight(w: jax.Array, axes: tuple[int, ...]) -> dict:
    """Symmetric int8 over ``axes`` with per-output-channel scales."""
    w32 = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(w32), axis=axes, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-12)  # all-zero channels stay zero
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale}


def quantize_params(params: dict) -> dict:
    """Training/serving params → int8 weight-only serving params. The
    returned tree drops the f32 masters for the quantized leaves (the
    memory saving is part of the point: a 4x smaller serving footprint).

    MoE params quantize with expert-axis-aware scales (per-expert
    per-output-channel); the router projection stays full precision —
    top-k routing decisions are more quantization-sensitive than the
    expert FFN values, and the router is tiny."""
    if is_quantized(params.get("lm_head")):
        return params  # already quantized: idempotent
    blocks = params["blocks"]
    is_moe = "router" in blocks
    axes_map = _MOE_BLOCK_AXES if is_moe else _BLOCK_AXES
    out = dict(params)
    out["blocks"] = {
        name: (quantize_weight(w, axes_map[name])
               if name in axes_map else w)
        for name, w in blocks.items()
    }
    out["lm_head"] = quantize_weight(params["lm_head"], (0,))  # (d, v)
    return out


def wcast(w, dtype) -> jax.Array:
    """Resolve a weight for compute: plain arrays cast (the existing
    behavior, free for unquantized params); quantized nodes dequantize —
    XLA fuses the convert+scale into the matmul operand load, so HBM sees
    int8 bytes."""
    if is_quantized(w):
        return (w["q"].astype(dtype) * w["s"].astype(dtype))
    return w.astype(dtype)
