from .transformer import TransformerConfig, init_params, forward, param_logical_specs

__all__ = ["TransformerConfig", "init_params", "forward", "param_logical_specs"]
