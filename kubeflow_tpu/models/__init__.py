from .transformer import TransformerConfig, init_params, forward, param_logical_specs
from .moe import MoEConfig, init_moe_params, moe_forward, moe_param_logical_specs
from .decode import (init_kv_cache, prefill, decode_step, decode_window,
                     generate)
from .speculative import SpecStats, speculative_generate
from .lora import (LoRAConfig, init_lora_params, lora_logical_specs,
                   make_sharded_lora_step, merge_lora)

__all__ = ["TransformerConfig", "init_params", "forward", "param_logical_specs",
           "MoEConfig", "init_moe_params", "moe_forward",
           "moe_param_logical_specs",
           "init_kv_cache", "prefill", "decode_step", "decode_window",
           "generate", "SpecStats", "speculative_generate",
           "LoRAConfig", "init_lora_params", "lora_logical_specs",
           "make_sharded_lora_step", "merge_lora"]
