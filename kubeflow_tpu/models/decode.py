"""Autoregressive decoding: KV cache, prefill, single-token step, generate.

The inference surface of the model families — what a provisioned notebook
runs when serving/sampling rather than training (the reference provisions
Jupyter images and has no model code, SURVEY §2d).

TPU-first decode:
- the KV cache is preallocated at ``max_seq_len`` and updated in place with
  ``lax.dynamic_update_slice`` — static shapes, no concatenation growth, so
  the decode step compiles once and XLA keeps the cache in HBM across steps
  (donated through lax.scan's carry);
- the causal structure at decode time is a position mask over the full cache
  (compare against ``arange(max_seq)``), not a data-dependent slice;
- generation is one ``lax.scan`` over decode steps — a single compiled loop,
  no per-token Python dispatch;
- GQA caches the un-repeated kv_heads (memory ∝ n_kv_heads, the point of
  GQA); heads are repeated after the cache read.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .moe import MoEConfig, moe_mlp_block
from .quant import wcast
from .transformer import (TransformerConfig, apply_rope, attention_block,
                          mlp_block, rms_norm, rope_frequencies)


def _mlp(x: jax.Array, layer: dict, config: TransformerConfig) -> jax.Array:
    """Dense or sparse MLP by config type. At decode time the MoE router
    sees one token per sequence (N = batch), so per-step expert capacity is
    ceil(batch/E·factor·k) — with a non-binding capacity (the usual serving
    setup) decode logits match the full forward exactly; the aux loss is a
    training quantity and is dropped here."""
    if isinstance(config, MoEConfig):
        x, _ = moe_mlp_block(x, layer, config)
        return x
    return mlp_block(x, layer, config)


# ------------------------------------------------------------------- cache
def init_kv_cache(config: TransformerConfig, batch: int,
                  kv_quant: bool = False) -> dict:
    """Zeroed (layers, batch, max_seq, kv_heads, d_head) K/V buffers.

    ``kv_quant``: int8 buffers + per-(position, kv_head) f32 scales.
    Decode attention is KV-bandwidth bound at long context (the cache is
    re-read every token); int8 halves those bytes while weights quantize
    independently (models/quant.py). Scales are amax over d_head at write
    time — one scalar per written position per kv head."""
    c = config
    shape = (c.n_layers, batch, c.max_seq_len, c.n_kv_heads, c.d_head)
    if not kv_quant:
        return {
            "k": jnp.zeros(shape, c.compute_dtype),
            "v": jnp.zeros(shape, c.compute_dtype),
        }
    return {
        "k": jnp.zeros(shape, jnp.int8),
        "v": jnp.zeros(shape, jnp.int8),
        "k_scale": jnp.zeros(shape[:-1], jnp.float32),
        "v_scale": jnp.zeros(shape[:-1], jnp.float32),
    }


def is_kv_quantized(cache: dict) -> bool:
    return "k_scale" in cache


def _quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(..., d_head) → int8 values + (...,) f32 amax/127 scales."""
    x32 = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(x32), axis=-1), 1e-12) / 127.0
    q = jnp.clip(jnp.round(x32 / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def _write_cache(cache_layer: dict, k: jax.Array, v: jax.Array,
                 start: jax.Array, layer: int | None = None) -> dict:
    """Write (b, s, h, d) K/V into a layer cache at sequence offset
    ``start``. With ``layer`` set, the cache is the stacked
    (L, b, max_seq, h, d) form and the write targets that layer (the
    decode_step unrolled-loop path). Quantized caches quantize at the
    write and store the per-position scales alongside."""
    zero = jnp.int32(0)
    idx = (zero, jnp.asarray(start, jnp.int32), zero, zero)
    sidx = idx[:-1]
    if layer is not None:
        idx = (jnp.int32(layer), *idx)
        sidx = (jnp.int32(layer), *sidx)
        k, v = k[None], v[None]
    if not is_kv_quantized(cache_layer):
        return {
            "k": lax.dynamic_update_slice(cache_layer["k"], k, idx),
            "v": lax.dynamic_update_slice(cache_layer["v"], v, idx),
        }
    qk, sk = _quantize_kv(k)
    qv, sv = _quantize_kv(v)
    return {
        "k": lax.dynamic_update_slice(cache_layer["k"], qk, idx),
        "v": lax.dynamic_update_slice(cache_layer["v"], qv, idx),
        "k_scale": lax.dynamic_update_slice(cache_layer["k_scale"], sk, sidx),
        "v_scale": lax.dynamic_update_slice(cache_layer["v_scale"], sv, sidx),
    }


def _write_cache_rows(stacked: dict, k: jax.Array, v: jax.Array,
                      pos: jax.Array, layer: int) -> dict:
    """Per-row single-position write: (b, 1, h, d) K/V lands at row b's own
    ``pos[b]`` (continuous batching — every sequence is at a different
    depth). Scatter via advanced indexing; XLA lowers it in place."""
    rows = jnp.arange(k.shape[0])
    if not is_kv_quantized(stacked):
        return {
            "k": stacked["k"].at[layer, rows, pos].set(k[:, 0]),
            "v": stacked["v"].at[layer, rows, pos].set(v[:, 0]),
        }
    qk, sk = _quantize_kv(k)
    qv, sv = _quantize_kv(v)
    return {
        "k": stacked["k"].at[layer, rows, pos].set(qk[:, 0]),
        "v": stacked["v"].at[layer, rows, pos].set(qv[:, 0]),
        "k_scale": stacked["k_scale"].at[layer, rows, pos].set(sk[:, 0]),
        "v_scale": stacked["v_scale"].at[layer, rows, pos].set(sv[:, 0]),
    }


def _read_cache_layer(stacked: dict, i: int, dt) -> tuple[jax.Array,
                                                          jax.Array]:
    """Layer ``i``'s (B, S, G, D) K/V in compute dtype. Quantized caches
    dequantize here — XLA fuses convert+scale into the attention matmul's
    operand load, so HBM traffic is the int8 bytes."""
    ck, cv = stacked["k"][i], stacked["v"][i]
    if is_kv_quantized(stacked):
        ck = ck.astype(dt) * stacked["k_scale"][i][..., None].astype(dt)
        cv = cv.astype(dt) * stacked["v_scale"][i][..., None].astype(dt)
    return ck, cv


# ----------------------------------------------------------------- prefill
def prefill(params: dict, tokens: jax.Array, config: TransformerConfig,
            kv_quant: bool = False):
    """Run the prompt through a fresh KV cache.

    tokens: (batch, prompt_len) → (logits (batch, vocab) for the LAST
    position, cache). Reuses the training forward's attention block
    (return_kv) so prefill stays a large, MXU-friendly batched pass; prompt
    lengths with no TPU-tileable divisor fall back to XLA attention inside
    flash_attention itself (ops/attention.py _pick_block)."""
    c = config
    B, S = tokens.shape
    cache = init_kv_cache(c, B, kv_quant=kv_quant)
    x = params["embed"].astype(c.compute_dtype)[tokens]
    positions = jnp.broadcast_to(
        jnp.arange(S, dtype=jnp.int32)[None, :], tokens.shape)
    cos, sin = rope_frequencies(c, positions)

    def layer_body(x, layer_and_cache):
        layer, cache_layer = layer_and_cache
        x, (k, v) = attention_block(x, layer, c, cos, sin, return_kv=True)
        cache_layer = _write_cache(cache_layer, k, v, 0)
        x = _mlp(x, layer, c)
        return x, cache_layer

    x, new_cache = lax.scan(layer_body, x, (params["blocks"], cache))
    x = rms_norm(x, params["final_norm"])
    logits = jnp.einsum("bd,dv->bv", x[:, -1], wcast(params["lm_head"], x.dtype))
    return logits.astype(jnp.float32), new_cache


def uses_flash_decode(config: TransformerConfig) -> bool:
    """Whether decode_step dispatches to the Pallas flash-decode kernel —
    streaming the cache HBM→VMEM instead of materializing (B, G, rep, 1, S)
    logits, the long-KV bandwidth path. "auto" engages on TPU once the
    cache is long enough for the einsum's extra HBM round-trip to matter.
    The ONE predicate: serving's spec_exact_only gate keys off it too (the
    verify window is always the einsum path, so kernel-mix bit divergence
    is possible exactly when this returns True)."""
    c = config
    return c.decode_attention == "flash" or (
        c.decode_attention == "auto" and jax.default_backend() == "tpu"
        and c.max_seq_len >= 2048)


# -------------------------------------------------------------- decode step
def decode_step(params: dict, cache: dict, token: jax.Array,
                pos: jax.Array, config: TransformerConfig):
    """One token in, next-token logits out.

    token: (batch,) int32; pos: scalar int32 (all rows at the same depth —
    the generate loop) or (batch,) int32 per-row positions (continuous
    batching: every sequence at its own depth). Attention runs over the
    full static cache with a ``<= pos`` mask.

    The layer loop is UNROLLED (not lax.scan): scanning over the stacked
    (L, B, S, G, D) cache forces per-layer dynamic-slice reads, a restacking
    write, and full cache copies every step — profiled at ~80% of decode
    wall time on v5e (copy + slice/update fusions ≈ 2 ms of a 2.5 ms step).
    With static layer indices the cache updates are single-position
    dynamic-update-slices XLA aliases in place across the outer generate
    scan; the unrolled compile covers n_layers identical bodies, a one-off
    cost the serving path amortizes."""
    c = config
    B = token.shape[0]
    pos32 = jnp.asarray(pos, jnp.int32)
    per_row = pos32.ndim == 1
    x = params["embed"].astype(c.compute_dtype)[token][:, None, :]  # (B,1,D)
    if per_row:
        positions = pos32[:, None]                           # (B, 1)
        valid = jnp.arange(c.max_seq_len, dtype=jnp.int32)[None, None,
                                                           None, :] \
            <= pos32[:, None, None, None]                    # (B,1,1,S)
    else:
        positions = jnp.broadcast_to(pos32[None, None], (B, 1))
        valid = jnp.arange(c.max_seq_len, dtype=jnp.int32)[None, None,
                                                           None, :] \
            <= pos32                                         # (1,1,1,S)
    cos, sin = rope_frequencies(c, positions)
    scale = 1.0 / math.sqrt(c.d_head)

    rep = c.n_heads // c.n_kv_heads
    stacked = dict(cache)                            # (L, B, S, G, D) (+scales)
    use_flash = uses_flash_decode(c)
    pos_vec = pos32 if per_row else jnp.broadcast_to(pos32, (B,))

    for i in range(c.n_layers):
        layer = jax.tree.map(lambda a: a[i], params["blocks"])
        h = rms_norm(x, layer["attn_norm"])
        dt = h.dtype
        q = jnp.einsum("bsd,dhk->bshk", h, wcast(layer["wq"], dt))
        k = jnp.einsum("bsd,dhk->bshk", h, wcast(layer["wk"], dt))
        v = jnp.einsum("bsd,dhk->bshk", h, wcast(layer["wv"], dt))
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        if per_row:
            stacked = _write_cache_rows(stacked, k, v, pos32, layer=i)
        else:
            stacked = _write_cache(stacked, k, v, pos32, layer=i)
        # grouped GQA: q heads fold to (kv_heads, rep) and contract against
        # the UN-repeated cache — head h reads kv head h//rep, matching
        # repeat_kv's layout, without materializing a rep× cache copy (the
        # KV-bandwidth saving is the point of GQA)
        B_, _, H_, D_ = q.shape
        if use_flash:
            from ..ops.decode_attention import flash_decode_attention
            quant = is_kv_quantized(stacked)
            out = flash_decode_attention(
                q[:, 0].reshape(B_, c.n_kv_heads, rep, D_),
                stacked["k"][i], stacked["v"][i], pos_vec,
                k_scale=stacked["k_scale"][i] if quant else None,
                v_scale=stacked["v_scale"][i] if quant else None)
            out = out.reshape(B_, H_, D_)[:, None].astype(dt)
        else:
            qg = q.reshape(B_, 1, c.n_kv_heads, rep, D_)
            ck, cv = _read_cache_layer(stacked, i, dt)   # (B, S, G, D)
            logits = jnp.einsum("bqgrd,bkgd->bgrqk", qg, ck,
                                preferred_element_type=jnp.float32) * scale
            logits = jnp.where(valid[:, :, None], logits, -jnp.inf)
            probs = jax.nn.softmax(logits, axis=-1).astype(dt)
            out = jnp.einsum("bgrqk,bkgd->bqgrd", probs, cv).reshape(
                B_, 1, H_, D_)
        x = x + jnp.einsum("bshk,hkd->bsd", out, wcast(layer["wo"], dt))
        x = _mlp(x, layer, c)

    x = rms_norm(x, params["final_norm"])
    logits = jnp.einsum("bd,dv->bv", x[:, 0], wcast(params["lm_head"], x.dtype))
    return logits.astype(jnp.float32), stacked


def _write_cache_window_rows(stacked: dict, k: jax.Array, v: jax.Array,
                             pos: jax.Array, layer: int) -> dict:
    """Per-row W-position write: (B, W, h, d) K/V lands at row b's
    ``pos[b] .. pos[b]+W-1`` (the speculative-verify window — every row at
    its own depth). Advanced-indexing scatter like _write_cache_rows."""
    B, W = k.shape[:2]
    rows = jnp.arange(B)[:, None]
    cols = pos[:, None] + jnp.arange(W)[None, :]
    if not is_kv_quantized(stacked):
        return {
            "k": stacked["k"].at[layer, rows, cols].set(k),
            "v": stacked["v"].at[layer, rows, cols].set(v),
        }
    qk, sk = _quantize_kv(k)
    qv, sv = _quantize_kv(v)
    return {
        "k": stacked["k"].at[layer, rows, cols].set(qk),
        "v": stacked["v"].at[layer, rows, cols].set(qv),
        "k_scale": stacked["k_scale"].at[layer, rows, cols].set(sk),
        "v_scale": stacked["v_scale"].at[layer, rows, cols].set(sv),
    }


def decode_window(params: dict, cache: dict, tokens: jax.Array,
                  pos: jax.Array, config: TransformerConfig):
    """W tokens in, W next-token logits out — the speculative-verify step.

    tokens: (B, W) consumed at positions ``pos[b] .. pos[b]+W-1``;
    logits[:, i] is the next-token distribution after consuming
    tokens[:, :i+1] (so ``decode_step`` is the W=1 case). One batched
    MXU-friendly forward scores a whole drafted block — the reason
    speculative decoding pays: W sequential target decode steps collapse
    into one pass whose matmuls re-read the weights ONCE.

    Attention is the einsum path with a two-part mask: full prefix
    (``s <= pos+i``) plus causal structure inside the window. W is small
    (the draft depth + 1), so the (B, G, rep, W, S) logits tensor stays
    tiny — the flash-decode kernel's streaming form isn't needed here.
    """
    c = config
    B, W = tokens.shape
    pos32 = jnp.asarray(pos, jnp.int32)
    if pos32.ndim == 0:
        pos32 = jnp.broadcast_to(pos32, (B,))
    x = params["embed"].astype(c.compute_dtype)[tokens]        # (B, W, D)
    positions = pos32[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
    cos, sin = rope_frequencies(c, positions)
    scale = 1.0 / math.sqrt(c.d_head)
    # key position s is visible to window query i iff s <= pos + i
    s_idx = jnp.arange(c.max_seq_len, dtype=jnp.int32)
    valid = s_idx[None, None, :] <= positions[:, :, None]      # (B, W, S)

    rep = c.n_heads // c.n_kv_heads
    stacked = dict(cache)
    for i in range(c.n_layers):
        layer = jax.tree.map(lambda a: a[i], params["blocks"])
        h = rms_norm(x, layer["attn_norm"])
        dt = h.dtype
        q = jnp.einsum("bsd,dhk->bshk", h, wcast(layer["wq"], dt))
        k = jnp.einsum("bsd,dhk->bshk", h, wcast(layer["wk"], dt))
        v = jnp.einsum("bsd,dhk->bshk", h, wcast(layer["wv"], dt))
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        stacked = _write_cache_window_rows(stacked, k, v, pos32, layer=i)
        B_, _, H_, D_ = q.shape
        qg = q.reshape(B_, W, c.n_kv_heads, rep, D_)
        ck, cv = _read_cache_layer(stacked, i, dt)             # (B, S, G, D)
        logits = jnp.einsum("bwgrd,bsgd->bgrws", qg, ck,
                            preferred_element_type=jnp.float32) * scale
        logits = jnp.where(valid[:, None, None], logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1).astype(dt)
        out = jnp.einsum("bgrws,bsgd->bwgrd", probs, cv).reshape(
            B_, W, H_, D_)
        x = x + jnp.einsum("bshk,hkd->bsd", out, wcast(layer["wo"], dt))
        x = _mlp(x, layer, c)

    x = rms_norm(x, params["final_norm"])
    logits = jnp.einsum("bwd,dv->bwv", x, wcast(params["lm_head"], x.dtype))
    return logits.astype(jnp.float32), stacked


# ---------------------------------------------------------------- generate
def top_k_top_p_mask(logits: jax.Array, top_k: jax.Array,
                     top_p: jax.Array) -> jax.Array:
    """Mask logits outside the top-k / nucleus (top-p) sets to -inf.

    Both knobs are TRACED per-row (batch,) vectors — one compiled executable
    covers every setting, matching the temperature contract:
    - top_k <= 0 disables the k-cut for that row;
    - top_p >= 1 disables the nucleus cut.
    Static shapes throughout: O(V log V) sorts over the vocab (tiny next to
    a decode step's matmuls), rank/cumulative-mass comparisons instead of
    dynamic gathers."""
    order = jnp.argsort(-logits, axis=-1)                        # desc
    sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
    ranks = jnp.argsort(order, axis=-1)                          # 0 = best
    keep = jnp.ones_like(logits, dtype=bool)
    k = top_k[:, None]
    keep &= jnp.where(k > 0, ranks < k, True)
    # nucleus: keep the smallest prefix of the sorted probs with mass >= p —
    # a token stays if the cumulative mass BEFORE it is < p
    probs_sorted = jax.nn.softmax(sorted_logits, axis=-1)
    cum_before = jnp.cumsum(probs_sorted, axis=-1) - probs_sorted
    mass_before = jnp.take_along_axis(cum_before, ranks, axis=-1)
    keep &= jnp.where(top_p[:, None] < 1.0,
                      mass_before < top_p[:, None], True)
    return jnp.where(keep, logits, -jnp.inf)


def sample_token(logits: jax.Array, key: jax.Array,
                 temperature: jax.Array, top_k: jax.Array,
                 top_p: jax.Array) -> jax.Array:
    """One sampling decision per row: greedy at temperature 0, else
    temperature-scaled top-k/top-p sampling. All knobs are traced (batch,)
    vectors — mixed greedy/sampled batches share one executable. Shared by
    ``generate``'s scan and the continuous-batching engine."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # temperature first, THEN the k/p cuts (the standard order: the
    # nucleus is computed on the temperature-scaled distribution)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    filtered = top_k_top_p_mask(scaled, top_k, top_p)
    sampled = jax.random.categorical(key, filtered, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


@partial(jax.jit, static_argnames=("config", "max_new_tokens", "kv_quant"))
def generate(params: dict, prompt: jax.Array, config: TransformerConfig,
             max_new_tokens: int, temperature: float = 0.0,
             key: jax.Array | None = None, top_k: int = 0,
             top_p: float = 1.0, eos_id: int | None = None,
             pad_id: int = 0, kv_quant: bool = False) -> jax.Array:
    """Greedy (temperature=0), temperature, top-k, and/or nucleus sampling.

    prompt: (batch, prompt_len) → (batch, max_new_tokens). One prefill pass,
    then a single scanned decode loop. ``temperature``/``top_k``/``top_p``
    are traced (serving varies them per request — one compiled executable
    covers all values; the greedy/sampled choice is a jnp.where, not a
    recompile) and may be scalars or per-row (batch,) vectors (mixed
    batches).

    ``eos_id``: sequences that emit it keep their static shape — every
    position after the first EOS holds ``pad_id`` (the loop still runs
    max_new_tokens steps; per-row early exit would be a dynamic shape).

    ``kv_quant``: int8 KV cache with per-position scales (activations stay
    bf16) — half the cache bytes re-read every token, the long-KV decode
    bandwidth lever; composes with int8 weights (models/quant.py)."""
    c = config
    B, prompt_len = prompt.shape
    if prompt_len + max_new_tokens > c.max_seq_len:
        raise ValueError(
            f"prompt_len {prompt_len} + max_new_tokens {max_new_tokens} "
            f"exceeds max_seq_len {c.max_seq_len}")
    if key is None:
        key = jax.random.key(0)
    temperature = jnp.broadcast_to(
        jnp.asarray(temperature, jnp.float32), (B,))
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (B,))
    top_p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (B,))

    logits, cache = prefill(params, prompt, c, kv_quant=kv_quant)

    def pick(logits, k):
        return sample_token(logits, k, temperature, top_k, top_p)

    def step(carry, i):
        logits, cache, key, done = carry
        key, sub = jax.random.split(key)
        token = pick(logits, sub)
        if eos_id is not None:
            token = jnp.where(done, jnp.int32(pad_id), token)
            done = done | (token == eos_id)
        logits, cache = decode_step(params, cache, token,
                                    prompt_len + i, c)
        return (logits, cache, key, done), token

    done0 = jnp.zeros((B,), dtype=bool)
    # scan N-1 steps; the last token needs only a pick from the carried
    # logits, not another full model step
    (logits, _, key, done), tokens = lax.scan(
        step, (logits, cache, key, done0),
        jnp.arange(max_new_tokens - 1, dtype=jnp.int32))
    _, sub = jax.random.split(key)
    last = pick(logits, sub)
    if eos_id is not None:
        last = jnp.where(done, jnp.int32(pad_id), last)
    tokens = jnp.concatenate([tokens, last[None]], axis=0)
    return tokens.T  # (steps, batch) → (batch, steps)
