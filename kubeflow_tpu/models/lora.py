"""LoRA finetuning: low-rank adapters over the frozen base model.

What a provisioned notebook actually does with a pretrained checkpoint:
finetune it cheaply. LoRA (Hu et al. 2021) freezes the base weights and
trains a rank-r delta W + (alpha/r)·A@B per target matrix — optimizer
state shrinks from 2 f32 copies of every weight to 2 copies of the
adapters (hundreds× smaller at r=8 on the flagship), and checkpoints of
a finetune are megabytes, not gigabytes.

TPU-first shape:
- the adapters MERGE into the base weights inside the jitted step
  (``merge_lora``): one fused einsum per target produces the effective
  weight, so the forward/backward is EXACTLY the base model's compute
  graph — flash kernels, remat policies, fused CE, pipeline/ring paths
  all apply unchanged, and XLA sees static shapes it already knows how
  to schedule. ``lax.stop_gradient`` on the base keeps autodiff from
  materializing base-weight gradients (the merge's extra weight copy is
  transient and fused);
- adapter shapes carry the stacked ``layers`` axis like every block
  weight, so they ride the same scans and the same logical-axis
  sharding machinery: A's input axis and B's output axes take the BASE
  weight's rules (tp/fsdp), the rank axis stays unsharded
  (``lora_logical_specs``);
- serving needs no LoRA code: ``merge_lora`` once on the host and the
  merged tree feeds generate/speculation/the engines as a plain model.

B initializes to zero (the standard: the delta starts as the identity),
so a freshly-initialized adapter reproduces the base model bit-for-bit —
pinned by tests/test_lora.py.

The reference provisions Jupyter images and has no model code
(SURVEY §2d); this belongs to the workload layer those images run.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from .transformer import TransformerConfig

# per target: (input logical axes, output logical axes) of the base weight
# (param_logical_specs minus the leading "layers")
_TARGET_AXES = {
    "wq": (("embed",), ("heads", "head_dim")),
    "wk": (("embed",), ("kv_heads", "head_dim")),
    "wv": (("embed",), ("kv_heads", "head_dim")),
    "wo": (("heads", "head_dim"), ("embed",)),
    "w_gate": (("embed",), ("mlp",)),
    "w_up": (("embed",), ("mlp",)),
    "w_down": (("mlp",), ("embed",)),
}


@dataclass(frozen=True)
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: tuple = ("wq", "wk", "wv", "wo")

    def __post_init__(self):
        if self.rank < 1:
            raise ValueError(f"rank must be >= 1, got {self.rank}")
        unknown = set(self.targets) - set(_TARGET_AXES)
        if unknown:
            raise ValueError(f"unknown LoRA targets {sorted(unknown)}; "
                             f"valid: {sorted(_TARGET_AXES)}")

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def _axis_dim(config: TransformerConfig, axis: str) -> int:
    """Logical axis name → its dimension on this config (the one source
    of truth tying _TARGET_AXES to concrete adapter shapes)."""
    return {"embed": config.d_model, "heads": config.n_heads,
            "kv_heads": config.n_kv_heads, "head_dim": config.d_head,
            "mlp": config.d_ff}[axis]


def _target_dims(config: TransformerConfig, name: str):
    """(in_shape, out_shape) of one layer's base weight, sans layers —
    derived from _TARGET_AXES so targets have a single definition."""
    in_axes, out_axes = _TARGET_AXES[name]
    return (tuple(_axis_dim(config, a) for a in in_axes),
            tuple(_axis_dim(config, a) for a in out_axes))


def init_lora_params(key: jax.Array, config: TransformerConfig,
                     lora: LoRAConfig) -> dict:
    """{"blocks": {target: {"A": (L, *in, r), "B": (L, r, *out)}}}.

    A ~ N(0, 1/in_features) (the base init's fan-in convention), B = 0:
    the initial delta is exactly zero."""
    c = config
    pdt = jnp.dtype(c.param_dtype)
    keys = jax.random.split(key, len(lora.targets))
    blocks = {}
    for k, name in zip(keys, sorted(lora.targets)):
        in_shape, out_shape = _target_dims(c, name)
        fan_in = 1
        for d in in_shape:
            fan_in *= d
        blocks[name] = {
            "A": jax.random.normal(
                k, (c.n_layers, *in_shape, lora.rank), pdt) /
            jnp.sqrt(jnp.float32(fan_in)).astype(pdt),
            "B": jnp.zeros((c.n_layers, lora.rank, *out_shape), pdt),
        }
    return {"blocks": blocks}


def lora_logical_specs(config: TransformerConfig, lora: LoRAConfig) -> dict:
    """Logical-axis names per adapter leaf: the base weight's rules on
    the input/output axes, the rank axis unsharded — feed to
    parallel.param_shardings like any other spec tree."""
    blocks = {}
    for name in sorted(lora.targets):
        in_axes, out_axes = _TARGET_AXES[name]
        blocks[name] = {
            "A": ("layers", *in_axes, None),
            "B": ("layers", None, *out_axes),
        }
    return {"blocks": blocks}


def merge_lora(params: dict, lora_params: dict,
               lora: LoRAConfig) -> dict:
    """Base params + (alpha/r)·A@B per target — the effective weights.

    Inside a jitted step this is one fused einsum per target; on the
    host it bakes a servable plain-model tree."""
    blocks = dict(params["blocks"])
    for name, ab in lora_params["blocks"].items():
        delta = _rank_contract(ab["A"], ab["B"])
        blocks[name] = blocks[name] + lora.scale * \
            delta.astype(blocks[name].dtype)
    return {**params, "blocks": blocks}


def _rank_contract(A: jax.Array, B: jax.Array) -> jax.Array:
    """(L, *in, r) × (L, r, *out) → (L, *in, *out) via one reshape-matmul
    (einsum subscripts cannot express two variadic groups)."""
    L = A.shape[0]
    r = A.shape[-1]
    in_shape = A.shape[1:-1]
    out_shape = B.shape[2:]
    a2 = A.reshape(L, -1, r)
    b2 = B.reshape(L, r, -1)
    return jnp.einsum("lir,lro->lio", a2, b2).reshape(
        L, *in_shape, *out_shape)


def make_sharded_lora_step(mesh, config: TransformerConfig,
                           lora: LoRAConfig, tc=None, rules=None):
    """(init_fn, step_fn) for adapter-only training over ``mesh``.

    init_fn(key) → (lora_params, opt_state): adapters and optimizer
    state shard per lora_logical_specs and are donated through the
    step; the base params ride as a non-donated step input (frozen —
    ``stop_gradient`` keeps autodiff off them entirely). Adapters stay
    f32-grade by construction (they are megabytes), so the dense step's
    ``bf16_params`` master-copy machinery does not apply here — the
    flag is rejected rather than silently ignored.
    step_fn(base, lora_params, opt_state, tokens, targets) →
    (lora_params, opt_state, loss).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.sharding import (PartitionRules, batch_sharding,
                                     param_shardings)
    from .train import (TrainConfig, apply_update, ce_chunk_for,
                        fused_loss_fn, loss_fn, make_optimizer,
                        opt_state_shardings)
    from .transformer import param_logical_specs

    tc = tc or TrainConfig()
    if tc.bf16_params:
        raise ValueError(
            "bf16_params is a dense-step lever (f32 master copies of the "
            "full weights); LoRA adapters are small enough to keep in "
            "full precision — drop the flag for the lora step")
    if mesh.shape.get("pp", 1) > 1:
        raise ValueError(
            "LoRA uses the scanned (non-pipelined) forward; a pp>1 mesh "
            "would silently waste its pipeline axis and disagree with "
            "evaluation's pipelined path — finetune on a tp/fsdp/dp mesh "
            "(adapters are small; pipeline parallelism buys nothing here)")
    rules = rules or PartitionRules()
    optimizer = make_optimizer(tc)
    base_sh = param_shardings(mesh, param_logical_specs(config), rules)
    lora_sh = param_shardings(mesh, lora_logical_specs(config, lora),
                              rules)
    replicated = NamedSharding(mesh, P())
    opt_sh = opt_state_shardings(
        optimizer,
        lambda k: init_lora_params(k, config, lora),
        lora_sh, replicated)
    batch_sh = batch_sharding(mesh)

    @partial(jax.jit, out_shardings=(lora_sh, opt_sh))
    def init_fn(key):
        lp = init_lora_params(key, config, lora)
        return lp, optimizer.init(lp)

    def _loss(lora_params, base, tokens, targets, chunk):
        merged = merge_lora(jax.lax.stop_gradient(base), lora_params,
                            lora)
        if chunk:
            return fused_loss_fn(merged, tokens, targets, config,
                                 mesh=mesh, chunk_tokens=chunk)
        return loss_fn(merged, tokens, targets, config, mesh)

    @partial(jax.jit,
             in_shardings=(base_sh, lora_sh, opt_sh, batch_sh, batch_sh),
             out_shardings=(lora_sh, opt_sh, None),
             donate_argnums=(1, 2))
    def step_fn(base, lora_params, opt_state, tokens, targets):
        chunk = ce_chunk_for(tc, tokens, config.vocab_size)
        loss, grads = jax.value_and_grad(_loss)(lora_params, base,
                                                tokens, targets, chunk)
        lora_params, opt_state = apply_update(optimizer, lora_params,
                                              opt_state, grads)
        return lora_params, opt_state, loss

    return init_fn, step_fn


def lora_num_params(config: TransformerConfig, lora: LoRAConfig) -> int:
    import math
    total = 0
    for name in lora.targets:
        in_shape, out_shape = _target_dims(config, name)
        total += config.n_layers * lora.rank * (
            math.prod(in_shape) + math.prod(out_shape))
    return total
